"""Graph learning ops (reference: python/paddle/geometric/ —
message_passing/send_recv.py send_u_recv:24/send_ue_recv:143/send_uv:291,
math.py segment_sum/mean/max/min; kernels
paddle/phi/kernels/send_u_recv_kernel.h, segment_pool_kernel.h).

TPU-native: all message passing lowers to gather + segment reduction
(jax.ops.segment_*), which XLA turns into sorted-scatter on TPU — the
reference's per-edge CUDA atomics have no TPU analog and aren't needed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor
from ..ops._registry import as_tensor, raw

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv",
]


def _num_segments(ids, given=None):
    if given is not None:
        return int(given)
    idv = np.asarray(jax.device_get(raw(as_tensor(ids))))
    return int(idv.max()) + 1 if idv.size else 0


def _segment(name, jfn):
    def op(data, segment_ids, name=None):
        n = _num_segments(segment_ids)
        return apply(lambda d, i: jfn(d, i, num_segments=n),
                     as_tensor(data), as_tensor(segment_ids), name=name)
    op.__name__ = name
    return op


segment_sum = _segment("segment_sum", jax.ops.segment_sum)
segment_max = _segment("segment_max", jax.ops.segment_max)
segment_min = _segment("segment_min", jax.ops.segment_min)


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids)

    def fn(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(i.shape, d.dtype), i,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1).reshape(
            (-1,) + (1,) * (d.ndim - 1))
    return apply(fn, as_tensor(data), as_tensor(segment_ids),
                 name="segment_mean")


_MSG = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
        "div": jnp.divide}
_RED = {"sum": jax.ops.segment_sum, "mean": None,
        "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def _reduce(msg, dst, n, reduce_op):
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msg, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(dst.shape, msg.dtype), dst,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1).reshape(
            (-1,) + (1,) * (msg.ndim - 1))
    out = _RED[reduce_op](msg, dst, num_segments=n)
    if reduce_op in ("max", "min"):
        # empty segments produce +-inf; the reference zeros them
        out = jnp.where(jnp.isfinite(out), out, 0)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges, reduce at destinations
    (reference: send_recv.py:24)."""
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(reduce_op)
    n = _num_segments(dst_index, out_size) if out_size is not None else \
        raw(as_tensor(x)).shape[0]

    def fn(xv, si, di):
        return _reduce(jnp.take(xv, si, axis=0), di, n, reduce_op)
    return apply(fn, as_tensor(x), as_tensor(src_index),
                 as_tensor(dst_index), name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine source-node features with per-edge features, reduce at
    destinations (reference: send_recv.py:143). y: (E, ...) edge feats."""
    if message_op not in _MSG:
        raise ValueError(message_op)
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(reduce_op)
    n = _num_segments(dst_index, out_size) if out_size is not None else \
        raw(as_tensor(x)).shape[0]
    mfn = _MSG[message_op]

    def fn(xv, yv, si, di):
        return _reduce(mfn(jnp.take(xv, si, axis=0), yv), di, n, reduce_op)
    return apply(fn, as_tensor(x), as_tensor(y), as_tensor(src_index),
                 as_tensor(dst_index), name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from source and destination node features
    (reference: send_recv.py:291)."""
    if message_op not in _MSG:
        raise ValueError(message_op)
    mfn = _MSG[message_op]

    def fn(xv, yv, si, di):
        return mfn(jnp.take(xv, si, axis=0), jnp.take(yv, di, axis=0))
    return apply(fn, as_tensor(x), as_tensor(y), as_tensor(src_index),
                 as_tensor(dst_index), name="send_uv")
