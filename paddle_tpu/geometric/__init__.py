"""Graph learning ops (reference: python/paddle/geometric/ —
message_passing/send_recv.py send_u_recv:24/send_ue_recv:143/send_uv:291,
math.py segment_sum/mean/max/min; kernels
paddle/phi/kernels/send_u_recv_kernel.h, segment_pool_kernel.h).

TPU-native: all message passing lowers to gather + segment reduction
(jax.ops.segment_*), which XLA turns into sorted-scatter on TPU — the
reference's per-edge CUDA atomics have no TPU analog and aren't needed.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor
from ..ops._registry import as_tensor, raw

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv",
    "weighted_sample_neighbors",
]


def _num_segments(ids, given=None):
    if given is not None:
        return int(given)
    idv = np.asarray(jax.device_get(raw(as_tensor(ids))))
    return int(idv.max()) + 1 if idv.size else 0


def _segment(name, jfn):
    def op(data, segment_ids, name=None):
        n = _num_segments(segment_ids)
        return apply(lambda d, i: jfn(d, i, num_segments=n),
                     as_tensor(data), as_tensor(segment_ids), name=name)
    op.__name__ = name
    return op


segment_sum = _segment("segment_sum", jax.ops.segment_sum)
segment_max = _segment("segment_max", jax.ops.segment_max)
segment_min = _segment("segment_min", jax.ops.segment_min)


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids)

    def fn(d, i):
        s = jax.ops.segment_sum(d, i, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(i.shape, d.dtype), i,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1).reshape(
            (-1,) + (1,) * (d.ndim - 1))
    return apply(fn, as_tensor(data), as_tensor(segment_ids),
                 name="segment_mean")


_MSG = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
        "div": jnp.divide}
_RED = {"sum": jax.ops.segment_sum, "mean": None,
        "max": jax.ops.segment_max, "min": jax.ops.segment_min}


def _reduce(msg, dst, n, reduce_op):
    if reduce_op == "mean":
        s = jax.ops.segment_sum(msg, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones(dst.shape, msg.dtype), dst,
                                  num_segments=n)
        return s / jnp.maximum(cnt, 1).reshape(
            (-1,) + (1,) * (msg.ndim - 1))
    out = _RED[reduce_op](msg, dst, num_segments=n)
    if reduce_op in ("max", "min"):
        # empty segments produce +-inf; the reference zeros them
        out = jnp.where(jnp.isfinite(out), out, 0)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather source-node features along edges, reduce at destinations
    (reference: send_recv.py:24)."""
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(reduce_op)
    n = _num_segments(dst_index, out_size) if out_size is not None else \
        raw(as_tensor(x)).shape[0]

    def fn(xv, si, di):
        return _reduce(jnp.take(xv, si, axis=0), di, n, reduce_op)
    return apply(fn, as_tensor(x), as_tensor(src_index),
                 as_tensor(dst_index), name="send_u_recv")


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine source-node features with per-edge features, reduce at
    destinations (reference: send_recv.py:143). y: (E, ...) edge feats."""
    if message_op not in _MSG:
        raise ValueError(message_op)
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(reduce_op)
    n = _num_segments(dst_index, out_size) if out_size is not None else \
        raw(as_tensor(x)).shape[0]
    mfn = _MSG[message_op]

    def fn(xv, yv, si, di):
        return _reduce(mfn(jnp.take(xv, si, axis=0), yv), di, n, reduce_op)
    return apply(fn, as_tensor(x), as_tensor(y), as_tensor(src_index),
                 as_tensor(dst_index), name="send_ue_recv")


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from source and destination node features
    (reference: send_recv.py:291)."""
    if message_op not in _MSG:
        raise ValueError(message_op)
    mfn = _MSG[message_op]

    def fn(xv, yv, si, di):
        return mfn(jnp.take(xv, si, axis=0), jnp.take(yv, di, axis=0))
    return apply(fn, as_tensor(x), as_tensor(y), as_tensor(src_index),
                 as_tensor(dst_index), name="send_uv")


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None, seed=None):
    """Weighted neighbor sampling over a CSC graph: for each input node,
    draw up to ``sample_size`` neighbors without replacement with
    probability proportional to ``edge_weight`` (A-Res reservoir keys:
    top-k of u^(1/w)); degree <= sample_size (or sample_size < 0) keeps
    every neighbor. Returns (out_neighbors, out_count[, out_eids]).

    reference: python/paddle/geometric/sampling/neighbors.py:244 +
    gpu/weighted_sample_neighbors_kernel.cu. Host-side numpy like the
    other samplers (incubate/graph.py) — sampling is data prep, not the
    jit path.
    """
    import numpy as _np_mod
    from ..incubate.graph import _np
    rown, cp = _np(row).reshape(-1), _np(colptr).reshape(-1)
    wts = _np(edge_weight).reshape(-1).astype(_np_mod.float64)
    nodes = _np(input_nodes).reshape(-1)
    eidsn = _np(eids).reshape(-1) if eids is not None else None
    if return_eids and eidsn is None:
        raise ValueError("return_eids=True requires eids")
    if seed is None:
        from .._core import random as _random
        import jax as _jax
        seed = int(_np_mod.asarray(
            _jax.random.bits(_random.next_rng_key(), dtype=_np_mod.uint32)))
    rng = _np_mod.random.default_rng(seed)
    neigh, eid_parts, counts = [], [], []
    for nd in nodes:
        lo, hi = int(cp[nd]), int(cp[nd + 1])
        deg = hi - lo
        if sample_size < 0 or deg <= sample_size:
            sel = _np_mod.arange(lo, hi)
        else:
            w = _np_mod.clip(wts[lo:hi], 1e-30, None)
            keys = rng.random(deg) ** (1.0 / w)
            sel = lo + _np_mod.argsort(-keys)[:sample_size]
        neigh.append(rown[sel])
        counts.append(len(sel))
        if eidsn is not None:
            eid_parts.append(eidsn[sel])
    from .._core.tensor import Tensor as _T
    out_n = _np_mod.concatenate(neigh) if neigh else \
        _np_mod.zeros((0,), rown.dtype)
    outs = (_T(out_n), _T(_np_mod.asarray(counts, _np_mod.int32)))
    if return_eids:
        out_e = _np_mod.concatenate(eid_parts) if eid_parts else \
            _np_mod.zeros((0,), eidsn.dtype)
        outs = outs + (_T(out_e),)
    return outs
