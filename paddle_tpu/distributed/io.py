"""paddle.distributed.io parity (reference: python/paddle/distributed/io.py
— save/load persistables for distributed (PS) programs).

On this framework persistable state is a state_dict; the distributed
variants delegate to framework.io for the dense part and to the parameter
server for sparse tables.
"""
from __future__ import annotations

import os


def is_persistable(var) -> bool:
    """reference: distributed/io.py is_persistable."""
    return bool(getattr(var, "persistable", False))


def save_persistables(executor, dirname, main_program=None, filename=None):
    """reference: distributed/io.py save_persistables — for a PS run the
    server-side tables are flushed; locally the program/layer state is
    saved through framework.io."""
    from ..framework.io import save as _save
    os.makedirs(dirname, exist_ok=True)
    state = {}
    if main_program is not None and hasattr(main_program, "state_dict"):
        state = main_program.state_dict()
    _save(state, os.path.join(dirname, filename or "persistables.pdparams"))


def load_persistables(executor, dirname, main_program=None, filename=None):
    """reference: distributed/io.py load_persistables."""
    from ..framework.io import load as _load
    path = os.path.join(dirname, filename or "persistables.pdparams")
    state = _load(path)
    if main_program is not None and hasattr(main_program, "set_state_dict"):
        main_program.set_state_dict(state)
    return state


def load_inference_model_distributed(dirname, executor):
    """reference: distributed/io.py load_inference_model_distributed."""
    from ..jit import load as _jit_load
    return _jit_load(os.path.join(dirname, "model"))
