"""Global device-mesh management: the TPU-native ProcessGroup substrate.

Re-design of the reference's communication bootstrap
(reference: python/paddle/distributed/parallel.py:978 init_parallel_env,
python/paddle/distributed/communication/collective.py:194 new_group,
paddle/phi/core/distributed/comm_context_manager.h:43). The reference
rendezvouses N processes over a TCPStore and builds NCCL communicators per
group of ranks. On TPU under JAX's single-controller SPMD model, the
equivalent structure is a ``jax.sharding.Mesh``: devices are the "ranks",
named mesh axes are the "groups", and XLA lowers collectives over ICI/DCN —
no eager communicator objects exist. A :class:`Group` here is therefore a
(mesh, axis-names) view, not a socket-holding object.

Multi-host: ``init_parallel_env`` calls ``jax.distributed.initialize`` when
coordinator env vars are present (the analog of TCPStore rendezvous —
PADDLE_MASTER/PADDLE_TRAINER_ID ≙ coordinator_address/process_id).
"""
from __future__ import annotations

import os
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

_lock = threading.Lock()
_state = {
    "initialized": False,
    "mesh": None,           # the global Mesh
    "groups": {},           # gid -> Group
    "next_gid": 1,
}


class ParallelEnv:
    """reference: python/paddle/distributed/parallel.py ParallelEnv —
    env-derived rank/world info. Under single-controller JAX, rank =
    jax.process_index (host granularity); device_id = local device."""

    @property
    def rank(self) -> int:
        return jax.process_index()

    @property
    def world_size(self) -> int:
        return jax.process_count()

    @property
    def device_id(self) -> int:
        return 0

    @property
    def nranks(self) -> int:
        return self.world_size

    @property
    def local_rank(self) -> int:
        return self.rank


class ReduceOp:
    """reference: python/paddle/distributed/communication/reduce.py ReduceOp."""
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A collective group = a set of devices with a named mesh axis.

    reference: python/paddle/distributed/communication/group.py:29 (Group).
    Unlike the reference (which owns a ProcessGroupNCCL), this is a view on
    the global mesh: ``axis_names`` identify which mesh axes the collective
    reduces over when used inside ``shard_map``; ``ranks`` list the flat
    device ids for parity with the reference API.
    """

    def __init__(self, gid: int, mesh: Mesh, axis_names: Tuple[str, ...],
                 ranks: Optional[List[int]] = None):
        self.id = gid
        self.mesh = mesh
        self.axis_names = tuple(axis_names)
        if ranks is None:
            ranks = [d.id for d in np.ravel(mesh.devices)]
        self.ranks = list(ranks)

    @property
    def nranks(self) -> int:
        n = 1
        for a in self.axis_names:
            n *= self.mesh.shape[a]
        return n

    world_size = nranks

    @property
    def rank(self) -> int:
        # Inside shard_map: position along the group axes; outside: 0 (the
        # single controller).
        try:
            idx = 0
            for a in self.axis_names:
                idx = idx * self.mesh.shape[a] + jax.lax.axis_index(a)
            return idx
        except Exception:
            return 0

    def get_group_rank(self, global_rank: int) -> int:
        return self.ranks.index(global_rank) if global_rank in self.ranks \
            else -1

    @property
    def process_ids(self):
        return self.ranks

    def __repr__(self):
        return (f"Group(id={self.id}, axes={self.axis_names}, "
                f"nranks={self.nranks})")


def _default_mesh_devices(devices=None):
    devs = list(devices) if devices is not None else list(jax.devices())
    return np.asarray(devs)


def init_parallel_env(mesh_shape: Optional[Sequence[int]] = None,
                      axis_names: Optional[Sequence[str]] = None) -> Group:
    """Bootstrap the global mesh (reference: parallel.py:978
    init_parallel_env — TCPStore rendezvous + global ProcessGroup creation).

    TPU-native: if JAX multi-host env vars are present, initialize the
    coordination service; then build the global 1-D mesh over all devices
    (axis ``"world"``) unless an explicit shape is given.
    """
    with _lock:
        if not _state["initialized"]:
            coord = os.environ.get("PADDLE_MASTER") or \
                os.environ.get("JAX_COORDINATOR_ADDRESS")
            nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
            # NOTE: the guard must not touch jax.process_count()/devices():
            # that would initialize the backend and make
            # jax.distributed.initialize a no-op/error
            if coord and nprocs > 1 and not jax.distributed.is_initialized():
                jax.distributed.initialize(
                    coordinator_address=coord,
                    num_processes=nprocs,
                    process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
            _state["initialized"] = True
        if mesh_shape is None:
            devices = _default_mesh_devices()
            mesh = Mesh(devices, ("world",))
        else:
            devices = _default_mesh_devices().reshape(tuple(mesh_shape))
            mesh = Mesh(devices, tuple(axis_names or
                                       [f"axis{i}" for i in
                                        range(len(mesh_shape))]))
        _state["mesh"] = mesh
        g = Group(0, mesh, mesh.axis_names)
        _state["groups"][0] = g
        return g


def serving_mesh(tp: int, dp: int = 1,
                 devices: Optional[Sequence] = None) -> Mesh:
    """Build the mesh the tensor-parallel serving engine shards over:
    1-D ``("tp",)`` at ``dp == 1`` (ISSUE 7, unchanged), 2-D
    ``("tp", "dp")`` when a data-parallel axis is requested (ISSUE 17)
    — the first ``tp * dp`` devices as a ``tp x dp`` grid. The serving
    stack deliberately takes a plain Mesh rather than a
    :class:`Group` — the engine's shard_map programs only need the axis
    names, and keeping it decoupled from the global-mesh singleton lets
    a server and a trainer coexist in one process.

    Use with ``ContinuousBatchingEngine(..., mesh=serving_mesh(4))``
    (or ``serving_mesh(2, 2)``); weights partition by
    :data:`paddle_tpu.models.llama.SERVING_TP_RULES` and the KV page
    pools shard on the head axis over tp (replicated across dp — same
    page ids on every dp shard), while the batch axis of the step
    programs splits over dp."""
    devs = list(devices) if devices is not None else list(jax.devices())
    if tp < 1:
        raise ValueError(f"serving_mesh: tp must be >= 1, got {tp}")
    if dp < 1:
        raise ValueError(f"serving_mesh: dp must be >= 1, got {dp}")
    if tp * dp > len(devs):
        raise ValueError(
            f"serving_mesh: tp={tp} x dp={dp} exceeds the {len(devs)} "
            f"available device(s)" if dp > 1 else
            f"serving_mesh: tp={tp} exceeds the {len(devs)} available "
            f"device(s)")
    if dp == 1:
        return Mesh(np.asarray(devs[:tp]), ("tp",))
    return Mesh(np.asarray(devs[:tp * dp]).reshape(tp, dp),
                ("tp", "dp"))


def is_initialized() -> bool:
    return _state["initialized"]


def set_mesh(mesh: Mesh) -> Group:
    """Install ``mesh`` as the global mesh (auto_parallel entry)."""
    with _lock:
        _state["initialized"] = True
        _state["mesh"] = mesh
        g = Group(0, mesh, mesh.axis_names)
        _state["groups"][0] = g
        return g


def get_mesh() -> Optional[Mesh]:
    return _state["mesh"]


def get_world_group() -> Group:
    if 0 not in _state["groups"]:
        init_parallel_env()
    return _state["groups"][0]


def new_group(ranks: Optional[List[int]] = None, *,
              axis_name: Optional[str] = None, backend=None,
              timeout=None) -> Group:
    """reference: communication/collective.py:194 new_group.

    TPU-native: a group is identified by mesh axis names. ``axis_name`` picks
    one or more axes of the global mesh; ``ranks`` is kept for API parity
    (used only to derive nranks when no axis matches — e.g. tests that pass
    explicit rank lists get a 1-axis view over those devices).
    """
    mesh = get_mesh()
    if mesh is None:
        init_parallel_env()
        mesh = get_mesh()
    with _lock:
        gid = _state["next_gid"]
        _state["next_gid"] += 1
        if axis_name is not None:
            names = (axis_name,) if isinstance(axis_name, str) \
                else tuple(axis_name)
            g = Group(gid, mesh, names)
        else:
            ranks = list(ranks) if ranks else [d.id for d in jax.devices()]
            devs = np.asarray([d for d in np.ravel(np.asarray(
                jax.devices(), dtype=object)) if d.id in set(ranks)])
            sub = Mesh(devs, (f"group{gid}",))
            g = Group(gid, sub, (f"group{gid}",), ranks)
        _state["groups"][gid] = g
        return g


def get_group(gid: int) -> Optional[Group]:
    return _state["groups"].get(gid)


def get_rank(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.rank
    try:
        return jax.process_index()
    except Exception:
        return 0


def get_world_size(group: Optional[Group] = None) -> int:
    if group is not None:
        return group.nranks
    try:
        return jax.process_count()
    except Exception:
        return 1


def in_mapped_context(group: Group) -> bool:
    """True when called under shard_map/pmap with the group's axes bound —
    the regime where lax collectives apply (vs eager global-array ops)."""
    try:
        for a in group.axis_names:
            jax.lax.axis_index(a)
        return True
    except Exception:
        return False


def barrier(group: Optional[Group] = None):
    """reference: communication/collective.py barrier — on the single
    controller this is a device sync."""
    jax.block_until_ready(jax.numpy.zeros(()))


def destroy_process_group(group: Optional[Group] = None):
    with _lock:
        if group is None:
            _state["groups"].clear()
            _state["mesh"] = None
            _state["initialized"] = False
        else:
            _state["groups"].pop(group.id, None)
