"""Functional collective API.

Re-design of the reference's eager collectives
(reference: python/paddle/distributed/communication/{all_reduce,all_gather,
reduce_scatter,all_to_all,broadcast,scatter,reduce,send,recv,
batch_isend_irecv}.py over ProcessGroupNCCL,
paddle/fluid/distributed/collective/process_group_nccl.h:37).

TPU-native semantics — two regimes, one API:

1. **Mapped regime** (inside ``jax.shard_map`` with the group's mesh axes
   bound): collectives are ``jax.lax`` primitives (psum/all_gather/
   psum_scatter/all_to_all/ppermute) compiled by XLA onto ICI. This is the
   regime every performance path uses (pipeline schedules, ring attention,
   MoE dispatch) and the regime the collective unit tests exercise — the
   analog of the reference's per-rank subprocess tests (SURVEY §4).

2. **Eager regime** (single controller, global arrays): explicit
   communication does not exist on TPU — GSPMD inserts collectives when
   computing on sharded arrays. Eager calls on DIST TENSORS (Shard /
   Partial / Replicate placements over the group axis) implement the
   reference's per-rank semantics exactly as a metadata/layout transform
   (all_reduce combines Partial pieces, all_gather flips Shard to
   Replicate, ...). Plain tensors over an nranks>1 group raise a
   descriptive error (pointing at shard_map / shard_tensor) rather than
   silently doing the wrong thing.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .._core.tensor import Tensor
from ..observability import hooks as _obs
from . import mesh as _mesh
from .mesh import Group, ReduceOp, get_world_group, in_mapped_context


def _resolve_group(group: Optional[Group]) -> Group:
    return group if group is not None else get_world_group()


def _raw(x):
    return x._value if isinstance(x, Tensor) else x


def _wrap(val, like=None):
    if isinstance(like, Tensor) or like is None:
        return Tensor(val, _internal=True)
    return val


def _axis(group: Group):
    names = group.axis_names
    return names[0] if len(names) == 1 else names


def _eager_error(opname: str, group: Group):
    raise RuntimeError(
        f"{opname}: eager collectives over a {group.nranks}-device group "
        "need a dist tensor (shard_tensor/dtensor_from_local over a mesh "
        "containing the group axis) — or run inside jax.shard_map (mapped "
        "regime) / use sharding annotations and let GSPMD insert the "
        "collective.")


def _eager_dist(tensor, g: Group):
    """Eager-regime dispatch info: (ProcessMesh, axis index, n, placements)
    when ``tensor`` is a dist tensor laid out over the (single) group axis.

    Single-controller eager collectives operate on the distribution
    METADATA: a Partial/Shard placement encodes what each group coordinate
    holds, so the reference's per-rank semantics have an exact global
    rewrite (reference eager path: process_group_nccl.cc; here it's a
    device_put/metadata transform — VERDICT round-1 weak #6)."""
    from .auto_parallel.api import is_dist_tensor
    if not (isinstance(tensor, Tensor) and is_dist_tensor(tensor)):
        return None
    if len(g.axis_names) != 1:
        return None
    ax = g.axis_names[0]
    pm = tensor._dist_mesh
    if ax not in pm.dim_names:
        return None
    axi = pm.dim_names.index(ax)
    return pm, axi, pm.shape[axi], list(tensor._dist_placements)


def _remark(t, pm, placements, val=None):
    from .auto_parallel.api import _mark, _sharding_for
    from .auto_parallel.placement import Partial, Replicate
    glob = t._value if val is None else val
    lay = [p if not isinstance(p, Partial) else Replicate()
           for p in placements]
    out = Tensor(jax.device_put(glob, _sharding_for(
        pm, lay, glob.ndim, glob.shape)), _internal=True)
    out.stop_gradient = t.stop_gradient if isinstance(t, Tensor) else True
    return _mark(out, pm, placements)


def _preduce(x, op, axis):
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        y = lax.psum(x, axis)
        if op == ReduceOp.AVG:
            y = y / lax.psum(jnp.ones((), x.dtype), axis)
        return y
    if op == ReduceOp.MAX:
        return lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return lax.pmin(x, axis)
    if op == ReduceOp.PROD:
        return jnp.prod(lax.all_gather(x, axis), axis=0)
    raise ValueError(f"unsupported ReduceOp {op}")


# ---- collectives -----------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group: Optional[Group] = None,
               sync_op: bool = True):
    """reference: communication/all_reduce.py (all_reduce)."""
    if _obs.enabled:
        _obs.collective("all_reduce", tensor)
    g = _resolve_group(group)
    x = _raw(tensor)
    if in_mapped_context(g):
        out = x
        for a in g.axis_names:
            out = _preduce(out, op, a)
        return _wrap(out, tensor)
    if g.nranks == 1:
        return tensor
    info = _eager_dist(tensor, g)
    if info is not None:
        from .auto_parallel.placement import Shard, Replicate, Partial
        pm, axi, n, plc = info
        p = plc[axi]
        plc[axi] = Replicate()
        if isinstance(p, Partial):
            if op in (ReduceOp.SUM, ReduceOp.AVG):
                # the combined (summed) value is already stored; reducing
                # just clears the Partial mark (AVG divides by group size)
                val = x / n if op == ReduceOp.AVG else x
                return _remark(tensor, pm, plc, val)
            pieces = getattr(tensor, "_partial_pieces", None)
            if pieces is None:
                _eager_error(f"all_reduce({op}) on Partial without "
                             "per-coordinate pieces", g)
            val = {ReduceOp.MAX: pieces.max(0), ReduceOp.MIN: pieces.min(0),
                   ReduceOp.PROD: pieces.prod(0)}[op]
            return _remark(tensor, pm, plc, val)
        if isinstance(p, Replicate):
            # every coordinate holds the same value: SUM -> n*x
            val = {ReduceOp.SUM: x * n, ReduceOp.AVG: x,
                   ReduceOp.MAX: x, ReduceOp.MIN: x,
                   ReduceOp.PROD: x ** n}[op]
            return _remark(tensor, pm, plc, val)
        # Shard(d): each coordinate holds a slice; result (per-rank shape
        # = slice) is the elementwise reduction over the n slices
        parts = jnp.split(x, n, axis=p.dim)
        if op in (ReduceOp.SUM, ReduceOp.AVG):
            val = sum(parts[1:], parts[0])
            val = val / n if op == ReduceOp.AVG else val
        elif op == ReduceOp.MAX:
            val = jnp.stack(parts).max(0)
        elif op == ReduceOp.MIN:
            val = jnp.stack(parts).min(0)
        else:
            val = jnp.stack(parts).prod(0)
        return _remark(tensor, pm, plc, val)
    _eager_error("all_reduce", g)


def all_gather(tensor_or_list, tensor=None, group: Optional[Group] = None,
               sync_op: bool = True, axis: int = 0):
    """reference: communication/all_gather.py — gathers and concatenates
    along dim 0. Accepts either paddle's (tensor_list, tensor) calling
    convention or the functional ``all_gather(tensor)`` form returning the
    concatenated result.
    """
    if tensor is None:
        t, out_list = tensor_or_list, None
    else:
        t, out_list = tensor, tensor_or_list
    if _obs.enabled:
        _obs.collective("all_gather", t)
    g = _resolve_group(group)
    x = _raw(t)
    if in_mapped_context(g):
        out = x
        for a in reversed(g.axis_names):
            out = lax.all_gather(out, a, axis=axis, tiled=True)
    elif g.nranks == 1:
        out = x
    else:
        info = _eager_dist(t, g)
        if info is None:
            _eager_error("all_gather", g)
        from .auto_parallel.placement import Shard, Replicate, Partial
        pm, axi, n, plc = info
        p = plc[axi]
        if isinstance(p, Partial):
            _eager_error("all_gather(Partial)", g)
        if isinstance(p, Shard):
            if p.dim == axis:
                out = x  # the global value IS the concatenation
            else:
                out = jnp.concatenate(jnp.split(x, n, axis=p.dim),
                                      axis=axis)
        else:  # Replicate: every coordinate contributes the same tensor
            out = jnp.concatenate([x] * n, axis=axis)
        if out_list is None:
            plc[axi] = Replicate()
            return _remark(t, pm, plc, out)
    if out_list is not None:
        n = g.nranks
        for i, piece in enumerate(jnp.split(out, n, axis=axis)):
            out_list.append(Tensor(piece, _internal=True))
        return None
    return _wrap(out, t)


def reduce_scatter(output, input=None, op=ReduceOp.SUM,
                   group: Optional[Group] = None, sync_op: bool = True,
                   axis: int = 0):
    """reference: communication/reduce_scatter.py — reduce then scatter
    along dim 0. Functional form: ``y = reduce_scatter(x)``."""
    if _obs.enabled:
        _obs.collective("reduce_scatter", output if input is None else input)
    if input is None:
        x_in, out_t = _raw(output), None
    else:
        x_in, out_t = _raw(input), output
    g = _resolve_group(group)
    if in_mapped_context(g):
        if op not in (ReduceOp.SUM, ReduceOp.AVG):
            raise ValueError("reduce_scatter supports SUM/AVG")
        out = x_in
        for a in g.axis_names:
            out = lax.psum_scatter(out, a, scatter_dimension=axis, tiled=True)
        if op == ReduceOp.AVG:
            out = out / g.nranks
    elif g.nranks > 1 and (
            _rs_info := _eager_dist(output if input is None else input,
                                    g)) is not None:
        from .auto_parallel.placement import Shard, Replicate, Partial
        from .auto_parallel.api import _mark
        src = output if input is None else input
        pm, axi, n, plc = _rs_info
        if op not in (ReduceOp.SUM, ReduceOp.AVG):
            raise ValueError("reduce_scatter supports SUM/AVG")
        p = plc[axi]
        if isinstance(p, Partial):
            out = x_in / n if op == ReduceOp.AVG else x_in
        elif isinstance(p, Replicate):
            out = x_in if op == ReduceOp.AVG else x_in * n
        else:
            _eager_error("reduce_scatter(Shard input)", g)
        plc[axi] = Shard(axis)
        res = _remark(src, pm, plc, out)
        if out_t is not None:
            # keep the dist metadata: _inplace_from copies value/node only
            out_t._inplace_from(res)
            _mark(out_t, pm, plc)
            return out_t
        return res
    elif g.nranks == 1:
        out = x_in
    else:
        _eager_error("reduce_scatter", g)
    if out_t is not None:
        out_t._inplace_assign(out)
        return None
    return Tensor(out, _internal=True)


def all_to_all(out_tensor_list, in_tensor_list=None,
               group: Optional[Group] = None, sync_op: bool = True):
    """reference: communication/all_to_all.py. Functional single-tensor
    form: ``y = alltoall_single(x)`` below; this list form stacks/unstacks.
    """
    if in_tensor_list is None:
        in_tensor_list, out_tensor_list = out_tensor_list, None
    if _obs.enabled:
        _obs.collective("all_to_all", in_tensor_list)
    g = _resolve_group(group)
    x = jnp.stack([_raw(t) for t in in_tensor_list], axis=0)
    if in_mapped_context(g):
        a = _axis(g)
        out = lax.all_to_all(x, a, split_axis=0, concat_axis=0, tiled=False)
    elif g.nranks == 1:
        out = x
    else:
        _eager_error("all_to_all", g)
    pieces = [Tensor(out[i], _internal=True) for i in range(out.shape[0])]
    if out_tensor_list is not None:
        out_tensor_list.extend(pieces)
        return None
    return pieces


def alltoall_single(in_tensor, out_tensor=None, in_split_sizes=None,
                    out_split_sizes=None, group: Optional[Group] = None,
                    sync_op: bool = True, axis: int = 0):
    """reference: communication/all_to_all.py alltoall_single — equal-split
    all-to-all along ``axis`` (static shapes: TPU requires equal splits).
    """
    if _obs.enabled:
        _obs.collective("all_to_all", in_tensor)
    g = _resolve_group(group)
    x = _raw(in_tensor)
    if in_mapped_context(g):
        a = _axis(g)
        out = lax.all_to_all(x, a, split_axis=axis, concat_axis=axis,
                             tiled=True)
    elif g.nranks == 1:
        out = x
    else:
        _eager_error("alltoall_single", g)
    if out_tensor is not None:
        out_tensor._inplace_assign(out)
        return None
    return _wrap(out, in_tensor)


def broadcast(tensor, src: int = 0, group: Optional[Group] = None,
              sync_op: bool = True):
    """reference: communication/broadcast.py — all ranks end with src's
    value. Mapped impl: mask + psum (one ICI reduction)."""
    if _obs.enabled:
        _obs.collective("broadcast", tensor)
    g = _resolve_group(group)
    x = _raw(tensor)
    if in_mapped_context(g):
        idx = g.rank
        masked = jnp.where(idx == src, x, jnp.zeros_like(x))
        out = masked
        for a in g.axis_names:
            out = lax.psum(out, a)
        if isinstance(tensor, Tensor):
            tensor._inplace_assign(out)
            return tensor
        return out
    if g.nranks == 1:
        return tensor
    info = _eager_dist(tensor, g)
    if info is not None:
        from .auto_parallel.placement import Shard, Replicate, Partial
        pm, axi, n, plc = info
        p = plc[axi]
        if isinstance(p, Replicate):
            return tensor  # already identical on every coordinate
        if isinstance(p, Shard):
            # each coordinate's tensor becomes src's slice
            parts = jnp.split(x, n, axis=p.dim)
            val = jnp.concatenate([parts[src]] * n, axis=p.dim)
            out = _remark(tensor, pm, plc, val)
            tensor._inplace_from(out)
            return tensor
        _eager_error("broadcast(Partial)", g)
    _eager_error("broadcast", g)


def reduce(tensor, dst: int = 0, op=ReduceOp.SUM,
           group: Optional[Group] = None, sync_op: bool = True):
    """reference: communication/reduce.py — dst rank gets the reduction,
    other ranks keep their input (the reference leaves them undefined)."""
    if _obs.enabled:
        _obs.collective("reduce", tensor)
    g = _resolve_group(group)
    x = _raw(tensor)
    if in_mapped_context(g):
        red = x
        for a in g.axis_names:
            red = _preduce(red, op, a)
        out = jnp.where(g.rank == dst, red, x)
        if isinstance(tensor, Tensor):
            tensor._inplace_assign(out)
            return tensor
        return out
    if g.nranks == 1:
        return tensor
    if _eager_dist(tensor, g) is not None:
        from .auto_parallel.api import _mark
        # single controller: every coordinate observes the reduction;
        # in-place like the mapped path
        res = all_reduce(tensor, op=op, group=g)
        tensor._inplace_from(res)
        _mark(tensor, res._dist_mesh, list(res._dist_placements))
        return tensor
    _eager_error("reduce", g)


def scatter(tensor, tensor_list=None, src: int = 0,
            group: Optional[Group] = None, sync_op: bool = True):
    """reference: communication/scatter.py — src's list is distributed; rank
    i receives tensor_list[i]."""
    if _obs.enabled:
        _obs.collective("scatter", tensor_list)
    g = _resolve_group(group)
    if in_mapped_context(g):
        a = _axis(g)
        stacked = jnp.stack([_raw(t) for t in tensor_list], axis=0)
        masked = jnp.where(g.rank == src, stacked, jnp.zeros_like(stacked))
        full = lax.psum(masked, a)
        out = full[g.rank]
        if isinstance(tensor, Tensor):
            tensor._inplace_assign(out)
            return tensor
        return out
    if g.nranks == 1:
        out = _raw(tensor_list[0])
        if isinstance(tensor, Tensor):
            tensor._inplace_assign(out)
            return tensor
        return Tensor(out, _internal=True)
    _eager_error("scatter", g)


def gather(tensor, gather_list=None, dst: int = 0,
           group: Optional[Group] = None, sync_op: bool = True):
    """reference: communication/gather.py."""
    if _obs.enabled:
        _obs.collective("gather", tensor)
    g = _resolve_group(group)
    x = _raw(tensor)
    if in_mapped_context(g):
        a = _axis(g)
        full = lax.all_gather(x, a, axis=0, tiled=False)
        if gather_list is not None:
            for i in range(g.nranks):
                gather_list.append(Tensor(full[i], _internal=True))
            return None
        return Tensor(full, _internal=True)
    if g.nranks == 1:
        if gather_list is not None:
            gather_list.append(tensor)
            return None
        return tensor
    _eager_error("gather", g)


# ---- point-to-point (ppermute-based) --------------------------------------

def ppermute(tensor, perm: Sequence, group: Optional[Group] = None):
    """TPU-native p2p primitive: pairwise send over ICI neighbours
    (reference's send/recv pairs, p2p_communication.py:573 — subsumed by
    lax.ppermute; perm is a list of (src, dst))."""
    if _obs.enabled:
        _obs.collective("ppermute", tensor)
    g = _resolve_group(group)
    x = _raw(tensor)
    if not in_mapped_context(g):
        if g.nranks == 1:
            return tensor
        _eager_error("ppermute", g)
    out = lax.ppermute(x, _axis(g), perm=list(perm))
    return _wrap(out, tensor)


def shift(tensor, offset: int = 1, group: Optional[Group] = None,
          wrap: bool = True):
    """Ring shift by ``offset`` (PP/ring-attention building block)."""
    g = _resolve_group(group)
    n = g.nranks
    if wrap:
        perm = [(i, (i + offset) % n) for i in range(n)]
    else:
        perm = [(i, i + offset) for i in range(n) if 0 <= i + offset < n]
    return ppermute(tensor, perm, g)


class P2POp:
    """reference: communication/batch_isend_irecv.py P2POp.

    SPMD divergence from the reference: the program is traced ONCE for all
    ranks, so a rank-specific destination cannot appear in the op list.
    ``peer`` is therefore a RING OFFSET from each rank (peer=+1 sends to
    rank+1, the pipeline-next pattern), not an absolute rank id. This is
    exactly the pattern the reference's pipeline scheduler uses
    (p2p_communication.py send-next/recv-prev).
    """

    def __init__(self, op, tensor, peer: int, group: Optional[Group] = None):
        self.op = op  # isend / irecv callables below
        self.tensor = tensor
        self.peer = peer
        self.group = group


def isend(tensor, dst: int, group: Optional[Group] = None):
    """``dst`` is a ring offset in the mapped regime (see P2POp)."""
    return P2POp(isend, tensor, dst, group)


def irecv(tensor, src: int, group: Optional[Group] = None):
    """``src`` is a ring offset in the mapped regime (see P2POp)."""
    return P2POp(irecv, tensor, src, group)


send = isend
recv = irecv


def batch_isend_irecv(p2p_op_list: List[P2POp]):
    """reference: communication/batch_isend_irecv.py:90 — execute a batch of
    p2p ops. TPU-native: each matched send/recv pair becomes ONE ppermute
    (a single collective-permute over ICI). Sends and recvs must come in
    matched pairs whose offsets are consistent (recv offset = -send offset,
    i.e. data received from the rank the symmetric send targets).
    """
    sends = [p for p in p2p_op_list if p.op is isend]
    recvs = [p for p in p2p_op_list if p.op is irecv]
    if not sends and not recvs:
        return []
    if _obs.enabled:
        _obs.collective("send_recv", [s.tensor for s in sends])
    if len(sends) != len(recvs):
        raise ValueError(
            f"batch_isend_irecv needs matched send/recv pairs, got "
            f"{len(sends)} sends / {len(recvs)} recvs")
    g = _resolve_group(sends[0].group if sends else recvs[0].group)
    if not in_mapped_context(g):
        if g.nranks == 1:
            return []
        _eager_error("batch_isend_irecv", g)
    a = _axis(g)
    n = g.nranks
    results = []
    for s, r in zip(sends, recvs):
        if (r.peer + s.peer) % n != 0:
            raise ValueError(
                f"send offset {s.peer} and recv offset {r.peer} do not "
                "describe the same ring rotation (need recv = -send mod "
                "group size)")
        perm = [(i, (i + s.peer) % n) for i in range(n)]
        out = lax.ppermute(_raw(s.tensor), a, perm=perm)
        if isinstance(r.tensor, Tensor):
            r.tensor._inplace_assign(out)
        results.append(Tensor(out, _internal=True))
    return results


def barrier(group: Optional[Group] = None):
    if _obs.enabled:
        _obs.collective("barrier", ())
    g = _resolve_group(group)
    if in_mapped_context(g):
        return lax.psum(jnp.zeros(()), _axis(g))
    return _mesh.barrier(g)
