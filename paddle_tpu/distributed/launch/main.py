"""Distributed launch CLI (reference: python/paddle/distributed/launch/
main.py:23 launch; controllers/controller.py:35 ControllerBase, :79 run,
:87 watch).

TPU-native: under JAX's single-controller model one process drives all
local chips, so the per-GPU-process fan-out of the reference becomes
per-HOST processes. The launcher:

- resolves rank/world from args or env (PADDLE_TRAINER_ID /
  PADDLE_TRAINERS_NUM / PADDLE_MASTER ≙ process_id / num_processes /
  coordinator_address),
- exports the env the framework's init_parallel_env consumes,
- for local debugging (``--nproc_per_node N``) spawns N processes with a
  forced CPU mesh so no-cluster multi-rank tests run anywhere (SURVEY §4
  pattern 1),
- watches children, restarts on elastic exit code 101
  (reference: fleet/elastic/manager.py:33 ELASTIC_EXIT_CODE).

Usage: python -m paddle_tpu.distributed.launch [--nproc_per_node N]
[--master host:port] [--rank R] [--nnodes M] script.py [args...]
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

ELASTIC_EXIT_CODE = 101


def _parse(argv):
    p = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"),
                   help="coordinator host:port")
    p.add_argument("--rank", type=int,
                   default=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    p.add_argument("--nnodes", type=int,
                   default=int(os.environ.get("PADDLE_TRAINERS_NUM", "1")))
    p.add_argument("--nproc_per_node", type=int, default=1,
                   help="local debug fan-out on a CPU mesh")
    p.add_argument("--devices", default=None,
                   help="accepted for reference-CLI parity")
    p.add_argument("--job_id", default="default")
    p.add_argument("--log_dir", default="log")
    p.add_argument("--elastic_registry",
                   default=os.environ.get("PADDLE_ELASTIC_REGISTRY"),
                   help="shared-FS dir for the elastic rank registry")
    p.add_argument("--max_restarts", type=int,
                   default=int(os.environ.get("PADDLE_ELASTIC_MAX_RESTARTS",
                                              "3")))
    p.add_argument("training_script")
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


class Context:
    def __init__(self, args):
        self.args = args


class ControllerBase:
    """reference: launch/controllers/controller.py:35."""

    def __init__(self, ctx: Context):
        self.ctx = ctx
        self.procs: List[subprocess.Popen] = []

    def build_env(self, local_rank: int) -> dict:
        a = self.ctx.args
        env = dict(os.environ)
        nprocs = a.nnodes * a.nproc_per_node
        rank = a.rank * a.nproc_per_node + local_rank
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(nprocs),
            "PADDLE_JOB_ID": a.job_id,
        })
        if a.elastic_registry:
            # trainers' ElasticManager defaults must hit the same
            # registry the controller reads scale events from
            env["PADDLE_ELASTIC_REGISTRY"] = a.elastic_registry
        env.update(getattr(self, "_scale_env", {}))
        if a.master:
            env["PADDLE_MASTER"] = a.master
            env["JAX_COORDINATOR_ADDRESS"] = a.master
        if a.nproc_per_node > 1:
            # local debug fan-out: no chip sharing — force CPU mesh
            env["JAX_PLATFORMS"] = "cpu"
            env.setdefault("XLA_FLAGS", "")
            env["XLA_FLAGS"] += " --xla_force_host_platform_device_count=1"
        return env

    def spawn(self):
        a = self.ctx.args
        os.makedirs(a.log_dir, exist_ok=True)
        for i in range(a.nproc_per_node):
            env = self.build_env(i)
            log = open(os.path.join(
                a.log_dir, f"workerlog.{env['PADDLE_TRAINER_ID']}"), "ab")
            cmd = [sys.executable, a.training_script,
                   *a.training_script_args]
            self.procs.append(subprocess.Popen(cmd, env=env, stdout=log,
                                               stderr=subprocess.STDOUT))

    def watch(self) -> int:
        """reference: controller.py:87 — poll children; first failure kills
        the pod; exit 101 requests elastic relaunch."""
        while True:
            alive = False
            for p in self.procs:
                ret = p.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    self.stop()
                    return ret
            if not alive:
                return 0
            time.sleep(0.2)

    def stop(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        t0 = time.time()
        for p in self.procs:
            while p.poll() is None and time.time() - t0 < 10:
                time.sleep(0.1)
            if p.poll() is None:
                p.kill()
        self.procs.clear()

    def _apply_scale_event(self) -> Optional[int]:
        """Pick up an N→M world change recorded by a training rank
        (ElasticManager.write_scale_event) before the relaunch
        (reference: the etcd-driven re-form in
        fleet/elastic/manager.py:125).

        Local fan-out (nnodes<=1): resize nproc_per_node; the event is
        consumed (clear=True — one controller owns it). Multi-host with
        one rank per host: every host's controller reads the SAME event
        (no clear), survivors renumber contiguously by their position
        in the survivor list, losers retire (self._retire). Multi-host
        with nproc_per_node>1 is not re-formable from per-host
        controllers and is left unchanged with a warning."""
        import warnings
        from ..fleet.elastic.manager import ElasticManager
        a = self.ctx.args
        mgr = ElasticManager(job_id=a.job_id,
                             registry_dir=a.elastic_registry or None,
                             np=a.nnodes * a.nproc_per_node)
        local = a.nnodes <= 1
        ev = mgr.read_scale_event(clear=local)
        if ev is None or not ev.get("np"):
            return None
        # one application per event: in multi-host mode the file is left
        # for sibling controllers, so a LATER unrelated 101 exit must not
        # re-apply the same generation's renumbering (double-retire /
        # rank collision)
        if ev.get("ts") is not None and \
                ev["ts"] == getattr(self, "_applied_scale_ts", None):
            return None
        self._applied_scale_ts = ev.get("ts")
        new = int(ev["np"])
        survivors = ev.get("survivors")
        if survivors is not None:
            # resuming apps can adopt the freshest surviving rank's
            # checkpoint (rank-private checkpoint dirs)
            self._scale_env = {"PADDLE_ELASTIC_PREV_SURVIVORS":
                               ",".join(str(r) for r in survivors)}
        if local:
            a.nproc_per_node = new
            return new
        if a.nproc_per_node != 1:
            warnings.warn(
                "elastic scale event ignored: multi-host re-form needs "
                "one rank per host (nproc_per_node=1)")
            return None
        if survivors is not None:
            if a.rank in survivors:
                a.rank = survivors.index(a.rank)   # contiguous renumber
            else:
                self._retire = True
        elif a.rank >= new:
            self._retire = True
        a.nnodes = new
        return new

    def run(self) -> int:
        restarts = 0
        self._retire = False
        while True:
            self.spawn()
            ret = self.watch()
            if ret == ELASTIC_EXIT_CODE and \
                    restarts < self.ctx.args.max_restarts:
                restarts += 1
                self._apply_scale_event()
                if self._retire:
                    return 0   # this host is outside the new world
                continue
            return ret


def launch(argv: Optional[list] = None):
    """reference: launch/main.py:23."""
    args = _parse(argv if argv is not None else sys.argv[1:])
    ctl = ControllerBase(Context(args))
    code = ctl.run()
    if code != 0:
        sys.exit(code)


if __name__ == "__main__":
    launch()
