from .api import save_state_dict, load_state_dict  # noqa: F401
