"""Distributed checkpoint: sharded save/load with reshard-on-load.

Re-design of the reference's distributed checkpoint
(reference: python/paddle/distributed/checkpoint/save_state_dict.py:145
(dedup of replicated shards :117, async queue :46), load_state_dict.py
(ReadItem:41 — cross-mesh re-slicing), metadata.py).

TPU-native format: one directory per checkpoint
  metadata.json           — per-tensor: shape, dtype, chunk grid, crc32s
  <name>.<chunk>.bin      — raw row-major chunk bytes (written/read by the
                            native parallel IO when available:
                            _native/ckptio.cpp ≙ the reference's
                            save_combine kernels + async_load.cc threads;
                            numpy tofile/fromfile fallback). Legacy .npy
                            chunks still load.

Save writes each tensor as a grid of chunk files following its CURRENT
sharding (one file per distinct shard — replicas deduplicated exactly like
the reference's :117, because the single controller enumerates unique
shards once). Load reassembles requested slices from whatever chunk grid is
on disk and lays them out per the TARGET mesh/placements — the reference's
reshard-on-load without point-to-point fetches (files are the transport).
Async save offloads file writing to a background thread (reference :46).
"""
from __future__ import annotations

import ctypes
import json
import os
import threading
import zlib
from typing import Dict, Optional

import numpy as np
import jax

from ..._core.tensor import Tensor
from ..auto_parallel.api import (is_dist_tensor, reshard as _reshard,
                                 _normalize_placements)
from ..auto_parallel.placement import Shard, Replicate, Partial
from ..auto_parallel.process_mesh import ProcessMesh

_async_jobs = []
_IO_THREADS = min(8, os.cpu_count() or 1)


def _crc(arr: np.ndarray) -> int:
    return zlib.crc32(memoryview(np.ascontiguousarray(arr)).cast("B"))


def _write_chunk(fpath: str, arr: np.ndarray) -> int:
    """Raw chunk write via the native parallel writer (large chunks) or
    numpy; returns the crc32 recorded in metadata."""
    data = np.ascontiguousarray(arr)
    crc = _crc(data)
    from ... import _native
    lib = _native.load()
    if lib is not None and data.nbytes >= (1 << 20):
        rc = lib.pt_file_write(fpath.encode(),
                               data.ctypes.data_as(ctypes.c_void_p),
                               data.nbytes, _IO_THREADS)
        if rc == data.nbytes:
            return crc
    data.tofile(fpath)
    return crc


def _read_chunk(fpath: str, shape, dtype) -> np.ndarray:
    if not os.path.exists(fpath):
        # legacy .npy checkpoints (pre-.bin format) — only when no .bin
        # exists, so a fresh save into an old directory wins
        legacy = fpath[:-4] + ".npy"
        if os.path.exists(legacy):
            return np.load(legacy)
    out = np.empty(shape, dtype=np.dtype(dtype))
    from ... import _native
    lib = _native.load()
    if lib is not None and out.nbytes >= (1 << 20):
        rc = lib.pt_file_read(fpath.encode(),
                              out.ctypes.data_as(ctypes.c_void_p),
                              out.nbytes, _IO_THREADS)
        if rc == out.nbytes:
            return out
        raise IOError(f"native read of {fpath} failed (rc={rc})")
    got = np.fromfile(fpath, dtype=np.dtype(dtype))
    if got.size != out.size:
        raise IOError(f"chunk {fpath} has {got.size} elems, "
                      f"expected {out.size}")
    return got.reshape(shape)


def _chunk_grid(shape, placements, mesh_shape):
    """Chunk counts per tensor dim implied by Shard placements. A dim is
    only chunked when evenly divisible — matching the layout degrade in
    auto_parallel.api._placements_to_spec, so chunk files always tile the
    full array exactly."""
    grid = [1] * len(shape)
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            n = mesh_shape[mesh_dim]
            if shape[p.dim] % (grid[p.dim] * n) == 0:
                grid[p.dim] *= n
    return grid


def save_state_dict(state_dict: Dict[str, Tensor], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False):
    """reference: checkpoint/save_state_dict.py:145."""
    os.makedirs(path, exist_ok=True)
    meta = {"state": {}}
    jobs = []
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            t = Tensor(t)
        arr = np.asarray(jax.device_get(t._value))
        if is_dist_tensor(t):
            placements = list(t._dist_placements)
            mesh_shape = list(t._dist_mesh.shape)
        else:
            placements, mesh_shape = [], []
        grid = _chunk_grid(arr.shape, placements, mesh_shape)
        entry = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "grid": grid,
            "crc": {},
        }
        meta["state"][name] = entry
        # write unique chunks (dedup: replicated axes write once)
        idx_iter = np.ndindex(*grid)
        for idx in idx_iter:
            sl = tuple(
                slice(i * (s // g), (i + 1) * (s // g))
                for i, s, g in zip(idx, arr.shape, grid))
            key = "_".join(map(str, idx))
            fname = name.replace("/", "_") + "." + key + ".bin"
            jobs.append((os.path.join(path, fname),
                         arr[sl] if arr.ndim else arr, entry, key))

    def write_all():
        for fpath, chunk, entry, key in jobs:
            entry["crc"][key] = _write_chunk(fpath, chunk)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)

    if async_save:
        th = threading.Thread(target=write_all, daemon=True)
        th.start()
        _async_jobs.append(th)
    else:
        write_all()


def wait_async_save():
    for th in _async_jobs:
        th.join()
    _async_jobs.clear()


def load_state_dict(state_dict: Dict[str, Tensor], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, offload: bool = False):
    """reference: checkpoint/load_state_dict.py — fill ``state_dict``'s
    tensors in place, re-slicing chunks to each target's mesh/placements."""
    for th in list(_async_jobs):
        th.join()
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)["state"]
    for name, t in state_dict.items():
        if name not in meta:
            raise KeyError(f"{name} not in checkpoint {path}")
        m = meta[name]
        grid = m["grid"]
        cshape = tuple(s // g for s, g in zip(m["shape"], grid))
        parts = {}
        for idx in np.ndindex(*grid):
            key = "_".join(map(str, idx))
            fname = name.replace("/", "_") + "." + key + ".bin"
            chunk = _read_chunk(os.path.join(path, fname), cshape,
                                m["dtype"])
            want = m.get("crc", {}).get(key)
            if want is not None and _crc(chunk) != want:
                raise IOError(
                    f"checkpoint corruption: crc mismatch for {name} "
                    f"chunk {key} in {path}")
            parts[idx] = chunk
        # assemble global array from the chunk grid
        arr = _assemble(parts, grid, tuple(m["shape"]), m["dtype"])
        if isinstance(t, Tensor):
            if is_dist_tensor(t):
                mesh, placements = t._dist_mesh, list(t._dist_placements)
                lay = Tensor(arr)
                new = _reshard(lay, mesh, placements)
                t._inplace_assign(new._value)
            else:
                t._inplace_assign(jax.numpy.asarray(arr).astype(t.dtype))
        else:
            state_dict[name] = Tensor(arr)
    return state_dict


def _assemble(parts, grid, shape, dtype):
    if not shape:
        return parts[()]
    arr = np.empty(shape, dtype=np.dtype(dtype))
    for idx, chunk in parts.items():
        sl = tuple(slice(i * (s // g), (i + 1) * (s // g))
                   for i, s, g in zip(idx, shape, grid))
        arr[sl] = chunk
    return arr
