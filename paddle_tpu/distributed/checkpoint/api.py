"""Distributed checkpoint: sharded save/load with reshard-on-load.

Re-design of the reference's distributed checkpoint
(reference: python/paddle/distributed/checkpoint/save_state_dict.py:145
(dedup of replicated shards :117, async queue :46), load_state_dict.py
(ReadItem:41 — cross-mesh re-slicing), metadata.py).

TPU-native format: one directory per checkpoint
  metadata.json           — per-tensor: shape, dtype, chunk grid, placements
  <name>.<chunk>.npy      — row-major chunk files

Save writes each tensor as a grid of chunk files following its CURRENT
sharding (one file per distinct shard — replicas deduplicated exactly like
the reference's :117, because the single controller enumerates unique
shards once). Load reassembles requested slices from whatever chunk grid is
on disk and lays them out per the TARGET mesh/placements — the reference's
reshard-on-load without point-to-point fetches (files are the transport).
Async save offloads file writing to a background thread (reference :46).
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, Optional

import numpy as np
import jax

from ..._core.tensor import Tensor
from ..auto_parallel.api import (is_dist_tensor, reshard as _reshard,
                                 _normalize_placements)
from ..auto_parallel.placement import Shard, Replicate, Partial
from ..auto_parallel.process_mesh import ProcessMesh

_async_jobs = []


def _chunk_grid(shape, placements, mesh_shape):
    """Chunk counts per tensor dim implied by Shard placements. A dim is
    only chunked when evenly divisible — matching the layout degrade in
    auto_parallel.api._placements_to_spec, so chunk files always tile the
    full array exactly."""
    grid = [1] * len(shape)
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            n = mesh_shape[mesh_dim]
            if shape[p.dim] % (grid[p.dim] * n) == 0:
                grid[p.dim] *= n
    return grid


def save_state_dict(state_dict: Dict[str, Tensor], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False):
    """reference: checkpoint/save_state_dict.py:145."""
    os.makedirs(path, exist_ok=True)
    meta = {"state": {}}
    jobs = []
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            t = Tensor(t)
        arr = np.asarray(jax.device_get(t._value))
        if is_dist_tensor(t):
            placements = list(t._dist_placements)
            mesh_shape = list(t._dist_mesh.shape)
        else:
            placements, mesh_shape = [], []
        grid = _chunk_grid(arr.shape, placements, mesh_shape)
        meta["state"][name] = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "grid": grid,
        }
        # write unique chunks (dedup: replicated axes write once)
        idx_iter = np.ndindex(*grid)
        for idx in idx_iter:
            sl = tuple(
                slice(i * (s // g), (i + 1) * (s // g))
                for i, s, g in zip(idx, arr.shape, grid))
            fname = name.replace("/", "_") + "." + \
                "_".join(map(str, idx)) + ".npy"
            jobs.append((os.path.join(path, fname),
                         arr[sl] if arr.ndim else arr))

    def write_all():
        for fpath, chunk in jobs:
            np.save(fpath, chunk)
        with open(os.path.join(path, "metadata.json"), "w") as f:
            json.dump(meta, f)

    if async_save:
        th = threading.Thread(target=write_all, daemon=True)
        th.start()
        _async_jobs.append(th)
    else:
        write_all()


def wait_async_save():
    for th in _async_jobs:
        th.join()
    _async_jobs.clear()


def load_state_dict(state_dict: Dict[str, Tensor], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, offload: bool = False):
    """reference: checkpoint/load_state_dict.py — fill ``state_dict``'s
    tensors in place, re-slicing chunks to each target's mesh/placements."""
    for th in list(_async_jobs):
        th.join()
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)["state"]
    for name, t in state_dict.items():
        if name not in meta:
            raise KeyError(f"{name} not in checkpoint {path}")
        m = meta[name]
        grid = m["grid"]
        parts = {}
        for idx in np.ndindex(*grid):
            fname = name.replace("/", "_") + "." + \
                "_".join(map(str, idx)) + ".npy"
            parts[idx] = np.load(os.path.join(path, fname))
        # assemble global array from the chunk grid
        arr = _assemble(parts, grid, tuple(m["shape"]), m["dtype"])
        if isinstance(t, Tensor):
            if is_dist_tensor(t):
                mesh, placements = t._dist_mesh, list(t._dist_placements)
                lay = Tensor(arr)
                new = _reshard(lay, mesh, placements)
                t._inplace_assign(new._value)
            else:
                t._inplace_assign(jax.numpy.asarray(arr).astype(t.dtype))
        else:
            state_dict[name] = Tensor(arr)
    return state_dict


def _assemble(parts, grid, shape, dtype):
    if not shape:
        return parts[()]
    arr = np.empty(shape, dtype=np.dtype(dtype))
    for idx, chunk in parts.items():
        sl = tuple(slice(i * (s // g), (i + 1) * (s // g))
                   for i, s, g in zip(idx, shape, grid))
        arr[sl] = chunk
    return arr
