"""TCPStore — rendezvous / KV coordination (reference:
paddle/phi/core/distributed/store/tcp_store.h:121 TCPStore, store.h:24
Store; python surface python/paddle/distributed/communication/...).

Native C++ server/client (paddle_tpu/_native/store.cpp) via ctypes; a
pure-Python socket fallback keeps the API alive without a toolchain. API
parity: set/get/wait/add + barrier helper.
"""
from __future__ import annotations

import ctypes
import os
import socket
import struct
import threading
import time
from typing import Optional

from .. import _native

_OP_SET, _OP_GET, _OP_WAIT, _OP_ADD, _OP_PING = 0, 1, 2, 3, 4


class Store:
    """reference: store/store.h:24 — abstract base."""

    def set(self, key: str, value: bytes):
        raise NotImplementedError

    def get(self, key: str) -> bytes:
        raise NotImplementedError

    def wait(self, key: str) -> bytes:
        raise NotImplementedError

    def add(self, key: str, amount: int) -> int:
        raise NotImplementedError


class TCPStore(Store):
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 is_master: bool = False, world_size: int = 1,
                 timeout: float = 30.0):
        self._lib = _native.load()
        self._server = None
        self._py_server = None
        self.world_size = world_size
        if is_master:
            port = port or _free_port()
            if self._lib is not None:
                self._server = self._lib.pt_store_server_start(port)
                if not self._server:
                    raise RuntimeError(f"TCPStore bind failed on {port}")
            else:
                self._py_server = _PyServer(port)
        self.host, self.port = host, port
        deadline = int(timeout * 1000)
        if self._lib is not None:
            self._fd = self._lib.pt_store_client_connect(
                host.encode(), port, deadline)
            if self._fd < 0:
                raise RuntimeError(f"TCPStore connect to {host}:{port} "
                                   f"failed")
            self._sock = None
        else:
            self._fd = -1
            self._sock = _py_connect(host, port, timeout)
        self._io_lock = threading.Lock()

    # ---- protocol ----
    def _request(self, op: int, key: str, val: bytes = b"") -> bytes:
        with self._io_lock:
            if self._lib is not None:
                out = ctypes.c_char_p()
                out_len = ctypes.c_int()
                rc = self._lib.pt_store_request(
                    self._fd, op, key.encode(), len(key.encode()), val,
                    len(val), ctypes.byref(out), ctypes.byref(out_len))
                if rc != 0:
                    raise RuntimeError("TCPStore io error")
                data = ctypes.string_at(out, out_len.value)
                self._lib.pt_store_free(out)
                return data
            return _py_request(self._sock, op, key, val)

    def set(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        self._request(_OP_SET, key, bytes(value))

    def get(self, key: str) -> bytes:
        return self._request(_OP_GET, key)

    def wait(self, key: str) -> bytes:
        return self._request(_OP_WAIT, key)

    def add(self, key: str, amount: int) -> int:
        out = self._request(_OP_ADD, key, struct.pack("<q", amount))
        return struct.unpack("<q", out)[0]

    def ping(self) -> bool:
        return self._request(_OP_PING, "") == b"pong"

    def barrier(self, name: str = "barrier", timeout: float = 60.0):
        """All world_size participants block until everyone arrived."""
        n = self.add(f"__b_{name}", 1)
        if n == self.world_size:
            self.set(f"__b_{name}_done", b"1")
        else:
            t0 = time.time()
            while time.time() - t0 < timeout:
                if self.get(f"__b_{name}_done") == b"1":
                    return
                time.sleep(0.01)
            raise TimeoutError(f"barrier {name}")

    def __del__(self):
        try:
            if self._lib is not None:
                if self._fd >= 0:
                    self._lib.pt_store_client_close(self._fd)
                if self._server:
                    self._lib.pt_store_server_stop(self._server)
            elif self._sock is not None:
                self._sock.close()
        except Exception:
            pass


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


# ---- pure-python fallback (no g++) ----
def _py_connect(host, port, timeout):
    deadline = time.time() + timeout
    while True:
        try:
            s = socket.create_connection((host, port), timeout=5)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return s
        except OSError:
            if time.time() > deadline:
                raise
            time.sleep(0.1)


def _py_request(sock, op, key, val):
    k = key.encode()
    sock.sendall(struct.pack("<BI", op, len(k)) + k +
                 struct.pack("<I", len(val)) + val)
    ln = struct.unpack("<I", _recv_exact(sock, 4))[0]
    return _recv_exact(sock, ln)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("store closed")
        buf += chunk
    return buf


class _PyServer:
    """Python fallback server speaking the same wire protocol."""

    def __init__(self, port):
        self.data = {}
        self.cv = threading.Condition()
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("0.0.0.0", port))
        self.sock.listen(128)
        self.running = True
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while self.running:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                op, klen = struct.unpack("<BI", _recv_exact(conn, 5))
                key = _recv_exact(conn, klen).decode()
                vlen = struct.unpack("<I", _recv_exact(conn, 4))[0]
                val = _recv_exact(conn, vlen)
                if op == _OP_SET:
                    with self.cv:
                        self.data[key] = val
                        self.cv.notify_all()
                    out = b""
                elif op == _OP_GET:
                    out = self.data.get(key, b"")
                elif op == _OP_WAIT:
                    with self.cv:
                        self.cv.wait_for(lambda: key in self.data)
                        out = self.data[key]
                elif op == _OP_ADD:
                    delta = struct.unpack("<q", val.ljust(8, b"\0"))[0]
                    with self.cv:
                        cur = struct.unpack(
                            "<q", self.data.get(key, b"\0" * 8))[0] + delta
                        self.data[key] = struct.pack("<q", cur)
                        self.cv.notify_all()
                    out = struct.pack("<q", cur)
                elif op == _OP_PING:
                    out = b"pong"
                else:
                    return
                conn.sendall(struct.pack("<I", len(out)) + out)
        except (ConnectionError, struct.error, OSError):
            pass
        finally:
            conn.close()
