"""Remaining public ``paddle.distributed`` surface.

Reference: python/paddle/distributed/__init__.py — object collectives
(communication/all_gather.py all_gather_object, broadcast.py
broadcast_object_list, scatter.py scatter_object_list), backend/introspection
helpers, ``dtensor_from_fn`` / sharding-stage markers (auto_parallel/api.py),
``shard_dataloader`` (auto_parallel/api.py:2467), ``shard_scaler``,
``split`` (fleet/layers/mpu/mp_ops.py:714), PS table entries
(distributed/entry_attr.py).

Single-controller TPU semantics: Python objects live once per PROCESS.
Within one controller every "rank" sees the same object, so the object
collectives are identity there; across real processes (multi-host) they
exchange pickled bytes through the TCP store.
"""
from __future__ import annotations

import pickle
from typing import Any, List, Optional, Sequence

import jax
import numpy as np

from .._core.tensor import Tensor
from . import mesh as _mesh
from .mesh import Group, get_world_group

__all__ = [
    "get_backend", "is_available", "wait", "ReduceType", "ParallelMode",
    "all_gather_object", "broadcast_object_list", "scatter_object_list",
    "dtensor_from_fn", "ShardingStage1", "ShardingStage2", "ShardingStage3",
    "DistAttr", "shard_dataloader", "shard_scaler", "split",
    "reset_split_layer_cache",
    "CountFilterEntry", "ProbabilityEntry", "ShowClickEntry",
]


def get_backend(group: Optional[Group] = None) -> str:
    """reference: communication/group.py get_backend — the collective
    backend name. Here always XLA collectives over ICI/DCN."""
    return "xla"


def is_available() -> bool:
    """reference: distributed/__init__.py is_available."""
    return True


def wait(tensor, group: Optional[Group] = None, use_calc_stream=True):
    """reference: communication/wait.py — block until pending collective
    work on ``tensor`` is done (XLA: block_until_ready)."""
    v = tensor._value if isinstance(tensor, Tensor) else tensor
    try:
        v.block_until_ready()
    except AttributeError:
        pass
    return tensor


class ReduceType:
    """reference: base/core ReduceType (dist-tensor Partial reduce kind)."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class ParallelMode:
    """reference: distributed/parallel.py ParallelMode."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


# ---------------- object collectives ----------------
def _store():
    from . import parallel as _par
    return getattr(_par, "_object_store", None)


def _nproc() -> int:
    try:
        return jax.process_count()
    except Exception:
        return 1


def _exchange_object(obj) -> List[Any]:
    """All-gather an arbitrary picklable object across PROCESSES (the
    multi-host path of the object collectives): pickle -> uint8 array ->
    length-padded process_allgather over DCN."""
    from jax.experimental import multihost_utils as mhu
    data = np.frombuffer(pickle.dumps(obj), np.uint8)
    lens = np.asarray(mhu.process_allgather(
        np.asarray([data.size], np.int64))).reshape(-1)
    maxlen = int(lens.max())
    padded = np.zeros(maxlen, np.uint8)
    padded[:data.size] = data
    gathered = np.asarray(mhu.process_allgather(padded)).reshape(
        len(lens), maxlen)
    return [pickle.loads(gathered[i, :int(lens[i])].tobytes())
            for i in range(len(lens))]


def all_gather_object(object_list: List[Any], obj: Any,
                      group: Optional[Group] = None):
    """reference: communication/all_gather.py all_gather_object."""
    g = group or get_world_group()
    n = g.nranks if g is not None else 1
    if _nproc() <= 1:
        # single controller: every rank's python object IS this object
        object_list.extend([obj] * max(1, n))
        return
    object_list.extend(_exchange_object(obj))


def broadcast_object_list(object_list: List[Any], src: int = 0,
                          group: Optional[Group] = None):
    """reference: communication/broadcast.py broadcast_object_list."""
    if _nproc() <= 1:
        return  # single controller: src's list already is everyone's list
    gathered = _exchange_object(list(object_list))
    object_list[:] = gathered[src]


def scatter_object_list(out_object_list: List[Any],
                        in_object_list: Optional[List[Any]] = None,
                        src: int = 0, group: Optional[Group] = None):
    """reference: communication/scatter.py scatter_object_list."""
    g = group or get_world_group()
    n = g.nranks if g is not None else 1
    if _nproc() <= 1:
        if in_object_list is None:
            raise ValueError("src rank needs in_object_list")
        if len(in_object_list) != n:
            raise ValueError(
                f"in_object_list has {len(in_object_list)} entries for "
                f"{n} ranks")
        # single controller: "this rank" is rank 0's view
        out_object_list.append(in_object_list[0])
        return
    rank = jax.process_index()
    gathered = _exchange_object(in_object_list)
    out_object_list.append(gathered[src][rank])


# ---------------- semi-auto helpers ----------------
def dtensor_from_fn(fn, mesh, placements, *args, **kwargs):
    """reference: auto_parallel/api.py dtensor_from_fn — build a tensor
    with a factory then shard it."""
    from .auto_parallel.api import shard_tensor
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


class _ShardingStage:
    stage = 0

    def __init__(self, mesh_dim: Optional[str] = None, mesh=None):
        self.mesh_dim = mesh_dim
        self.mesh = mesh

    def __repr__(self):
        return f"{type(self).__name__}(mesh_dim={self.mesh_dim!r})"


class ShardingStage1(_ShardingStage):
    """reference: auto_parallel/strategy.py ShardingStage1 marker (ZeRO-1:
    optimizer states sharded over the data axis)."""
    stage = 1


class ShardingStage2(_ShardingStage):
    stage = 2


class ShardingStage3(_ShardingStage):
    stage = 3


class DistAttr:
    """reference: base DistAttr (legacy semi-auto attr: process_mesh +
    per-dim sharding specs). Kept for construction parity; the modern
    Placements path is paddle_tpu.distributed.shard_tensor."""

    def __init__(self, mesh=None, sharding_specs: Optional[Sequence] = None):
        self.process_mesh = mesh
        self.sharding_specs = list(sharding_specs or [])

    def __repr__(self):
        return (f"DistAttr(mesh={self.process_mesh}, "
                f"specs={self.sharding_specs})")


def shard_dataloader(dataloader, meshes=None, shard_dims=None,
                     input_keys=None, is_dataset_splitted=False):
    """reference: auto_parallel/api.py shard_dataloader:2467 — wrap a
    DataLoader so each batch lands dp-sharded on the mesh.

    TPU-native: batches become global arrays sharded over the given mesh
    dim (default: the current mesh's first axis); with
    ``is_dataset_splitted`` the loader's batches are treated as this
    process's local shard (multi-host)."""
    from .auto_parallel.api import shard_tensor
    from .auto_parallel.placement import Shard, Replicate

    mesh = meshes if meshes is not None else _mesh.get_mesh()
    if isinstance(mesh, (list, tuple)):
        mesh = mesh[0]
    dim = shard_dims if isinstance(shard_dims, str) else None

    class _ShardedLoader:
        def __init__(self, inner):
            self._inner = inner

        def __len__(self):
            return len(self._inner)

        def _place(self, t):
            if not isinstance(t, Tensor) or mesh is None or t.ndim == 0:
                return t
            names = getattr(mesh, "dim_names", None) or \
                list(getattr(mesh, "axis_names", []))
            ax = dim or (names[0] if names else None)
            if ax is None:
                return t
            pl = [Shard(0)] + [Replicate()] * (len(names) - 1) \
                if names and names[0] == ax else \
                [Shard(0) if n == ax else Replicate() for n in names]
            try:
                return shard_tensor(t, mesh, pl)
            except Exception:
                return t

        def __iter__(self):
            for batch in self._inner:
                if isinstance(batch, (list, tuple)):
                    yield type(batch)(self._place(b) for b in batch)
                elif isinstance(batch, dict):
                    yield {k: self._place(v) for k, v in batch.items()}
                else:
                    yield self._place(batch)

    return _ShardedLoader(dataloader)


def shard_scaler(scaler):
    """reference: auto_parallel/api.py shard_scaler — adapt a GradScaler
    for dist tensors. The TPU GradScaler's found-inf reduction already
    runs on global arrays (XLA inserts the cross-device reduce), so the
    scaler is returned as-is."""
    return scaler


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference: fleet/layers/mpu/mp_ops.py split:714 — one-call
    model-parallel embedding/linear over the mp group. Delegates to
    mpu.mp_ops.split. ``name=`` is REQUIRED for create-once parameter
    reuse: only named calls hit the per-(name, config, mesh) layer cache;
    an unnamed call builds a fresh layer with freshly initialized weights
    every time (fine at model construction, wrong inside a per-step
    forward)."""
    from .fleet.layers.mpu.mp_ops import split as _split
    return _split(x, size, operation=operation, axis=axis,
                  num_partitions=num_partitions, gather_out=gather_out,
                  weight_attr=weight_attr, bias_attr=bias_attr, name=name)


def reset_split_layer_cache() -> int:
    """Explicitly release the named :func:`split` layer cache (which
    never evicts on its own — each entry pins its mesh alive). Called
    automatically by ``fleet.init`` on re-initialization; exposed here
    for servers/tests that churn meshes outside fleet. Returns the
    number of evicted layers."""
    from .fleet.layers.mpu.mp_ops import reset_split_layer_cache as _r
    return _r()


# ---------------- PS sparse-table entry configs ----------------
class _EntryAttr:
    def _to_attr(self) -> str:
        raise NotImplementedError


class CountFilterEntry(_EntryAttr):
    """reference: distributed/entry_attr.py CountFilterEntry — a sparse
    feature enters the table after being seen ``count_filter`` times."""

    def __init__(self, count_filter: int):
        if not isinstance(count_filter, int) or count_filter < 0:
            raise ValueError("count_filter must be a non-negative integer")
        self.count_filter = count_filter

    def _to_attr(self):
        return f"count_filter_entry:{self.count_filter}"


class ProbabilityEntry(_EntryAttr):
    """reference: entry_attr.py ProbabilityEntry — admit with probability."""

    def __init__(self, probability: float):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self.probability = probability

    def _to_attr(self):
        return f"probability_entry:{self.probability}"


class ShowClickEntry(_EntryAttr):
    """reference: entry_attr.py ShowClickEntry — show/click-var driven."""

    def __init__(self, show_name: str, click_name: str):
        if not isinstance(show_name, str) or not isinstance(click_name, str):
            raise ValueError("show_name/click_name must be variable names")
        self.show_name = show_name
        self.click_name = click_name

    def _to_attr(self):
        return f"show_click_entry:{self.show_name}:{self.click_name}"
