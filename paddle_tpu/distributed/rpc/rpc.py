"""RPC layer (reference: python/paddle/distributed/rpc/rpc.py init_rpc /
rpc_sync / rpc_async over C++ RpcAgent, paddle/fluid/distributed/rpc/
rpc_agent.h — brpc-based).

TPU-native: host-side control-plane RPC (data-plane traffic rides XLA
collectives, never RPC). Implementation: each worker runs a pickle-over-
socket server thread; endpoints rendezvous through the shared filesystem
or an explicit worker map. Functions must be importable at the callee
(same contract as the reference).
"""
from __future__ import annotations

import concurrent.futures
import os
import pickle
import select
import socket
import socketserver
import struct
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

_state = {
    "name": None, "rank": None, "world": None,
    "workers": {},        # name -> (host, port)
    "server": None,
    "pool": None,
}


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str
    port: int


def _send_msg(sock, obj):
    # protocol 5: numpy arrays serialize through the buffer protocol —
    # the PS pull/push hot path is row matrices
    data = pickle.dumps(obj, protocol=5)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_msg(sock):
    hdr = b""
    while len(hdr) < 8:
        c = sock.recv(8 - len(hdr))
        if not c:
            raise ConnectionError("rpc peer closed")
        hdr += c
    n = struct.unpack("<Q", hdr)[0]
    chunks = []
    got = 0
    while got < n:
        c = sock.recv(min(1 << 20, n - got))
        if not c:
            raise ConnectionError("rpc peer closed")
        chunks.append(c)
        got += len(c)
    return pickle.loads(b"".join(chunks))


class _Handler(socketserver.BaseRequestHandler):
    """Serves a PERSISTENT connection: one request/response per loop
    iteration until the peer closes (the reference's brpc keeps
    long-lived channels; a fresh TCP handshake per pull/push was the
    dominant wire cost — see tools/ps_bench.py)."""

    def handle(self):
        try:
            self.request.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
        except OSError:
            pass
        while True:
            try:
                fn, args, kwargs = _recv_msg(self.request)
            except ConnectionError:
                return
            try:
                result = ("ok", fn(*args, **kwargs))
            except Exception as e:  # ship the exception back
                result = ("err", e)
            try:
                _send_msg(self.request, result)
            except ConnectionError:
                return
            except Exception as e:
                # unpicklable result/exception: the request DID execute,
                # so the connection must stay open with a response — a
                # silent close would let the client's clean-EOF retry
                # run it twice
                try:
                    _send_msg(self.request, ("err", RuntimeError(
                        f"rpc result not serializable: {e!r}")))
                except Exception:
                    return


class _Server(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """reference: rpc.py init_rpc. Starts this worker's server and
    registers its endpoint; rendezvous via a shared registry dir."""
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    world_size = world_size or int(
        os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    # The wire protocol is pickle (code execution on deserialize), so only
    # expose the server beyond loopback when multi-host is explicitly
    # requested via PADDLE_LOCAL_IP — the address peers should dial.
    host = os.environ.get("PADDLE_LOCAL_IP")
    bind = "0.0.0.0" if host else "127.0.0.1"
    server = _Server((bind, 0), _Handler)
    port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True).start()
    if not host:
        host = "127.0.0.1"
    reg = os.environ.get("PADDLE_RPC_REGISTRY", "/tmp/paddle_tpu_rpc")
    job = os.environ.get("PADDLE_JOB_ID", "default")
    os.makedirs(os.path.join(reg, job), exist_ok=True)
    with open(os.path.join(reg, job, f"{name}.addr"), "w") as f:
        f.write(f"{rank}\t{host}\t{port}")

    _state.update(name=name, rank=rank, world=world_size, server=server,
                  pool=concurrent.futures.ThreadPoolExecutor(16))
    # wait for all workers to register
    deadline = time.time() + 60
    while time.time() < deadline:
        entries = os.listdir(os.path.join(reg, job))
        if len([e for e in entries if e.endswith(".addr")]) >= world_size:
            break
        time.sleep(0.05)
    _rescan_registry()


def _rescan_registry():
    reg = os.environ.get("PADDLE_RPC_REGISTRY", "/tmp/paddle_tpu_rpc")
    job = os.environ.get("PADDLE_JOB_ID", "default")
    for fn in os.listdir(os.path.join(reg, job)):
        if fn.endswith(".addr"):
            wname = fn[:-5]
            with open(os.path.join(reg, job, fn)) as f:
                r, host, p = f.read().split("\t")
            _state["workers"][wname] = WorkerInfo(wname, int(r), host,
                                                  int(p))


def wait_for_workers(names, timeout: float = 60.0):
    """Block until every NAMED peer is registered (the generic count
    wait can be satisfied by the wrong peers — e.g. sibling trainers
    racing ahead of a slow server)."""
    deadline = time.time() + timeout
    missing = [n for n in names if n not in _state["workers"]]
    while missing and time.time() < deadline:
        time.sleep(0.05)
        _rescan_registry()
        missing = [n for n in names if n not in _state["workers"]]
    if missing:
        raise TimeoutError(f"rpc peers never registered: {missing}")


_conn_local = threading.local()
_all_conns: set = set()          # every pooled socket, across threads
_all_conns_lock = threading.Lock()


def _conn_cache() -> Dict[str, socket.socket]:
    cache = getattr(_conn_local, "conns", None)
    if cache is None:
        cache = _conn_local.conns = {}
    return cache


def _dial(info, timeout) -> socket.socket:
    s = socket.create_connection((info.ip, info.port),
                                 timeout=timeout or None)
    try:
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        pass
    return s


def _drop_conn(to: str):
    s = _conn_cache().pop(to, None)
    if s is not None:
        with _all_conns_lock:
            _all_conns.discard(s)
        try:
            s.close()
        except OSError:
            pass


def _close_all_conns():
    """Close EVERY pooled socket — including ones owned by other
    threads (the rpc_async pool); their caches keep stale entries, but
    the next _call on those threads fails-fast and re-dials."""
    for to in list(_conn_cache()):
        _drop_conn(to)
    with _all_conns_lock:
        conns = list(_all_conns)
        _all_conns.clear()
    for s in conns:
        try:
            s.close()
        except OSError:
            pass


def _peer_closed(s: socket.socket) -> bool:
    """Non-blocking FIN probe on an idle pooled connection: a peer that
    restarted between calls has closed its end, making the socket
    readable with EOF. Request/response discipline means no data is
    ever pending on an idle connection, so readable == dead (EOF or
    RST). A zero-timeout select does the probe — MSG_DONTWAIT alone
    would be defeated by CPython's readiness wait on blocking sockets."""
    try:
        r, _, _ = select.select([s], [], [], 0)
        if not r:
            return False       # nothing pending — alive
        return s.recv(1, socket.MSG_PEEK) == b""
    except (OSError, ValueError):
        # ValueError: fd >= FD_SETSIZE (select's 1024 limit) — can't
        # probe; treat as dead so the call re-dials a fresh socket
        return True


def _call(to: str, fn, args, kwargs, timeout):
    """Request/response over a pooled per-(thread, peer) persistent
    connection, strictly at-most-once:

    - staleness (peer restarted between calls) is detected BEFORE the
      send — a FIN probe on the idle socket — and re-dialed, so no
      retry ever races an executed request;
    - a send failure re-dials once (a partially-sent request tears the
      server's message decode before the function runs);
    - any failure AFTER the request is fully sent raises — the response
      is lost and the request may have executed.

    PADDLE_TPU_RPC_ONESHOT=1: dial-per-call (the pre-pooling wire, kept
    as the measurement A/B for tools/ps_bench.py)."""
    info = _state["workers"].get(to)
    if info is None:
        raise RuntimeError(f"unknown rpc worker {to!r}")
    oneshot = bool(os.environ.get("PADDLE_TPU_RPC_ONESHOT"))
    cache = _conn_cache()
    s = None
    try:
        for attempt in (0, 1):
            if oneshot:
                s, fresh = _dial(info, timeout), True
            else:
                s = cache.get(to)
                if s is not None and _peer_closed(s):
                    _drop_conn(to)
                    s = None
                fresh = s is None
                if fresh:
                    s = _dial(info, timeout)
                    cache[to] = s
                    with _all_conns_lock:
                        _all_conns.add(s)
            try:
                s.settimeout(timeout or None)
                _send_msg(s, (fn, args or (), kwargs or {}))
            except (ConnectionError, OSError):
                if not oneshot:
                    _drop_conn(to)
                if fresh or attempt:
                    raise
                continue       # partial send: server cannot have run it
            try:
                status, payload = _recv_msg(s)
                break
            except (ConnectionError, OSError) as e:
                if not oneshot:
                    _drop_conn(to)
                raise ConnectionError(
                    f"rpc response from {to!r} lost ({e}); the request "
                    f"may have executed — not retrying") from e
    finally:
        if oneshot and s is not None:
            try:
                s.close()
            except OSError:
                pass
    if status == "err":
        raise payload
    return payload


def rpc_sync(to: str, fn, args=None, kwargs=None, timeout=180):
    """reference: rpc.py rpc_sync — blocking remote call."""
    return _call(to, fn, args, kwargs, timeout)


def rpc_async(to: str, fn, args=None, kwargs=None, timeout=180):
    """reference: rpc.py rpc_async — returns a Future with .wait()."""
    fut = _state["pool"].submit(_call, to, fn, args, kwargs, timeout)
    fut.wait = fut.result  # paddle Future API alias
    return fut


def get_current_worker_info() -> WorkerInfo:
    return _state["workers"][_state["name"]]


def get_worker_info(name: str) -> WorkerInfo:
    return _state["workers"][name]


def get_all_worker_infos():
    return list(_state["workers"].values())


def shutdown():
    """reference: rpc.py shutdown (barrier semantics relaxed: local)."""
    _close_all_conns()
    if _state["server"] is not None:
        _state["server"].shutdown()
        _state["server"] = None
    if _state["pool"] is not None:
        _state["pool"].shutdown(wait=False)
        _state["pool"] = None
    reg = os.environ.get("PADDLE_RPC_REGISTRY", "/tmp/paddle_tpu_rpc")
    job = os.environ.get("PADDLE_JOB_ID", "default")
    try:
        os.remove(os.path.join(reg, job, f"{_state['name']}.addr"))
    except OSError:
        pass
    _state["workers"].clear()
