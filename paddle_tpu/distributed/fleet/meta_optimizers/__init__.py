"""Fleet meta-optimizers (reference: fleet/meta_optimizers/__init__.py)."""
from .hybrid_parallel_optimizer import (HybridParallelOptimizer,
                                        DygraphShardingOptimizer)
from .dgc_optimizer import DGCMomentumOptimizer
from .localsgd_optimizer import LocalSGDOptimizer, AdaptiveLocalSGDOptimizer

__all__ = ["HybridParallelOptimizer", "DygraphShardingOptimizer",
           "DGCMomentumOptimizer", "LocalSGDOptimizer",
           "AdaptiveLocalSGDOptimizer"]
