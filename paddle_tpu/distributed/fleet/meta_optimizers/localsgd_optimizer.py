"""Local SGD meta-optimizers (plain + adaptive communication interval).

Reference: python/paddle/distributed/fleet/meta_optimizers/localsgd_optimizer.py
(LocalSGDOptimizer — every ``k_steps`` after ``begin_step`` the workers
average parameters via snapshot-delta allreduce; AdaptiveAsyncLocalSGD
resizes the interval from the loss trajectory:
``next_k = clip(ceil(sqrt(lr0*loss/(lr*loss0) * init_k)), 1, 16)``,
localsgd_optimizer.py:458).

TPU-native redesign: the per-worker divergent state the reference gets from
independent processes lives here either (a) in a ``shard_map`` train step
where each dp shard carries its own parameter replica — the sync is a
``lax.pmean`` (``localsgd_params_average``); or (b) across multiple
controller processes, where the eager dist-tensor collective path performs
the average.  Under single-controller SPMD with *replicated* parameters the
average is mathematically the identity, so the wrapper is still correct —
the interesting regimes are (a) and (b).  The reference's snapshot/delta
dance (param = snapshot - allreduce(snapshot - param)/n) is algebraically
``mean(param)`` and is implemented directly as such.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ... import collective as _collective
from ...mesh import Group, ReduceOp, get_world_group, in_mapped_context

__all__ = ["LocalSGDOptimizer", "AdaptiveLocalSGDOptimizer",
           "localsgd_params_average"]


def localsgd_params_average(params, axis: str):
    """Average a parameter pytree over mesh axis ``axis`` (mapped regime).

    The shard_map-native sync step: call on the per-rank replica pytree
    every ``k_steps`` local updates.
    """
    return jax.tree_util.tree_map(lambda p: lax.pmean(p, axis), params)


class LocalSGDOptimizer:
    """reference: meta_optimizers/localsgd_optimizer.py:28.

    Wraps an inner optimizer; runs it every step and averages parameters
    over the data-parallel group once per ``k_steps`` after ``begin_step``.
    """

    def __init__(self, inner_opt, k_steps: int = 1, begin_step: int = 1,
                 group: Optional[Group] = None):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self._inner = inner_opt
        self._k_steps = int(k_steps)
        self._begin_step = int(begin_step)
        self._group = group
        self._step_count = 0
        # reference initializes last_step to begin_step, so the first
        # average fires at begin_step + k_steps (not begin_step + 1)
        self._last_sync = self._begin_step

    # --- delegation ---
    def __getattr__(self, item):
        return getattr(self._inner, item)

    @property
    def k_steps(self) -> int:
        return self._k_steps

    def _average_params(self):
        g = self._group or get_world_group()
        if g is None or g.nranks <= 1:
            return
        for p in (self._inner._parameter_list or []):
            if in_mapped_context(g):
                avg = lax.pmean(p._value, g.axis_names[0])
                p._inplace_assign(avg)
            elif _collective._eager_dist(p, g) is not None:
                res = _collective.all_reduce(p, op=ReduceOp.AVG, group=g)
                if res is not None:   # eager regime returns a new Tensor
                    p._inplace_from(res)
            # else: a plain (replicated) single-controller tensor holds the
            # same value on every rank by construction — mean == identity

    def _sync_due(self) -> bool:
        return (self._step_count > self._begin_step
                and self._step_count - self._last_sync >= self._k_steps)

    def step(self):
        self._inner.step()
        self._step_count += 1
        if self._sync_due():
            self._average_params()
            self._last_sync = self._step_count

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self._inner.minimize(loss, startup_program, parameters, no_grad_set)
        self._step_count += 1
        if self._sync_due():
            self._average_params()
            self._last_sync = self._step_count
        return None, None

    def clear_grad(self, set_to_zero=False):
        self._inner.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        sd = dict(self._inner.state_dict())
        sd["@localsgd_step"] = self._step_count
        sd["@localsgd_last_sync"] = self._last_sync
        sd["@localsgd_k_steps"] = self._k_steps
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        self._step_count = int(sd.pop("@localsgd_step", self._step_count))
        self._last_sync = int(sd.pop("@localsgd_last_sync", self._last_sync))
        self._k_steps = int(sd.pop("@localsgd_k_steps", self._k_steps))
        self._inner.set_state_dict(sd)


class AdaptiveLocalSGDOptimizer(LocalSGDOptimizer):
    """reference: meta_optimizers/localsgd_optimizer.py:212 (AdaptiveAsync).

    The communication interval grows as training flattens: at every sync
    the next interval is ``clip(ceil(sqrt(lr0 * loss / (lr * loss0) *
    init_k_steps)), 1, 16)`` where ``(lr0, loss0)`` are recorded on the
    first step (reference :458-470).  Call ``minimize(loss)`` (or
    ``step(loss=...)``) so the wrapper sees the loss.
    """

    _MAX_K = 16

    def __init__(self, inner_opt, init_k_steps: int = 1, begin_step: int = 1,
                 group: Optional[Group] = None):
        super().__init__(inner_opt, k_steps=init_k_steps,
                         begin_step=begin_step, group=group)
        self._init_k_steps = int(init_k_steps)
        self._loss0: Optional[float] = None
        self._lr0: Optional[float] = None

    def _record_initial(self, loss_value: float):
        if self._loss0 is None:
            self._loss0 = float(loss_value)
            self._lr0 = float(self._inner.get_lr())

    def _next_k(self, loss_value: float) -> int:
        lr = float(self._inner.get_lr())
        if not self._loss0 or not lr:
            return self._k_steps
        nk = math.ceil(math.sqrt(
            self._lr0 * float(loss_value) / (lr * self._loss0)
            * self._init_k_steps))
        return max(1, min(self._MAX_K, int(nk)))

    def _after_step(self, loss):
        self._step_count += 1
        loss_value = None
        if loss is not None:
            loss_value = float(jnp.asarray(
                loss._value if hasattr(loss, "_value") else loss))
            self._record_initial(loss_value)
        if self._sync_due():
            self._average_params()
            self._last_sync = self._step_count
            if loss_value is not None:
                self._k_steps = self._next_k(loss_value)

    def step(self, loss=None):
        self._inner.step()
        self._after_step(loss)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self._inner.minimize(loss, startup_program, parameters, no_grad_set)
        self._after_step(loss)
        return None, None

    def state_dict(self):
        sd = super().state_dict()
        sd["@localsgd_init_k"] = self._init_k_steps
        if self._loss0 is not None:
            sd["@localsgd_loss0"] = self._loss0
            sd["@localsgd_lr0"] = self._lr0
        return sd

    def set_state_dict(self, sd):
        sd = dict(sd)
        self._init_k_steps = int(sd.pop("@localsgd_init_k",
                                        self._init_k_steps))
        if "@localsgd_loss0" in sd:
            self._loss0 = float(sd.pop("@localsgd_loss0"))
            self._lr0 = float(sd.pop("@localsgd_lr0"))
        super().set_state_dict(sd)
