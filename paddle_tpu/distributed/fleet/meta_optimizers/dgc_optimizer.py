"""Deep Gradient Compression momentum optimizer.

Reference: python/paddle/distributed/fleet/meta_optimizers/dgc_optimizer.py
(DGCMomentumOptimizer — momentum correction accumulators ``_dgc_u_`` /
``_dgc_v_``, rampup sparsity schedule, 16384-element / fp32 eligibility
gate, clip-norm scaled by ``num_trainers**-0.5``) and the native kernels
paddle/fluid/operators/dgc_op.h (top-k encode + error feedback) and
dgc_momentum op (momentum update before ``rampup_begin_step``, plain SGD
after — the momentum is already folded into the compressed gradient).

TPU-native redesign: the reference ships gradients through the external
libdgc CSC sparse-allreduce over NCCL rings.  Here compression is a pure
jax function (``dgc_compress``) and the sparse exchange is an
``all_gather`` of fixed-``k`` (index, value) pairs over the data-parallel
mesh axis followed by a dense scatter-add (``dgc_sparse_allreduce``) —
static shapes, ICI-friendly, and the comm volume is ``2*k*nranks`` words
instead of ``numel``.  ``k`` is resolved per rampup *stage* at trace time
(the stage is a host-level step counter), so each stage compiles once and
``lax.top_k`` always sees a static k.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ....optimizer.optimizers import Momentum, _apply_l2
from ....nn.clip_grad import ClipGradByNorm
from ...mesh import Group, in_mapped_context

__all__ = ["DGCMomentumOptimizer", "dgc_compress", "dgc_sparse_allreduce",
           "dgc_stage_sparsity"]

# Eligibility gate (reference dgc_optimizer.py:116 `_is_use_dgc`): small or
# non-fp32 params take the plain dense momentum path.
_DGC_MIN_NUMEL = 16384


def dgc_stage_sparsity(step: int, rampup_begin_step: int, rampup_step: int,
                       sparsity: Sequence[float]) -> Optional[float]:
    """Sparsity in effect at host-step ``step`` or None for the dense phase.

    Mirrors the reference rampup (dgc_op: warmup stages spread uniformly
    over ``rampup_step`` steps, final sparsity afterwards).
    """
    if step < rampup_begin_step:
        return None
    off = step - rampup_begin_step
    if rampup_step <= 0 or off >= rampup_step:
        return float(sparsity[-1])
    period = max(1, math.ceil(rampup_step / len(sparsity)))
    return float(sparsity[min(off // period, len(sparsity) - 1)])


def _k_for(numel: int, s: float) -> int:
    return max(1, min(numel, int(round(numel * (1.0 - s)))))


def dgc_compress(g, u, v, *, momentum: float, k: int,
                 nesterov: bool = False):
    """Momentum-corrected top-k sparsification with error feedback.

    u' = m*u + g ; v' = v + u' (plain momentum) or v' = v + g + m*u'
    (Nesterov, matching the reference dgc op's use_nesterov branch);
    select the k largest |v'| entries; the selected entries are
    communicated and cleared from BOTH accumulators (reference dgc_op.h
    encode step), the rest stay as local residual.

    Returns ``(idx, vals, new_u, new_v)`` with ``idx``/``vals`` of static
    length ``k`` (flat indices into the parameter).
    """
    u = momentum * u + g
    v = v + (g + momentum * u if nesterov else u)
    flat_v = v.reshape(-1)
    _, idx = lax.top_k(jnp.abs(flat_v), k)
    vals = flat_v[idx]
    new_v = flat_v.at[idx].set(0.0).reshape(v.shape)
    new_u = u.reshape(-1).at[idx].set(0.0).reshape(u.shape)
    return idx, vals, new_u, new_v


def dgc_sparse_allreduce(idx, vals, numel: int, axis: Optional[str] = None,
                         mean: bool = True):
    """Exchange sparse (idx, vals) over mesh axis ``axis`` and densify.

    Inside shard_map: all_gather both halves (2*k words per rank on the
    wire vs ``numel`` for a dense all-reduce) and scatter-add into a dense
    flat gradient.  With ``axis=None`` (single worker) it just densifies.
    """
    if axis is not None:
        idx = lax.all_gather(idx, axis, tiled=True)
        vals = lax.all_gather(vals, axis, tiled=True)
        n = lax.psum(jnp.ones((), jnp.float32), axis)
    else:
        n = jnp.ones((), jnp.float32)
    dense = jnp.zeros((numel,), vals.dtype).at[idx].add(vals)
    return dense / n if mean else dense


class DGCMomentumOptimizer(Momentum):
    """reference: fleet/meta_optimizers/dgc_optimizer.py:31.

    Before ``rampup_begin_step`` this is exactly ``Momentum`` (dense-phase
    gradients are assumed already averaged by the DP regime, as everywhere
    else in this codebase).  From ``rampup_begin_step`` on, eligible
    parameters switch to compressed updates: the momentum lives in the
    ``_dgc_u_`` accumulator, the synced sparse gradient is applied as plain
    SGD (reference dgc_momentum op semantics).
    """

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 rampup_begin_step=0, rampup_step=1,
                 sparsity: Sequence[float] = (0.999,), parameters=None,
                 use_nesterov=False, num_trainers: Optional[int] = None,
                 weight_decay=None, grad_clip=None, name=None,
                 group: Optional[Group] = None):
        if grad_clip is not None and not isinstance(grad_clip, ClipGradByNorm):
            raise TypeError(
                "DGCMomentumOptimizer only supports ClipGradByNorm "
                "(reference dgc_optimizer.py:82)")
        self._clip_norm = None
        self._local_clip_norm = None
        if grad_clip is not None:
            if not isinstance(num_trainers, int) or num_trainers <= 0:
                raise ValueError(
                    "num_trainers (positive int) is required with grad_clip")
            # clipping happens in this class's step() pre-pass, NOT via the
            # base optimizer (which would see already-averaged gradients):
            # compressed params clip their LOCAL grad to clip_norm/sqrt(n)
            # before compression so the aggregate respects clip_norm
            # (reference :89); dense-phase / ineligible params clip the
            # averaged grad at the full clip_norm.
            self._clip_norm = float(grad_clip.clip_norm)
            self._local_clip_norm = self._clip_norm * (num_trainers ** -0.5)
        super().__init__(learning_rate, momentum, parameters,
                         use_nesterov=use_nesterov, weight_decay=weight_decay,
                         grad_clip=None, name=name)
        if rampup_begin_step < 0:
            raise ValueError("rampup_begin_step must be >= 0")
        self._rampup_begin_step = int(rampup_begin_step)
        self._rampup_step = int(rampup_step)
        self._sparsity = [float(s) for s in sparsity]
        self._group = group

    # ---- helpers ----
    def _use_dgc(self, p) -> bool:
        numel = 1
        for d in p.shape:
            numel *= int(d)
        return numel >= _DGC_MIN_NUMEL and jnp.result_type(
            p._value if hasattr(p, "_value") else p) == jnp.float32

    def _comm_axis(self) -> Optional[str]:
        g = self._group
        if g is not None and in_mapped_context(g):
            return g.axis_names[0]
        return None

    def _update_rule(self, p, g, state, lr, ctx):
        if not ctx.get("_dgc_active", False):
            return super()._update_rule(p, g, state, lr, ctx)
        # compressed phase: g is the densified synced sparse gradient with
        # momentum already folded in -> plain SGD (dgc_momentum op).
        g = _apply_l2(g.astype(jnp.float32), p.astype(jnp.float32),
                      ctx.get("weight_decay"))
        return p - lr * g, state

    @staticmethod
    def _clip_to(g, c):
        n = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
        return (g * jnp.minimum(1.0, c / jnp.maximum(n, 1e-12))).astype(
            g.dtype)

    def step(self):
        # 0-based completed-step count: the first step sees step=0, so
        # rampup_begin_step=0 starts at sparsity[0] (stage schedule is
        # 0-based like the reference dgc kernel's current_step compare)
        sparsity = dgc_stage_sparsity(
            self._global_step, self._rampup_begin_step,
            self._rampup_step, self._sparsity)
        def clip_grad(p, c):
            # honors per-param opt-out like ClipGradByNorm._dygraph_clip
            if c is not None and getattr(p, "need_clip", True):
                p.grad._inplace_assign(self._clip_to(p.grad._value, c))

        if sparsity is None:
            for p in (self._parameter_list or []):
                if not p.stop_gradient and p.grad is not None:
                    clip_grad(p, self._clip_norm)
            super().step()
            return
        axis = self._comm_axis()
        # the n^-0.5 local threshold only makes sense when a cross-rank SUM
        # follows; outside the mapped regime the "aggregate" IS the single
        # locally-clipped grad, so the full clip_norm applies
        local_clip = (self._local_clip_norm if axis is not None
                      else self._clip_norm)
        # pre-pass: replace eligible grads with synced compressed grads and
        # flag them so _update_rule applies SGD instead of momentum.
        flagged = []
        for p in (self._parameter_list or []):
            if p.stop_gradient or p.grad is None:
                continue
            if not self._use_dgc(p):
                clip_grad(p, self._clip_norm)
                continue
            # per-worker pre-aggregation clip (reference dgc op order)
            clip_grad(p, local_clip)
            u = self._acc("_dgc_u_", p)
            v = self._acc("_dgc_v_", p)
            numel = 1
            for d in p.shape:
                numel *= int(d)
            k = _k_for(numel, sparsity)
            idx, vals, nu, nv = dgc_compress(
                p.grad._value, u._value, v._value,
                momentum=self._momentum, k=k, nesterov=self._nesterov)
            u._inplace_assign(nu)
            v._inplace_assign(nv)
            synced = dgc_sparse_allreduce(idx, vals, numel, axis=axis)
            p.grad._inplace_assign(synced.reshape(p.grad._value.shape))
            flagged.append(p)
        marker = set(id(p) for p in flagged)
        orig_rule = self._update_rule

        # route flagged params through the SGD branch via ctx
        def rule(pv, gv, st, plr, ctx):
            ctx = dict(ctx)
            pobj = ctx.get("param")
            ctx["_dgc_active"] = pobj is not None and id(pobj) in marker
            return orig_rule(pv, gv, st, plr, ctx)

        self._update_rule = rule  # type: ignore[method-assign]
        try:
            super().step()
        finally:
            del self._update_rule
