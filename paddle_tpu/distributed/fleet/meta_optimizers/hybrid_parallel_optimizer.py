"""Hybrid-parallel optimizer wrappers.

Re-design of the reference's dygraph meta-optimizers
(reference: python/paddle/distributed/fleet/meta_optimizers/
dygraph_optimizer/hybrid_parallel_optimizer.py:266 HybridParallelOptimizer,
dygraph_sharding_optimizer.py:54 DygraphShardingOptimizer).

The reference's step() syncs TP grads, reduce-scatters sharding grads and
broadcasts updated shards. Single-controller TPU: grads on global arrays are
already consistent (the compiled backward holds the reductions), so the
wrapper's jobs are (a) API parity, (b) global-norm grad clip across the
whole parameter set (the reference clips across groups), and (c) ZeRO
stage-1 state sharding — optimizer accumulators laid out over the
``sharding`` mesh axis so each device stores 1/N of the state (the memory
win of DygraphShardingOptimizer, without the bookkeeping).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...._core.tensor import Tensor


def _shard_state_over(axis: str, mesh):
    """Wrap Optimizer._acc so accumulators are sharded on dim 0 over
    ``axis`` when divisible (ZeRO-1 memory layout)."""
    def deco(orig_acc):
        def _acc(name, p, init=None, dtype=None):
            t = orig_acc(name, p, init=init, dtype=dtype)
            if getattr(t, "_zero_sharded", False) or t.ndim == 0:
                return t
            n = mesh.shape[axis]
            if n > 1 and t.ndim >= 1 and t.shape[0] % n == 0:
                spec = [None] * t.ndim
                spec[0] = axis
                try:
                    t._inplace_assign(jax.device_put(
                        t._value, NamedSharding(mesh, P(*spec))))
                    t._zero_sharded = True
                except Exception:
                    pass
            return t
        return _acc
    return deco


class HybridParallelOptimizer:
    """reference: hybrid_parallel_optimizer.py:266."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        self._sharding_enabled = (
            hcg is not None and hcg.get_sharding_parallel_world_size() > 1)
        if self._sharding_enabled:
            # unwrap meta-optimizer shells (LocalSGD etc.): the patch must
            # land on the object whose step() resolves self._acc, or the
            # accumulators silently stay replicated
            target = optimizer
            while hasattr(target, "_inner"):
                target = target._inner
            target._acc = _shard_state_over(
                "sharding", hcg.mesh)(target._acc)

    def step(self, *args, **kwargs):
        # forwarded so meta-optimizers with extended signatures stay
        # reachable (AdaptiveLocalSGDOptimizer.step(loss=...))
        self._inner_opt.step(*args, **kwargs)

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        return self._inner_opt.minimize(loss)

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)


class DygraphShardingOptimizer(HybridParallelOptimizer):
    """reference: dygraph_sharding_optimizer.py:54 — stage-1 sharding is the
    state layout installed by the base class; rank-local param slicing is
    subsumed by the sharded accumulator layout."""

    def __init__(self, optimizer, hcg=None, strategy=None):
        super().__init__(optimizer, hcg, strategy)
