"""Sequence parallelism (Megatron-LM style, within the TP group).

Re-design of the reference's sequence_parallel_utils
(reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
— ScatterOp:85, GatherOp:97, AllGatherOp:111, ReduceScatterOp:127,
register_sequence_parallel_allreduce_hooks:192,
ColumnSequenceParallelLinear:429, RowSequenceParallelLinear:564).

Layout convention follows the reference: activations are [s, b, h] and the
sequence dim (0) is split across the mp group. TPU-native: the split IS a
sharding of dim 0 over the ``mp`` mesh axis; the scatter/gather/
reduce-scatter transitions around the TP linears are sharding transitions
that GSPMD lowers to the same reduce_scatter/all_gather pairs the reference
issues manually — fused with the matmuls where profitable.
"""
from __future__ import annotations

import jax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...._core import autograd as ag
from ....nn import functional as F
from ....nn.layer.layers import Layer
from ...mesh import Group, in_mapped_context
from ..layers.mpu import mp_ops
from ..layers.mpu.mp_layers import _mp_group, _shard_param


def _seq_spec(ndim, axis_name):
    spec = [None] * ndim
    spec[0] = axis_name
    return P(*spec)


def ScatterOp(x, group=None):
    """Split along seq dim 0; bwd = all-gather (reference :85)."""
    g = _mp_group(group)
    if g.nranks == 1:
        return x
    if in_mapped_context(g):
        a = g.axis_names[0]
        n = g.nranks

        def f(v):
            idx = lax.axis_index(a)
            size = v.shape[0] // n
            return lax.dynamic_slice_in_dim(v, idx * size, size, 0)
        return ag.apply(f, x, name="sp_scatter")
    return ag.apply(lambda v: mp_ops._constraint(
        v, _seq_spec(v.ndim, g.axis_names[0]), g.mesh), x, name="sp_scatter")


def GatherOp(x, group=None):
    """All-gather along seq dim 0; bwd = scatter (reference :97)."""
    g = _mp_group(group)
    if g.nranks == 1:
        return x
    if in_mapped_context(g):
        a = g.axis_names[0]
        return ag.apply(lambda v: lax.all_gather(v, a, axis=0, tiled=True),
                        x, name="sp_gather")
    return ag.apply(lambda v: mp_ops._constraint(v, P(), g.mesh),
                    x, name="sp_gather")


def AllGatherOp(x, group=None):
    """All-gather fwd / reduce-scatter bwd (reference :111) — the input
    transition of a column-parallel linear under SP."""
    g = _mp_group(group)
    if g.nranks == 1:
        return x
    if in_mapped_context(g):
        a = g.axis_names[0]

        @jax.custom_vjp
        def agat(v):
            return lax.all_gather(v, a, axis=0, tiled=True)

        def fwd(v):
            return agat(v), None

        def bwd(_, ct):
            return (lax.psum_scatter(ct, a, scatter_dimension=0, tiled=True),)

        agat.defvjp(fwd, bwd)
        return ag.apply(agat, x, name="sp_allgather")
    return ag.apply(lambda v: mp_ops._constraint(v, P(), g.mesh),
                    x, name="sp_allgather")


def ReduceScatterOp(x, group=None):
    """Reduce-scatter fwd / all-gather bwd (reference :127) — the output
    transition of a row-parallel linear under SP."""
    g = _mp_group(group)
    if g.nranks == 1:
        return x
    if in_mapped_context(g):
        a = g.axis_names[0]

        @jax.custom_vjp
        def rs(v):
            return lax.psum_scatter(v, a, scatter_dimension=0, tiled=True)

        def fwd(v):
            return rs(v), None

        def bwd(_, ct):
            return (lax.all_gather(ct, a, axis=0, tiled=True),)

        rs.defvjp(fwd, bwd)
        return ag.apply(rs, x, name="sp_reduce_scatter")
    return ag.apply(lambda v: mp_ops._constraint(
        v, _seq_spec(v.ndim, g.axis_names[0]), g.mesh),
        x, name="sp_reduce_scatter")


def mark_as_sequence_parallel_parameter(param):
    """reference :169 — SP params (LayerNorm etc.) need grad allreduce over
    the mp group. GSPMD handles replicated-param grad reduction; keep the
    marker for parity/tests."""
    param.sequence_parallel = True


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps=1,
                                               fuse_sequence_parallel_allreduce=False):
    """reference :192 — no eager hook needed: grads of replicated params are
    reduced by the compiled backward. No-op for parity."""
    return


class ColumnSequenceParallelLinear(Layer):
    """reference :429 — all-gather(seq) then column-parallel matmul."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, fuse_matmul_bias=False,
                 mp_group=None, name=None):
        super().__init__()
        self._group = _mp_group(mp_group)
        n = self._group.nranks
        if out_features % max(n, 1) != 0:
            raise ValueError("out_features not divisible by mp degree")
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=weight_attr,
                                          is_bias=True) if has_bias else None
        if n > 1:
            ax = self._group.axis_names[0]
            _shard_param(self.weight, self._group.mesh, P(None, ax))
            if self.bias is not None:
                _shard_param(self.bias, self._group.mesh, P(ax))

    def forward(self, x):
        x = AllGatherOp(x, self._group)
        return F.linear(x, self.weight, self.bias)


class RowSequenceParallelLinear(Layer):
    """reference :564 — row-parallel matmul then reduce-scatter(seq)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True,
                 fuse_matmul_bias=False, mp_group=None, name=None):
        super().__init__()
        self._group = _mp_group(mp_group)
        n = self._group.nranks
        if in_features % max(n, 1) != 0:
            raise ValueError("in_features not divisible by mp degree")
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=weight_attr,
                                          is_bias=True) if has_bias else None
        if n > 1:
            _shard_param(self.weight, self._group.mesh,
                         P(self._group.axis_names[0], None))

    def forward(self, x):
        out = F.linear(x, self.weight, None)
        out = ReduceScatterOp(out, self._group)
        if self.bias is not None:
            out = out + self.bias
        return out
