"""Fleet slot-data pipelines (reference: python/paddle/distributed/fleet/
dataset/dataset.py — DatasetBase, InMemoryDataset:455, QueueDataset; data
generators fleet/data_generator/data_generator.py).

The reference backs these with the C++ MultiSlotDataFeed reading
space-separated slot files into the trainer threads.  TPU-native: the
pipeline is host-side python/numpy feeding jit steps, so the datasets
here parse the same slot file format eagerly (InMemory) or lazily
(Queue) and iterate (slot_name -> np.ndarray) batches.

Slot line format (MultiSlotDataFeed): for each slot in ``use_var`` order,
``<n> v1 ... vn`` repeated on one line per sample.
"""
from __future__ import annotations

import os
import random
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


class DatasetBase:
    """reference: fleet/dataset/dataset.py DatasetBase."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.use_var: List[Any] = []
        self.pipe_command = "cat"
        self.input_type = 0

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self.batch_size = batch_size
        self.thread_num = thread_num
        self.use_var = list(use_var or [])
        self.pipe_command = pipe_command
        self.input_type = input_type

    def set_filelist(self, filelist: Sequence[str]):
        self.filelist = list(filelist)

    def _var_names(self) -> List[str]:
        names = []
        for v in self.use_var:
            names.append(getattr(v, "name", None) or str(v))
        return names

    def _parse_line(self, line: str) -> Optional[List[np.ndarray]]:
        toks = line.split()
        if not toks:
            return None
        slots = []
        i = 0
        try:
            for _ in self.use_var:
                n = int(toks[i])
                vals = toks[i + 1:i + 1 + n]
                i += 1 + n
                arr = np.asarray([float(v) for v in vals], np.float32)
                if all(float(v).is_integer() for v in arr.tolist()):
                    # id slots stay integral (sparse feature ids)
                    arr = arr.astype(np.int64)
                slots.append(arr)
        except (ValueError, IndexError):
            return None
        return slots

    def _iter_samples(self) -> Iterator[List[np.ndarray]]:
        for path in self.filelist:
            with open(path, "r", encoding="utf-8",
                      errors="replace") as f:
                for line in f:
                    s = self._parse_line(line)
                    if s is not None:
                        yield s

    def _batches_from(self, samples) -> Iterator[Dict[str, np.ndarray]]:
        names = self._var_names()
        buf: List[List[np.ndarray]] = []
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._collate(names, buf)
                buf = []
        if buf:
            yield self._collate(names, buf)

    @staticmethod
    def _collate(names, buf) -> Dict[str, np.ndarray]:
        out = {}
        for j, name in enumerate(names):
            cols = [s[j] for s in buf]
            width = max(len(c) for c in cols)
            mat = np.zeros((len(cols), width), cols[0].dtype)
            for r, c in enumerate(cols):
                mat[r, :len(c)] = c
            out[name] = mat
        return out

    def get_memory_data_size(self, fleet=None) -> int:
        return 0


class InMemoryDataset(DatasetBase):
    """reference: fleet/dataset/dataset.py InMemoryDataset:455 — load the
    slot files into host RAM, shuffle there, iterate batches."""

    def __init__(self):
        super().__init__()
        self._memory: List[List[np.ndarray]] = []
        self._queue_num = None
        self._shuffle_seed = 0

    def init(self, **kwargs):
        super().init(**kwargs)
        self._queue_num = kwargs.get("queue_num", self.thread_num)

    def update_settings(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, k if not k.startswith("_") else k, v)
            if k == "batch_size":
                self.batch_size = v

    def load_into_memory(self):
        self._memory = list(self._iter_samples())

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        rng = random.Random(self._shuffle_seed)
        rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single controller: global == local
        self.local_shuffle()

    def release_memory(self):
        self._memory = []

    def get_memory_data_size(self, fleet=None) -> int:
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None) -> int:
        return len(self._memory)

    def set_shuffle_seed(self, seed: int):
        self._shuffle_seed = int(seed)

    def __iter__(self):
        return self._batches_from(iter(self._memory))


class QueueDataset(DatasetBase):
    """reference: fleet/dataset/dataset.py QueueDataset — streaming: files
    are read on the fly, one pass, no shuffle."""

    def __iter__(self):
        return self._batches_from(self._iter_samples())
