"""Fleet slot-data pipelines (reference: python/paddle/distributed/fleet/
dataset/dataset.py — DatasetBase, InMemoryDataset:455, QueueDataset; data
generators fleet/data_generator/data_generator.py).

The reference backs these with the C++ MultiSlotDataFeed reading
space-separated slot files into the trainer threads.  TPU-native: the
pipeline is host-side python/numpy feeding jit steps, so the datasets
here parse the same slot file format eagerly (InMemory) or lazily
(Queue) and iterate (slot_name -> np.ndarray) batches.

Slot line format (MultiSlotDataFeed): for each slot in ``use_var`` order,
``<n> v1 ... vn`` repeated on one line per sample.
"""
from __future__ import annotations

import os
import random
from typing import Any, Dict, Iterator, List, Optional, Sequence

import numpy as np


class DatasetBase:
    """reference: fleet/dataset/dataset.py DatasetBase."""

    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist: List[str] = []
        self.use_var: List[Any] = []
        self.pipe_command = "cat"
        self.input_type = 0

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command="cat", input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self.batch_size = batch_size
        self.thread_num = thread_num
        self.use_var = list(use_var or [])
        self.pipe_command = pipe_command
        self.input_type = input_type

    def set_filelist(self, filelist: Sequence[str]):
        self.filelist = list(filelist)

    def _var_names(self) -> List[str]:
        names = []
        for v in self.use_var:
            names.append(getattr(v, "name", None) or str(v))
        return names

    @staticmethod
    def _token_ok(tok: str) -> bool:
        # exotic numeric forms are rejected by BOTH parse paths so native
        # and python stay sample-identical: hex floats ('0x10' — C strtod
        # accepts, float() rejects) and PEP-515 underscores ('1_5' —
        # float() accepts, C strtod rejects)
        return not any(c in tok for c in "_xX")

    def _parse_line(self, line: str) -> Optional[List[np.ndarray]]:
        toks = line.split()
        if not toks:
            return None
        slots = []
        i = 0
        try:
            for _ in self.use_var:
                if not self._token_ok(toks[i]):
                    return None
                n = int(toks[i])
                if n < 0 or i + 1 + n > len(toks):
                    return None          # truncated slot: malformed line
                vals = toks[i + 1:i + 1 + n]
                i += 1 + n
                if not all(self._token_ok(v) for v in vals):
                    return None
                slots.append(np.asarray([float(v) for v in vals],
                                        np.float64))
        except (ValueError, IndexError):
            return None
        return slots

    def _declared_dtypes(self) -> List[Optional[Any]]:
        """Declared slot dtypes (reference: the MultiSlot PROTO fixes each
        slot's type from the config, not from data): honored when
        ``use_var`` entries carry a dtype (placeholders/tensors); plain
        string names leave the slot undeclared (None)."""
        out = []
        for v in self.use_var:
            d = getattr(v, "dtype", None)
            if d is None:
                out.append(None)
                continue
            name = str(d).split(".")[-1]
            out.append(np.int64 if "int" in name else np.float32)
        return out

    def _slot_dtypes(self, first_sample) -> List[Any]:
        """Canonical dtype rule for BOTH parse paths: declared dtype when
        given, else inferred per slot from the FIRST valid line of the
        file — integral non-empty values -> int64 (sparse feature ids),
        else float32. An inferred-int slot that later shows fractional
        values is PROMOTED to float32 (with a warning) rather than
        silently truncated — see :meth:`_safe_cast`."""
        declared = self._declared_dtypes()
        out = []
        for arr, dec in zip(first_sample, declared):
            if dec is not None:
                out.append(dec)
                continue
            a = np.asarray(arr, np.float64)
            out.append(np.int64 if a.size and
                       bool(np.all(a == np.round(a))) else np.float32)
        return out

    def _safe_cast(self, arr64: np.ndarray, dtypes: List[Any],
                   slot: int, declared: List[Optional[Any]]) -> np.ndarray:
        """Cast per the slot dtype; an UNDECLARED slot inferred int64
        falls back to float32 for any sample carrying fractions (and
        flips the slot for the rest of the stream)."""
        d = dtypes[slot]
        if d is np.int64 and declared[slot] is None and \
                arr64.size and not bool(np.all(arr64 == np.round(arr64))):
            import warnings
            warnings.warn(
                f"slot {slot}: fractional values after an integral first "
                "line — promoting the slot to float32 (declare the slot "
                "dtype via use_var to silence)")
            dtypes[slot] = np.float32
            d = np.float32
        return arr64.astype(d)

    def _iter_python(self, path) -> Iterator[List[np.ndarray]]:
        dtypes = None
        declared = self._declared_dtypes()   # hoisted out of the hot loop
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            for line in f:
                raw_slots = self._parse_line(line)
                if raw_slots is None:
                    continue
                if dtypes is None:
                    dtypes = self._slot_dtypes(raw_slots)
                yield [self._safe_cast(a, dtypes, s, declared)
                       for s, a in enumerate(raw_slots)]

    _NATIVE_CHUNK = 64 << 20  # stream files in 64 MB line-aligned blocks

    def _native_lib(self):
        from ... import _native
        lib = _native.load()
        if lib is None or not hasattr(lib, "pt_slotfile_scan"):
            return None
        import ctypes
        lib.pt_slotfile_scan.restype = ctypes.c_int64
        lib.pt_slotfile_parse.restype = ctypes.c_int64
        return lib

    def _parse_chunk_native(self, lib, buf: bytes, dtypes):
        """Parse one line-aligned byte chunk with the C++ parser; returns
        (samples, dtypes) with dtypes resolved from the first sample when
        not yet known."""
        import ctypes
        n_slots = len(self.use_var)
        total = ctypes.c_int64(0)
        n = lib.pt_slotfile_scan(buf, ctypes.c_int64(len(buf)),
                                 ctypes.c_int(n_slots),
                                 ctypes.byref(total),
                                 ctypes.c_int(self.thread_num))
        if n <= 0:
            return [], dtypes
        vals = np.empty(total.value, np.float64)
        lens = np.empty((n, n_slots), np.int64)
        got = int(lib.pt_slotfile_parse(
            buf, ctypes.c_int64(len(buf)), ctypes.c_int(n_slots),
            vals.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
            ctypes.c_int64(total.value),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(n)))
        lens = lens[:got]
        flat_lens = lens.reshape(-1)
        ends = np.cumsum(flat_lens)
        starts = ends - flat_lens
        if dtypes is None and got:
            first = [vals[starts[s]:ends[s]] for s in range(n_slots)]
            dtypes = self._slot_dtypes(first)
        # same promote-on-fraction rule as _safe_cast, vectorized and at
        # the SAME granularity: an UNDECLARED inferred-int64 slot flips to
        # float32 from its first fractional SAMPLE onward (warn), never
        # truncating — identical output to the python path regardless of
        # chunk boundaries
        declared = self._declared_dtypes()
        chunk_dtypes = list(dtypes)
        flip_at = {}
        int_slots = [s for s in range(n_slots)
                     if dtypes[s] is np.int64 and declared[s] is None]
        if int_slots:
            frac_cum = np.concatenate(
                [[0], np.cumsum(vals != np.round(vals))])
            for s in int_slots:
                idx = np.arange(got) * n_slots + s
                s_starts = starts[idx]
                s_lens = flat_lens[idx]
                bad = (frac_cum[s_starts + s_lens]
                       - frac_cum[s_starts]) > 0
                if bool(bad.any()):
                    import warnings
                    warnings.warn(
                        f"slot {s}: fractional values after an integral "
                        "first line — promoting the slot to float32 "
                        "(declare the slot dtype via use_var to silence)")
                    flip_at[s] = int(np.argmax(bad))
                    dtypes[s] = np.float32   # persists to later chunks
        # one full-array cast per dtype actually used; per-sample work is
        # then two O(1) view slices per slot
        cast = {}
        for d in set(chunk_dtypes) | set(dtypes):
            cast[d] = vals.astype(d)
        samples = []
        for i in range(got):
            base = i * n_slots
            row = []
            for s in range(n_slots):
                d = chunk_dtypes[s]
                if s in flip_at and i >= flip_at[s]:
                    d = np.float32
                row.append(cast[d][starts[base + s]:ends[base + s]])
            samples.append(row)
        return samples, dtypes

    def _iter_native(self, path) -> Optional[Iterator[List[np.ndarray]]]:
        lib = self._native_lib()
        if lib is None:
            return None

        def gen():
            dtypes = None
            rem = b""
            with open(path, "rb") as f:
                while True:
                    blk = f.read(self._NATIVE_CHUNK)
                    if not blk:
                        if rem.strip():
                            samples, dtypes2 = self._parse_chunk_native(
                                lib, rem, dtypes)
                            yield from samples
                        return
                    buf = rem + blk
                    cut = buf.rfind(b"\n")
                    if cut < 0:
                        rem = buf
                        continue
                    samples, dtypes = self._parse_chunk_native(
                        lib, buf[:cut + 1], dtypes)
                    rem = buf[cut + 1:]
                    yield from samples
        return gen()

    def _iter_samples(self) -> Iterator[List[np.ndarray]]:
        for path in self.filelist:
            native = self._iter_native(path)
            if native is not None:
                yield from native
                continue
            yield from self._iter_python(path)

    def _batches_from(self, samples) -> Iterator[Dict[str, np.ndarray]]:
        names = self._var_names()
        buf: List[List[np.ndarray]] = []
        for s in samples:
            buf.append(s)
            if len(buf) == self.batch_size:
                yield self._collate(names, buf)
                buf = []
        if buf:
            yield self._collate(names, buf)

    @staticmethod
    def _collate(names, buf) -> Dict[str, np.ndarray]:
        out = {}
        for j, name in enumerate(names):
            cols = [s[j] for s in buf]
            width = max(len(c) for c in cols)
            mat = np.zeros((len(cols), width), cols[0].dtype)
            for r, c in enumerate(cols):
                mat[r, :len(c)] = c
            out[name] = mat
        return out

    def get_memory_data_size(self, fleet=None) -> int:
        return 0


class InMemoryDataset(DatasetBase):
    """reference: fleet/dataset/dataset.py InMemoryDataset:455 — load the
    slot files into host RAM, shuffle there, iterate batches."""

    def __init__(self):
        super().__init__()
        self._memory: List[List[np.ndarray]] = []
        self._queue_num = None
        self._shuffle_seed = 0

    def init(self, **kwargs):
        super().init(**kwargs)
        self._queue_num = kwargs.get("queue_num", self.thread_num)

    def update_settings(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, k if not k.startswith("_") else k, v)
            if k == "batch_size":
                self.batch_size = v

    def load_into_memory(self):
        self._memory = list(self._iter_samples())

    def preload_into_memory(self, thread_num=None):
        self.load_into_memory()

    def wait_preload_done(self):
        pass

    def local_shuffle(self):
        rng = random.Random(self._shuffle_seed)
        rng.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single controller: global == local
        self.local_shuffle()

    def release_memory(self):
        self._memory = []

    def get_memory_data_size(self, fleet=None) -> int:
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None) -> int:
        return len(self._memory)

    def set_shuffle_seed(self, seed: int):
        self._shuffle_seed = int(seed)

    def __iter__(self):
        return self._batches_from(iter(self._memory))


class QueueDataset(DatasetBase):
    """reference: fleet/dataset/dataset.py QueueDataset — streaming: files
    are read on the fly, one pass, no shuffle."""

    def __iter__(self):
        return self._batches_from(self._iter_samples())
