"""Model-parallel communication ops.

Re-design of the reference's mp_ops
(reference: python/paddle/distributed/fleet/layers/mpu/mp_ops.py —
_c_identity:91, _c_concat:134, _c_split:196, _mp_allreduce:293, split:714).

The reference implements these as eager NCCL calls with custom backward
rules (identity fwd / allreduce bwd etc.). TPU-native, the same contracts
are expressed as SHARDING transitions on global arrays — XLA GSPMD inserts
the collective (or its transpose in the backward) over the ICI ring:

  _c_identity   : fwd identity,     bwd all-reduce   ≙ replicate -> replicate
                  (GSPMD derives the grad psum from the sharded consumer)
  _mp_allreduce : fwd all-reduce,   bwd identity     ≙ partial   -> replicate
  _c_split      : fwd local slice,  bwd all-gather   ≙ replicate -> Shard(-1)
  _c_concat     : fwd all-gather,   bwd local slice  ≙ Shard(-1) -> replicate

Inside ``shard_map`` (manual-control regime) the same functions fall back to
explicit lax collectives with custom_vjp parity rules.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ....._core.tensor import Tensor
from ....._core import autograd as ag
from .... import mesh as _mesh
from ....mesh import Group, in_mapped_context


def _mp_group(group) -> Group:
    if group is not None:
        return group
    from ...fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    if hcg is not None:
        return hcg.get_model_parallel_group()
    return _mesh.get_world_group()


def _constraint(x, spec, mesh):
    try:
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, spec))
    except Exception:
        return x


def _apply(fn, *tensors, name):
    return ag.apply(fn, *tensors, name=name)


def _c_identity(tensor, group=None, skip_c_identity_dynamic=False):
    """Identity fwd / all-reduce bwd (reference mp_ops.py:91)."""
    g = _mp_group(group)
    if g.nranks == 1:
        return tensor
    if in_mapped_context(g):
        axis = g.axis_names[0]

        @jax.custom_vjp
        def ident(x):
            return x

        def fwd(x):
            return x, None

        def bwd(_, ct):
            return (lax.psum(ct, axis),)

        ident.defvjp(fwd, bwd)
        return _apply(ident, tensor, name="c_identity")
    # GSPMD: consumers' sharded weights produce the grad reduction
    return tensor


def _mp_allreduce(tensor, op=None, group=None, use_calc_stream=True,
                  use_model_parallel=True):
    """All-reduce fwd / identity bwd (reference mp_ops.py:293)."""
    g = _mp_group(group)
    if g.nranks == 1:
        return tensor
    if in_mapped_context(g):
        axis = g.axis_names[0]

        @jax.custom_vjp
        def ar(x):
            return lax.psum(x, axis)

        def fwd(x):
            return lax.psum(x, axis), None

        def bwd(_, ct):
            return (ct,)

        ar.defvjp(fwd, bwd)
        return _apply(ar, tensor, name="mp_allreduce")
    # GSPMD: the partial produced by a row-parallel matmul is reduced by
    # XLA when we constrain the output to replicated over the mp axis.
    return _apply(
        lambda x: _constraint(x, P(), g.mesh), tensor, name="mp_allreduce")


def _c_split(tensor, group=None, axis=-1):
    """Take this rank's slice along ``axis`` (reference mp_ops.py:196)."""
    g = _mp_group(group)
    n = g.nranks
    if n == 1:
        return tensor
    if in_mapped_context(g):
        aname = g.axis_names[0]

        def f(x):
            idx = lax.axis_index(aname)
            size = x.shape[axis] // n
            return lax.dynamic_slice_in_dim(x, idx * size, size, axis)
        return _apply(f, tensor, name="c_split")
    # GSPMD: constrain to sharded along `axis` over the mp mesh axis —
    # the array stays global; each device materializes only its shard.
    nd = tensor.ndim
    ax = axis % nd
    spec = [None] * nd
    spec[ax] = g.axis_names[0]
    return _apply(lambda x: _constraint(x, P(*spec), g.mesh),
                  tensor, name="c_split")


def _c_concat(tensor, group=None, axis=-1):
    """All-gather along ``axis`` (reference mp_ops.py:134)."""
    g = _mp_group(group)
    if g.nranks == 1:
        return tensor
    if in_mapped_context(g):
        aname = g.axis_names[0]
        return _apply(lambda x: lax.all_gather(x, aname, axis=axis % x.ndim,
                                               tiled=True),
                      tensor, name="c_concat")
    return _apply(lambda x: _constraint(x, P(), g.mesh),
                  tensor, name="c_concat")


def _c_softmax_with_cross_entropy(logits, label, group=None,
                                  return_softmax=False):
    """Vocab-parallel softmax-CE (reference mp_ops.py
    _c_softmax_with_cross_entropy). GSPMD computes the global softmax over
    the vocab-sharded logits directly."""
    from .....nn.functional.loss import cross_entropy
    loss = cross_entropy(logits, label, reduction="none", soft_label=False)
    if return_softmax:
        from .....nn.functional.activation import softmax
        return loss, softmax(logits, axis=-1)
    return loss


def reset_split_layer_cache() -> int:
    """Release every layer created by named :func:`split` calls.

    The split cache never evicts on its own (named layers must persist
    like layers held on a module, and each key pins its mesh object
    alive), so a long-lived server or test process that churns meshes
    accumulates dead layers — and their sharded parameters — forever.
    This is the explicit release valve: call it when a mesh generation
    is retired for good. :func:`paddle_tpu.distributed.fleet.init` calls
    it automatically on RE-initialization (a fresh topology starts a
    fresh layer generation); returns the number of evicted layers.

    After a reset, the next named split call re-creates (and
    re-initializes) its layer — don't reset between the construction
    and use of live layers."""
    cache = getattr(split, "_layers", None)
    n = len(cache) if cache else 0
    if cache:
        cache.clear()
    return n


def split(x, size, operation="linear", axis=0, num_partitions=1,
          gather_out=True, weight_attr=None, bias_attr=None, name=None):
    """reference: mp_ops.py:714 paddle.distributed.split — one-shot
    parallel linear/embedding over the model-parallel group.

    - ``operation="embedding"`` (axis must be 0): VocabParallelEmbedding
      over ``size=(vocab, dim)``.
    - ``operation="linear", axis=0``: RowParallelLinear — weight rows
      split; outputs partial-sum-reduced over the mp group.
    - ``operation="linear", axis=1``: ColumnParallelLinear — weight
      columns split; ``gather_out`` gathers the column shards.

    TPU-native: the created layer's weights carry mp shardings and GSPMD
    inserts the collectives. Like the reference, each (unnamed) call
    creates a FRESH layer — split is a model-construction helper, called
    once per projection. Passing ``name`` opts into create-once reuse:
    repeated calls with the same name (and config, and mp group) return
    the same parameters, so split can live inside a per-step forward."""
    from .mp_layers import (ColumnParallelLinear, RowParallelLinear,
                            VocabParallelEmbedding)
    if operation not in ("linear", "embedding"):
        raise ValueError(
            f"distributed.split: operation must be 'linear' or "
            f"'embedding', got {operation!r}")
    if len(tuple(size)) != 2:
        raise ValueError(f"distributed.split: size must be (in, out), "
                         f"got {size}")
    g = _mp_group(None)
    if num_partitions not in (1, max(g.nranks, 1)):
        raise ValueError(
            f"distributed.split: num_partitions={num_partitions} does "
            f"not match the model-parallel degree {max(g.nranks, 1)}")
    cache = getattr(split, "_layers", None)
    if cache is None:
        cache = split._layers = {}
    # the mesh OBJECT is part of the key (jax.sharding.Mesh is hashable;
    # holding it in the key also keeps it alive, so — unlike an id() key —
    # a GC'd-and-reallocated mesh can never collide): a fleet re-init with
    # a different mesh must not resurrect layers sharded over the old one.
    # Attrs are NOT in the key (an inline-constructed ParamAttr would miss
    # every step and re-initialize); instead the creation-time attrs are
    # remembered and a later mismatch warns that attrs only apply at
    # creation.
    key = (name, operation, axis, tuple(size), bool(gather_out),
           bias_attr is not False, g.nranks, g.mesh)
    entry = cache.get(key) if name is not None else None
    if name is not None and entry is None and \
            any(k[0] == name and k != key for k in cache):
        import warnings
        warnings.warn(
            f"distributed.split(name={name!r}): called with a DIFFERENT "
            "config than an existing layer of the same name — a second "
            "parameter set will be created for this config. If this is "
            "the per-step forward of a layer built at construction time, "
            "the configs must match exactly for reuse.", stacklevel=2)
    if entry is not None:
        layer, w0, b0 = entry
        if w0 is not weight_attr or b0 is not bias_attr:
            import warnings
            warnings.warn(
                f"distributed.split(name={name!r}): weight_attr/bias_attr "
                "differ from the layer's creation-time attrs and are "
                "ignored — attrs only apply when the named layer is first "
                "created", stacklevel=2)
    else:
        if operation == "embedding":
            if axis != 0:
                raise ValueError(
                    "distributed.split(embedding): only axis=0 is "
                    "supported (vocab-dimension split), got "
                    f"axis={axis}")
            layer = VocabParallelEmbedding(size[0], size[1],
                                           weight_attr=weight_attr,
                                           name=name)
        elif axis == 0:
            layer = RowParallelLinear(size[0], size[1],
                                      weight_attr=weight_attr,
                                      bias_attr=(None if bias_attr is False
                                                 else bias_attr),
                                      has_bias=bias_attr is not False,
                                      input_is_parallel=False, name=name)
        elif axis == 1:
            layer = ColumnParallelLinear(size[0], size[1],
                                         weight_attr=weight_attr,
                                         bias_attr=(None if bias_attr is
                                                    False else bias_attr),
                                         has_bias=bias_attr is not False,
                                         gather_output=gather_out,
                                         name=name)
        else:
            raise ValueError(
                f"distributed.split(linear): axis must be 0 (row "
                f"parallel) or 1 (column parallel), got {axis}")
        if name is not None:
            # NO automatic eviction: named layers persist like layers
            # held on a module — a process that alternates meshes within
            # one fleet generation must find its named layers again
            # under each, and any eviction policy here silently
            # re-initializes trained weights for whichever mesh it
            # evicts. The release valve is EXPLICIT:
            # reset_split_layer_cache(), called by fleet.init on
            # RE-initialization, so servers/tests that churn meshes
            # don't leak dead layers' sharded parameters.
            cache[key] = (layer, weight_attr, bias_attr)
    return layer(x)
