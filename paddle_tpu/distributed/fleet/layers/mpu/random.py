"""Per-parallel-stream RNG state tracking.

Re-design of the reference's RNGStatesTracker
(reference: python/paddle/distributed/fleet/layers/mpu/random.py:34). The
reference snapshots/restores CUDA generator state per named stream so that
e.g. dropout inside TP layers is identical across TP ranks ("local_seed")
while DP ranks differ ("global_seed"). Stateless-PRNG equivalent: each named
stream owns a key-splitting Generator; the context manager routes draws to
it. Under jit the train-step wrapper threads traced keys instead (see
_core/random.py rng_scope) and folds in the mesh axis index for per-rank
streams.
"""
from __future__ import annotations

import contextlib
from typing import Dict

from ....._core import random as _random

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_: Dict[str, _random.Generator] = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name: str, seed: int):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.seeds_.add(seed)
        self.states_[name] = _random.Generator(seed)

    def get_states_tracker(self):
        return {n: g.get_state() for n, g in self.states_.items()}

    def set_states_tracker(self, states):
        for n, s in states.items():
            if n in self.states_:
                self.states_[n].set_state(s)

    @contextlib.contextmanager
    def rng_state(self, name: str = MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        with _random.use_generator(self.states_[name]):
            yield


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed: int = 2023):
    """reference: mpu/random.py model_parallel_random_seed — derive
    distinct local/global seeds per mp rank. Single-controller: mp-rank
    folding happens inside traced programs; here we install the two named
    streams the reference uses."""
    from ...fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    mp_rank = hcg.get_model_parallel_rank() if hcg is not None else 0
    global_seed = seed
    local_seed = seed + 1024 + mp_rank
    tracker = get_rng_state_tracker()
    tracker.reset()
    tracker.add("global_seed", global_seed)
    tracker.add("local_seed", local_seed)
    _random.seed(global_seed)


def determinate_seed(name: str) -> int:
    g = _RNG_STATE_TRACKER.states_.get(name)
    return g.initial_seed() if g else 0
