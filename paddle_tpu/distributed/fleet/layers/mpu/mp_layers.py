"""Megatron-style tensor-parallel layers.

Re-design of the reference's mp_layers
(reference: python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:49, ColumnParallelLinear:336, RowParallelLinear:543,
ParallelCrossEntropy:744).

The reference stores the LOCAL weight shard per rank and calls explicit
NCCL collectives. TPU-native inversion: each layer stores the GLOBAL weight
annotated with a NamedSharding over the ``mp`` mesh axis —

  VocabParallelEmbedding : weight  P('mp', None)   (vocab rows sharded)
  ColumnParallelLinear   : weight  P(None, 'mp')   (output cols sharded)
  RowParallelLinear      : weight  P('mp', None)   (input rows sharded)

and lets GSPMD place the matmul shards on the MXU and insert the reduce /
gather over ICI. ``gather_output=False`` / ``input_is_parallel=True`` become
output/input sharding constraints, so chained Column->Row pairs keep the
activation sharded between them exactly like the reference keeps it local.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ....._core.tensor import Tensor
from ....._core import autograd as ag
from ....._core import dtype as dtypes
from .....nn.layer.layers import Layer
from .....nn import functional as F
from ....mesh import Group, in_mapped_context
from . import mp_ops


def _mp_group(mp_group) -> Group:
    return mp_ops._mp_group(mp_group)


def _shard_param(p: Tensor, mesh, spec):
    """Lay out a parameter's global value over the mesh."""
    try:
        p._inplace_assign(jax.device_put(p._value,
                                         NamedSharding(mesh, spec)))
    except Exception:
        pass  # mesh may be unavailable in pure-eager unit tests
    return p


class VocabParallelEmbedding(Layer):
    """reference: mpu/mp_layers.py:49."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self._group = _mp_group(mp_group)
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr)
        if self._group.nranks > 1:
            _shard_param(self.weight, self._group.mesh,
                         P(self._group.axis_names[0], None))

    def forward(self, x):
        return F.embedding(x, self.weight)


class ColumnParallelLinear(Layer):
    """reference: mpu/mp_layers.py:336."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, bias_attr=None):
        super().__init__()
        self._group = _mp_group(mp_group)
        self.gather_output = gather_output
        n = self._group.nranks
        if out_features % max(n, 1) != 0:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree {n}")
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        # bias gets its OWN attr (default zero-init like Megatron;
        # reference mp_layers.py:442 Constant(0.0)) — never weight_attr,
        # whose initializer expects the weight's 2-D shape
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True) if has_bias else None
        if n > 1:
            ax = self._group.axis_names[0]
            _shard_param(self.weight, self._group.mesh, P(None, ax))
            if self.bias is not None:
                _shard_param(self.bias, self._group.mesh, P(ax))

    def forward(self, x):
        x = mp_ops._c_identity(x, self._group)
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = mp_ops._c_concat(out, self._group, axis=-1)
        elif self._group.nranks > 1 and not in_mapped_context(self._group):
            ax = self._group.axis_names[0]
            spec = [None] * out.ndim
            spec[-1] = ax
            out = ag.apply(
                lambda v: mp_ops._constraint(v, P(*spec), self._group.mesh),
                out, name="col_parallel_out")
        return out


class RowParallelLinear(Layer):
    """reference: mpu/mp_layers.py:543."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None,
                 bias_attr=None):
        super().__init__()
        self._group = _mp_group(mp_group)
        self.input_is_parallel = input_is_parallel
        n = self._group.nranks
        if in_features % max(n, 1) != 0:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree {n}")
        self.weight = self.create_parameter([in_features, out_features],
                                            attr=weight_attr)
        # bias is NOT sharded and added after the reduce (reference keeps a
        # full bias on every rank and adds post-allreduce); it gets its OWN
        # attr (default zero-init, reference mp_layers.py:678) — never
        # weight_attr, whose initializer expects the weight's 2-D shape
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True) if has_bias else None
        if n > 1:
            _shard_param(self.weight, self._group.mesh,
                         P(self._group.axis_names[0], None))

    def forward(self, x):
        if not self.input_is_parallel:
            x = mp_ops._c_split(x, self._group, axis=-1)
        out = F.linear(x, self.weight, None)
        out = mp_ops._mp_allreduce(out, group=self._group)
        if self.bias is not None:
            out = out + self.bias
        return out


class ParallelCrossEntropy(Layer):
    """reference: mpu/mp_layers.py:744 — vocab-parallel softmax CE. GSPMD
    computes the stable global softmax over vocab-sharded logits (the
    reference's max/sum allreduce pair) automatically."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self._group = _mp_group(mp_group)
        self.ignore_index = ignore_index

    def forward(self, input, label):
        return mp_ops._c_softmax_with_cross_entropy(
            input, label, group=self._group)
