"""SPMD pipeline parallelism: stacked stages + ppermute rotation.

This is the TPU-native execution of pipeline parallelism — the counterpart
of the reference's multi-process 1F1B engine
(reference: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:575 forward_backward_pipeline + pp_utils/
p2p_communication.py eager NCCL p2p). The reference pipelines across
*processes*; XLA pipelines across *mesh coordinates inside one program*:

- Each coordinate of the ``pp`` mesh axis holds ONE stage's weights: every
  homogeneous-stage parameter is stacked with a leading ``[num_stages]``
  axis sharded over ``pp``.
- A ``lax.scan`` runs M + P - 1 ticks. Per tick each stage applies its
  layer block, then activations rotate one hop along the pp ring via
  ``lax.ppermute`` (ICI neighbour traffic only). Stage 0 feeds a fresh
  microbatch each tick; stage P-1 emits a finished microbatch from tick
  P-1 on — the classic GPipe wavefront.
- Differentiating through the scan + ppermute gives the reverse wavefront
  (ppermute transposes to the opposite rotation, scan reverses time): the
  backward pipeline the reference hand-schedules falls out of AD.
- Other mesh axes (dp/mp/...) are listed outside ``axis_names`` so GSPMD
  keeps auto-sharding them inside the manual pp program (jax.shard_map
  partial-manual mode).

Four schedules, mirroring the reference's set (reference:
meta_parallel/pipeline_parallel.py:575 1F1B, :1174 interleaved VPP, :2256
FThenB; passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:62):

- ``gpipe`` (``pipeline_spmd``): forward wavefront scan; AD reverses it.
  Bubble (P-1)/(M+P-1); activation residency grows with M (all in-flight
  microbatch residuals live until the backward wavefront).
- ``interleave`` (``pipeline_interleave``): each pp coordinate holds
  ``num_chunks`` non-adjacent virtual stages (Megatron VPP); microbatches
  lap the ring num_chunks times. Bubble shrinks to
  (P-1)/(M*num_chunks + P-1) at GPipe-like residency.
- ``1f1b`` (``pipeline_1f1b``): ONE combined scan runs the forward and the
  hand-written backward concurrently; stage inputs live in a (2P-1)-slot
  ring carried through the scan, so activation residency is bounded by the
  pipeline depth — NOT by M. This is the reference 1F1B's memory contract;
  under lockstep SPMD it costs ~P extra ticks vs gpipe, the price of
  in-scan backward. Backward recomputes the stage forward from the saved
  input (remat), the same tradeoff the big configs already take.
- ``zero_bubble`` (``pipeline_1f1b(defer_dw=True)``): 1F1B structure but
  the per-tick backward computes only dX (the serial dependency); dW
  matmuls are hoisted out of the scan into a scan-accumulated pass over
  the stashed (input, cotangent) pairs — the XLA translation of
  zero-bubble's "fill bubbles with W-grad work": the serialized chain per
  tick drops from fwd+dX+dW to fwd+dX, at gpipe-like stash memory. The
  dW tail accumulates via lax.scan, NOT vmap: a vmapped tail
  materializes T full dW trees at once (AOT-measured 307 GB temp on the
  13B recipe vs 27 GB for 1f1b).
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(per_stage_params: Sequence[Any], mesh: Mesh,
                       pp_axis: str = "pp"):
    """Stack per-stage pytrees into leading-[P] arrays sharded over pp."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0),
                           *per_stage_params)

    def place(x):
        spec = [pp_axis] + [None] * (x.ndim - 1)
        try:
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        except Exception:
            return x
    return jax.tree.map(place, stacked)


def _psum_act(x, pp_axis: str, mesh: Mesh):
    """psum for activation-sized tensors. On CPU meshes the all-reduce
    runs in f32: XLA's CPU AllReducePromotion pass CHECK-crashes cloning
    a bf16 all-reduce whose reducer carries a copy ("Invalid binary
    instruction opcode copy", hlo_instruction.cc:1585 — observed
    AOT-compiling the 13B bf16 recipe on the 16-device CPU mesh; TPU
    backends never run that pass). Native-dtype psum is kept on TPU so
    the collective rides ICI at bf16 bytes.

    THE SAME XLA BUG has two workarounds in this repo — this is the
    canonical inventory so one can be retired when upstream fixes the
    CHECK:

    1. **This f32 upcast** — covers every bf16 activation psum the
       SPMD pipeline entry points emit EXPLICITLY (``pipeline_spmd``,
       ``pipeline_spmd_grad``, ``pipeline_spmd_hetero``, and the
       interleave forward), i.e. all in-process CPU-mesh runs: tier-1
       tests, the 16-device CPU smoke meshes, eager fleet engines.
    2. **The XLA-flag disable** (``tools/aot_validate.py`` child env:
       ``--xla_disable_hlo_passes=all-reduce-promotion``) — needed
       because the interleave-schedule AD graph also contains
       GSPMD-INSERTED bf16 all-reduces that never route through this
       helper, so the upcast can't reach them; bf16 all-reduces compile
       and run correctly on CPU with the pass off.

    Retirement order once the upstream CHECK is fixed: drop (1) first
    (native bf16 everywhere, this helper becomes plain ``lax.psum``),
    then (2); keep them in lockstep with this docstring. Set
    ``PADDLE_TPU_NATIVE_BF16_PSUM=1`` to bypass the upcast early and
    probe whether the installed XLA still crashes."""
    import os
    if mesh.devices.flat[0].platform == "cpu" and \
            x.dtype == jnp.bfloat16 and \
            not os.environ.get("PADDLE_TPU_NATIVE_BF16_PSUM"):
        return lax.psum(x.astype(jnp.float32), pp_axis).astype(x.dtype)
    return lax.psum(x, pp_axis)


def pipeline_spmd(stage_fn: Callable, stacked_params, microbatches,
                  mesh: Mesh, pp_axis: str = "pp",
                  last_fn: Optional[Callable] = None):
    """Run the GPipe wavefront over the pp axis.

    stage_fn(stage_params, x) -> y         (uniform across stages)
    stacked_params: pytree, leading dim [P] sharded over pp_axis
    microbatches:   [M, mb, ...] input activations for stage 0
    last_fn(y) -> z (optional): applied to finished microbatches
    returns [M, ...] outputs of the last stage.
    """
    num_stages = mesh.shape[pp_axis]
    M = microbatches.shape[0]
    T = M + num_stages - 1
    manual = frozenset({pp_axis})

    def per_device(params_local, mb_local):
        # params_local: my stage's params (leading dim 1) ; squeeze it
        params_me = jax.tree.map(lambda x: x[0], params_local)
        stage_id = lax.axis_index(pp_axis)
        perm_fwd = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        x0 = jnp.zeros_like(mb_local[0])

        def tick(carry, t):
            recv = carry
            feed = mb_local[jnp.minimum(t, M - 1)]
            x_in = jnp.where(stage_id == 0, feed, recv)
            y = stage_fn(params_me, x_in)
            nxt = lax.ppermute(y, pp_axis, perm_fwd)
            return nxt, y

        _, ys = lax.scan(tick, x0, jnp.arange(T))
        # finished microbatches leave the last stage at ticks [P-1, T-1]
        outs = lax.dynamic_slice_in_dim(ys, num_stages - 1, M, axis=0)
        # broadcast last-stage outputs to all pp coords so the result is
        # replicated over pp (callers compute loss once)
        mask = (stage_id == num_stages - 1).astype(outs.dtype)
        outs = _psum_act(outs * mask, pp_axis, mesh)
        return outs

    fn = jax.shard_map(
        per_device, mesh=mesh, axis_names=manual,
        in_specs=(jax.tree.map(lambda _: P(pp_axis), stacked_params), P()),
        out_specs=P(), check_vma=False)
    outs = fn(stacked_params, microbatches)
    if last_fn is not None:
        outs = jax.vmap(last_fn)(outs)
    return outs


def pipeline_loss_spmd(stage_fn: Callable, loss_fn: Callable,
                       stacked_params, head_params, microbatches, labels,
                       mesh: Mesh, pp_axis: str = "pp"):
    """Pipeline + per-microbatch loss, averaged — the training objective.

    loss_fn(head_params, y, label) -> scalar loss for one microbatch.
    Returns mean loss over microbatches; differentiable w.r.t. both
    stacked_params and head_params.
    """
    outs = pipeline_spmd(stage_fn, stacked_params, microbatches, mesh,
                         pp_axis)
    losses = jax.vmap(lambda y, l: loss_fn(head_params, y, l))(outs, labels)
    return jnp.mean(losses)


def stack_stage_params_interleaved(per_stage_params: Sequence[Any],
                                   mesh: Mesh, num_chunks: int,
                                   pp_axis: str = "pp"):
    """Stack V = P*num_chunks virtual-stage pytrees into [P, num_chunks, ...]
    arrays (virtual stage s lives on device s % P as chunk s // P — the
    Megatron round-robin layout), dim 0 sharded over pp."""
    P_ = mesh.shape[pp_axis]
    V = P_ * num_chunks
    assert len(per_stage_params) == V
    rows = []
    for d in range(P_):
        chunks = [per_stage_params[c * P_ + d] for c in range(num_chunks)]
        rows.append(jax.tree.map(lambda *xs: jnp.stack(xs, 0), *chunks))
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, 0), *rows)

    def place(x):
        spec = [pp_axis] + [None] * (x.ndim - 1)
        try:
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        except Exception:
            return x
    return jax.tree.map(place, stacked)


def pipeline_interleave(stage_fn: Callable, stacked_params, microbatches,
                        mesh: Mesh, num_chunks: int, pp_axis: str = "pp"):
    """Interleaved (VPP) wavefront: V = P*num_chunks virtual stages laid
    out round-robin; the Megatron interleaved schedule in closed form.

    Device d at tick t serves coordinate u = t - d, decomposed
    u = g*(v*P) + c*P + r  ->  chunk c, microbatch m = g*P + r.
    This is a per-device bijection (each device busy every steady tick) and
    every virtual stage's output is consumed by the next ring device exactly
    one tick later — so a single ppermute carries all traffic and the
    wavefront finishes in T = M*num_chunks + P - 1 ticks: bubble
    (P-1)/(M*v + P-1), the VPP contract. Requires M % P == 0 (Megatron's
    constraint, reference: meta_parallel/pipeline_parallel.py:1174).

    stage_fn(chunk_params, x) -> y        (uniform across virtual stages)
    stacked_params: pytree [P, num_chunks, ...], dim 0 sharded over pp
    microbatches:   [M, mb, ...] stage-0 inputs
    returns [M, ...] outputs of the last virtual stage. Differentiable.
    """
    num_stages = mesh.shape[pp_axis]
    M = microbatches.shape[0]
    assert M % num_stages == 0, (
        f"interleaved schedule needs microbatches ({M}) % pp stages "
        f"({num_stages}) == 0")
    vP = num_stages * num_chunks
    T = M * num_chunks + num_stages - 1
    manual = frozenset({pp_axis})

    def per_device(params_local, mb_local):
        params_me = jax.tree.map(lambda x: x[0], params_local)  # [v, ...]
        stage = lax.axis_index(pp_axis)
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
        x0 = jnp.zeros_like(mb_local[0])
        out0 = jnp.zeros((M,) + mb_local.shape[1:], mb_local.dtype)

        def tick(carry, t):
            x_rc, out_buf = carry
            u = t - stage
            g = jnp.where(u >= 0, u // vP, 0)
            rem = jnp.clip(u - g * vP, 0, vP - 1)
            c = rem // num_stages
            m = jnp.clip(g * num_stages + rem % num_stages, 0, M - 1)
            active = (u >= 0) & (u < M * num_chunks)

            feed = lax.dynamic_index_in_dim(mb_local, m, 0, keepdims=False)
            x_in = jnp.where((stage == 0) & (c == 0), feed, x_rc)
            p_c = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                params_me)
            y = stage_fn(p_c, x_in)
            y = jnp.where(active, y, x_in)

            emit = active & (stage == num_stages - 1) & (c == num_chunks - 1)
            upd = lax.dynamic_update_index_in_dim(
                out_buf, y.astype(out_buf.dtype), m, 0)
            out_buf = jnp.where(emit, upd, out_buf)

            x_nx = lax.ppermute(y, pp_axis, perm)
            return (x_nx, out_buf), None

        (_, outs), _ = lax.scan(tick, (x0, out0), jnp.arange(T))
        # out_buf is populated only on the last stage; replicate over pp
        mask = (stage == num_stages - 1).astype(outs.dtype)
        return _psum_act(outs * mask, pp_axis, mesh)

    fn = jax.shard_map(
        per_device, mesh=mesh, axis_names=manual,
        in_specs=(jax.tree.map(lambda _: P(pp_axis), stacked_params), P()),
        out_specs=P(), check_vma=False)
    return fn(stacked_params, microbatches)


def _interleave_1f1b_core(apply_chunk, stacked_vec, head_params,
                          microbatches, labels, mesh: Mesh,
                          num_chunks: int, pp_axis: str, loss_fn,
                          vec_spec, defer_dw: bool = False):
    """Shared combined fwd+bwd scan for the interleaved (VPP) 1F1B
    schedule — the closed forms documented on pipeline_interleave_1f1b.
    ``apply_chunk(params_me, c, x, d)`` applies this device's virtual
    stage of chunk ``c``; ``vec_spec`` is the shard_map pytree-prefix
    spec for the stacked carrier (and its gradient). ``defer_dw`` is the
    ZB-V composition: the per-tick backward emits only dX, and dW
    accumulates in a scan-accumulated tail over the stashed (input,
    cotangent, chunk) triples — the zero-bubble contract at the VPP
    bubble, with O(1) dW memory like pipeline_1f1b's defer_dw."""
    num_stages = mesh.shape[pp_axis]
    C = num_chunks
    V = num_stages * C
    M = microbatches.shape[0]
    assert M % num_stages == 0, (
        f"interleaved schedule needs microbatches ({M}) % pp stages "
        f"({num_stages}) == 0")
    U = M * C
    T = U + V + num_stages - 2
    R = 2 * V - 1
    manual = frozenset({pp_axis})
    inv_m = 1.0 / M

    def per_device(vec_local, head, mb_local, lab_local):
        vec_me = jax.tree.map(lambda a: a[0], vec_local)
        d = lax.axis_index(pp_axis)
        P_ = num_stages
        last = P_ - 1
        perm_f = [(i, (i + 1) % P_) for i in range(P_)]
        perm_b = [(i, (i - 1) % P_) for i in range(P_)]

        zero_x = jnp.zeros_like(mb_local[0])
        ring0 = jnp.zeros((R,) + zero_x.shape, zero_x.dtype)
        dw0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                           vec_me)
        dhead0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                              head)
        dx0 = jnp.zeros((M,) + zero_x.shape, jnp.float32)

        def tick(carry, t):
            (f_rc, b_rc, ring, dw, dhead, dx_out, loss_acc) = carry

            # ---- forward unit u = t - d ----
            u = t - d
            f_on = (u >= 0) & (u < U)
            uc = jnp.clip(u, 0, U - 1)
            g_f = uc // V
            rem_f = uc - g_f * V
            c_f = rem_f // P_
            m_f = jnp.clip(g_f * P_ + rem_f % P_, 0, M - 1)
            feed = lax.dynamic_index_in_dim(mb_local, m_f, 0,
                                            keepdims=False)
            x_in = jnp.where((d == 0) & (c_f == 0), feed, f_rc)
            y = apply_chunk(vec_me, c_f, x_in, d)
            ring = jnp.where(
                f_on,
                lax.dynamic_update_index_in_dim(ring, x_in,
                                                jnp.mod(t, R), 0),
                ring)

            # head loss + cotangent on the LAST virtual stage's output.
            # Gated behind ``on_last`` (ADVICE r5): only the last
            # device's last chunk ever uses these values — off-tick
            # lanes previously paid a full head forward+backward (a
            # vocab-sized matmul pair at LM shapes) per tick just to be
            # masked to zero. ``lax.cond`` evaluates the cheap
            # zeros branch instead on every other (device, tick).
            # Parity: the only OFF-tick consumer is ``dy_self`` via the
            # ``(d == last) & (c_b == C-1)`` select below, and at the
            # ticks where that backward is ON its unit coincides with
            # this tick's forward unit (u_b == u), which makes the
            # predicate equal to ``on_last`` — so a live path never
            # reads the zeros.
            lab = jax.tree.map(
                lambda l: lax.dynamic_index_in_dim(l, m_f, 0,
                                                   keepdims=False),
                lab_local)
            on_last = f_on & (d == last) & (c_f == C - 1)

            def _head_eval(hp, yy):
                lval, head_vjp = jax.vjp(
                    lambda h, yo: loss_fn(h, yo, lab), hp, yy)
                dhead_c, dy_self = head_vjp(
                    jnp.asarray(inv_m, jnp.float32))
                return lval, dhead_c, dy_self

            lval, dhead_c, dy_self = lax.cond(
                on_last, _head_eval,
                lambda hp, yy: jax.tree.map(
                    lambda s: jnp.zeros(s.shape, s.dtype),
                    jax.eval_shape(_head_eval, hp, yy)),
                head, y)
            loss_acc = loss_acc + jnp.where(on_last, lval, 0.0)
            dhead = jax.tree.map(
                lambda acc, g: acc + jnp.where(on_last, g, 0.0),
                dhead, dhead_c)

            # ---- backward unit w = t - (V-1) - (P-1-d) ----
            w = t - (V - 1) - (last - d)
            b_on = (w >= 0) & (w < U)
            wc = jnp.clip(w, 0, U - 1)
            g_b = wc // V
            rem_b = wc - g_b * V
            c_b = C - 1 - rem_b // P_
            # forward of this unit ran here at tick u_b + d
            u_b = g_b * V + c_b * P_ + rem_b % P_
            slot_b = jnp.mod(u_b + d, R)
            x_sv = lax.dynamic_index_in_dim(ring, slot_b, 0,
                                            keepdims=False)
            dy_in = jnp.where((d == last) & (c_b == C - 1),
                              dy_self.astype(b_rc.dtype), b_rc)
            _, stage_vjp = jax.vjp(
                lambda vme, xx: apply_chunk(vme, c_b, xx, d), vec_me,
                x_sv)
            # vjp through dynamic_index scatters into a full-size tree
            # (zeros off-chunk), so plain accumulation lands the chunk's
            # grads without any indexed add
            dv_c, dx_c = stage_vjp(dy_in)
            if not defer_dw:
                dw = jax.tree.map(
                    lambda acc, g: acc + jnp.where(b_on,
                                                   g.astype(jnp.float32),
                                                   0.0),
                    dw, dv_c)
            m_b = jnp.clip(g_b * P_ + rem_b % P_, 0, M - 1)
            dx_out = jnp.where(
                b_on & (d == 0) & (c_b == 0),
                lax.dynamic_update_index_in_dim(
                    dx_out, dx_c.astype(jnp.float32), m_b, 0),
                dx_out)

            f_nx = lax.ppermute(y, pp_axis, perm_f)
            b_nx = lax.ppermute(dx_c.astype(b_rc.dtype), pp_axis, perm_b)
            stash = (x_sv, dy_in, b_on, c_b) if defer_dw else None
            return (f_nx, b_nx, ring, dw, dhead, dx_out, loss_acc), stash

        init = (zero_x, jnp.zeros_like(zero_x), ring0, dw0, dhead0,
                dx0, jnp.float32(0.0))
        (_, _, _, dw, dhead, dx_out, loss_acc), stash = lax.scan(
            tick, init, jnp.arange(T))

        if defer_dw:
            # scan-accumulated dW tail (NOT vmap — see pipeline_1f1b's
            # defer_dw note: a vmapped tail materializes T dW trees)
            xs, dys, mask, cs = stash

            def acc_one(acc, xdmc):
                x_sv, dy, on, c = xdmc
                _, vjp = jax.vjp(
                    lambda vme, xx: apply_chunk(vme, c, xx, d), vec_me,
                    x_sv)
                dv = vjp(dy)[0]
                return jax.tree.map(
                    lambda a, g: a + jnp.where(on, g.astype(jnp.float32),
                                               0.0), acc, dv), None
            dw, _ = lax.scan(acc_one, dw, (xs, dys, mask, cs))

        lastf = (d == last).astype(jnp.float32)
        loss_mean = lax.psum(loss_acc * lastf, pp_axis) * inv_m
        dhead = jax.tree.map(lambda g: lax.psum(g * lastf, pp_axis), dhead)
        dx_out = lax.psum(
            dx_out * (d == 0).astype(jnp.float32), pp_axis)
        return loss_mean, jax.tree.map(lambda a: a[None], dw), dhead, \
            dx_out

    fn = jax.shard_map(
        per_device, mesh=mesh, axis_names=manual,
        in_specs=(vec_spec, P(), P(), P()),
        out_specs=(P(), vec_spec, P(), P()),
        check_vma=False)
    return fn(stacked_vec, head_params, microbatches, labels)


def pipeline_interleave_1f1b(stage_fn: Callable, loss_fn: Callable,
                             stacked_params, head_params, microbatches,
                             labels, mesh: Mesh, num_chunks: int,
                             pp_axis: str = "pp",
                             defer_dw: bool = False):
    """Interleaved (VPP) schedule with a HAND-WRITTEN depth-bounded
    backward — the memory contract of ``pipeline_1f1b`` at the bubble of
    ``pipeline_interleave``.

    Motivation (round-5 AOT sweep, PERF_NOTES): AD through the interleave
    wavefront keeps every in-flight microbatch residual alive until the
    reverse wavefront — 223 GB/chip on the 13B recipe. Here the combined
    scan runs one forward AND one backward VIRTUAL-STAGE unit per tick
    (the shared ``_interleave_1f1b_core``), stashing only raw stage
    inputs in a (2V-1)-slot ring (V = P*C virtual stages), so activation
    residency is bounded by the virtual pipeline depth — NOT by M —
    while the bubble stays the VPP (P-1)/(M*C + P-1) class. This is the
    TPU lockstep translation of Megatron's interleaved 1F1B (reference:
    meta_parallel/pipeline_parallel.py:1174
    forward_backward_pipeline_with_interleaving).

    Schedule closed forms (d = device, t = tick, requires M % P == 0):
    - forward: unit u = t - d; u = g*V + c*P + r -> chunk c,
      microbatch m = g*P + r. Output ppermutes d -> d+1 (wrap P-1 -> 0
      carries chunk c's exit into chunk c+1's entry), consumed next tick.
    - backward: unit w = t - (V-1) - (P-1-d); w = g*V + q*P + r ->
      chunk c = C-1 - q, microbatch m = g*P + r. Cotangent ppermutes
      d -> d-1 (wrap 0 -> P-1 carries chunk c+1's entry-grad back to
      chunk c's exit), consumed next tick. The first backward (v = V-1)
      consumes the same-tick head-loss cotangent, as in pipeline_1f1b.
    - the stash ring holds stage INPUTS by forward tick mod (2V-1); the
      backward of a unit forward-run at tick t_f reads slot t_f mod R,
      and max(t_b - t_f) = 2V - 2 < R, so no slot is overwritten early.
      Backward recomputes the stage forward from the saved input (remat).

    stage_fn(chunk_params, x) -> y; loss_fn(head_params, y, label) ->
    scalar (per-microbatch, scaled by 1/M here).
    stacked_params: pytree [P, num_chunks, ...] round-robin layout
    (virtual stage v at [v % P, v // P]), dim 0 sharded over pp.
    Returns (mean_loss, d_stacked [P, num_chunks, ...] f32, d_head,
    d_microbatches) — gradients accumulate in f32.
    """
    def apply_chunk(vme, c, x, d):
        p_c = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
            vme)
        return stage_fn(p_c, x)

    return _interleave_1f1b_core(
        apply_chunk, stacked_params, head_params, microbatches, labels,
        mesh, num_chunks, pp_axis, loss_fn,
        jax.tree.map(lambda _: P(pp_axis), stacked_params),
        defer_dw=defer_dw)



def pipeline_1f1b(stage_fn: Callable, loss_fn: Callable, stacked_params,
                  head_params, microbatches, labels, mesh: Mesh,
                  pp_axis: str = "pp", defer_dw: bool = False):
    """Combined forward/backward 1F1B scan with depth-bounded residency.

    stage_fn(stage_params, x) -> y           (uniform across stages)
    loss_fn(head_params, y, label) -> scalar (per-microbatch mean loss)
    stacked_params: pytree [P, ...] sharded over pp_axis
    head_params:    replicated pytree (final norm / head weights)
    microbatches:   [M, mb, ...]; labels: [M, ...]

    Returns (mean_loss, d_stacked_params, d_head_params, d_microbatches) —
    the hand-written pipeline VJP: stage i runs fwd of microbatch m at tick
    i+m and bwd at tick 2(P-1)-i+m, stage inputs parked in a (2P-1)-slot
    ring carried through the scan (activation residency ~2P, independent of
    M). With defer_dw (zero-bubble), the in-scan backward emits only dX and
    the stashed (x, dy) pairs; dW is one batched vjp after the scan.
    """
    num_stages = mesh.shape[pp_axis]
    M = microbatches.shape[0]
    T = M + 2 * num_stages - 2
    R = 2 * num_stages - 1
    manual = frozenset({pp_axis})
    inv_m = 1.0 / M

    def per_device(params_local, head, mb_local, lab_local):
        params_me = jax.tree.map(lambda x: x[0], params_local)
        stage = lax.axis_index(pp_axis)
        last = num_stages - 1
        perm_f = [(i, (i + 1) % num_stages) for i in range(num_stages)]
        perm_b = [(i, (i - 1) % num_stages) for i in range(num_stages)]

        zero_x = jnp.zeros_like(mb_local[0])
        ring0 = jnp.zeros((R,) + zero_x.shape, zero_x.dtype)
        dwsum0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                              params_me)
        dhead0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                              head)
        dx0 = jnp.zeros((M,) + zero_x.shape, jnp.float32)

        def tick(carry, t):
            (f_rc, b_rc, ring, dw, dhead, dx_out, loss_acc) = carry

            # ---- forward slot: stage i runs microbatch m_f = t - i ----
            m_f = t - stage
            f_on = (m_f >= 0) & (m_f < M)
            feed = lax.dynamic_index_in_dim(
                mb_local, jnp.clip(m_f, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(stage == 0, feed, f_rc)
            y = stage_fn(params_me, x_in)
            slot_f = jnp.mod(t, R)
            ring = jnp.where(
                f_on,
                lax.dynamic_update_index_in_dim(ring, x_in, slot_f, 0),
                ring)

            # last stage: per-microbatch loss + cotangent, scaled by 1/M
            lab = jax.tree.map(
                lambda l: lax.dynamic_index_in_dim(
                    l, jnp.clip(m_f, 0, M - 1), 0, keepdims=False),
                lab_local)
            lval, head_vjp = jax.vjp(lambda hp, yy: loss_fn(hp, yy, lab),
                                     head, y)
            dhead_c, dy_self = head_vjp(jnp.asarray(inv_m, jnp.float32))
            on_last = f_on & (stage == last)
            loss_acc = loss_acc + jnp.where(on_last, lval, 0.0)
            dhead = jax.tree.map(
                lambda acc, g: acc + jnp.where(on_last, g, 0.0),
                dhead, dhead_c)

            # ---- backward slot: stage i runs m_b = t - (2P-2-i) ----
            m_b = t - (2 * last - stage)
            b_on = (m_b >= 0) & (m_b < M)
            # fwd of m_b on this stage happened at tick stage + m_b
            slot_b = jnp.mod(stage + jnp.clip(m_b, 0, M - 1), R)
            x_sv = lax.dynamic_index_in_dim(ring, slot_b, 0, keepdims=False)
            dy_in = jnp.where(stage == last, dy_self.astype(b_rc.dtype),
                              b_rc)
            _, stage_vjp = jax.vjp(stage_fn, params_me, x_sv)
            dp_c, dx_c = stage_vjp(dy_in)
            if not defer_dw:
                dw = jax.tree.map(
                    lambda acc, g: acc + jnp.where(b_on, g, 0.0).astype(
                        jnp.float32),
                    dw, dp_c)
            dx_out = jnp.where(
                b_on & (stage == 0),
                lax.dynamic_update_index_in_dim(
                    dx_out, dx_c.astype(jnp.float32),
                    jnp.clip(m_b, 0, M - 1), 0),
                dx_out)

            f_nx = lax.ppermute(y, pp_axis, perm_f)
            b_nx = lax.ppermute(dx_c.astype(b_rc.dtype), pp_axis, perm_b)
            stash = (x_sv, dy_in, b_on) if defer_dw else None
            return (f_nx, b_nx, ring, dw, dhead, dx_out, loss_acc), stash

        init = (zero_x, jnp.zeros_like(zero_x), ring0, dwsum0, dhead0,
                dx0, jnp.float32(0.0))
        (_, _, _, dw, dhead, dx_out, loss_acc), stash = lax.scan(
            tick, init, jnp.arange(T))

        if defer_dw:
            # dW AFTER the pipeline scan (the zero-bubble point: dW work
            # leaves the serialized per-tick path) — but accumulated with
            # a scan, NOT a vmap: vmapping the per-tick vjp materializes
            # T full dW trees at once (AOT-measured 307 GB temp on the
            # 13B recipe vs 27 GB for 1f1b); the scan keeps dW at O(1)
            xs, dys, mask = stash

            def acc_one(acc, xdm):
                x_sv, dy, on = xdm
                _, vjp = jax.vjp(stage_fn, params_me, x_sv)
                dp = vjp(dy)[0]
                return jax.tree.map(
                    lambda a, g: a + jnp.where(on, g, 0.0).astype(
                        jnp.float32), acc, dp), None
            dw, _ = lax.scan(acc_one, dw, (xs, dys, mask))

        # replicate scalars / edge products over pp (mask -> psum)
        lastf = (stage == last).astype(jnp.float32)
        loss_mean = lax.psum(loss_acc * lastf, pp_axis) * inv_m
        dhead = jax.tree.map(lambda g: lax.psum(g * lastf, pp_axis), dhead)
        dx_out = lax.psum(
            dx_out * (stage == 0).astype(jnp.float32), pp_axis)
        dw = jax.tree.map(lambda g: g[None], dw)  # -> [1,...] per device
        return loss_mean, dw, dhead, dx_out

    fn = jax.shard_map(
        per_device, mesh=mesh, axis_names=manual,
        in_specs=(jax.tree.map(lambda _: P(pp_axis), stacked_params),
                  P(), P(), P()),
        out_specs=(P(), jax.tree.map(lambda _: P(pp_axis), stacked_params),
                   P(), P()),
        check_vma=False)
    return fn(stacked_params, head_params, microbatches, labels)


# --------------------------------------------------------------------------
# Heterogeneous stages (VERDICT r2 missing #4)
#
# The reference segments ARBITRARY layers into stages
# (reference: meta_parallel/parallel_layers/pp_layers.py:93 SegmentLayers,
# :258 PipelineLayer) — stage 0 (embedding) != mid (decoder blocks) != last
# (norm + head). The stacked-stage formulation above needs identical
# per-stage param structures; the heterogeneous formulation below removes
# that requirement the TPU way:
#
# - Each stage's param pytree is FLATTENED into per-dtype NATIVE vectors
#   ({dtype_name: vector}); per dtype, vectors pad to the longest stage and
#   stack into [P, Lmax_dt] sharded over pp — memory still scales ~1/P
#   (padding waste bounded by the largest stage), and bf16 params cost bf16
#   bytes in the stacked copy (VERDICT r4 weak #4: the earlier single-f32
#   carrier doubled the stacked copy's HBM for bf16 configs). Gradients
#   still ACCUMULATE in f32 regardless of storage dtype.
# - Inside the shard_map, ``lax.switch(stage_id, branches)`` dispatches to
#   the stage's own function; branch s statically knows stage s's
#   (treedef, shapes, dtypes) spec and carves its slice of the vector.
# - The activation CARRY stays one static shape (XLA requirement). Shape-
#   changing entry/exit layers (token embedding in, lm head out) run
#   outside the ring — embedding before microbatching, head inside the
#   per-microbatch loss — exactly how the flagship pp step is built
#   (models/train_pp.py).
# --------------------------------------------------------------------------
import numpy as _np


def _flatten_stage(params):
    """pytree -> ({dtype_name: native-dtype vector},
    (treedef, [(shape, dtype), ...]))."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    metas, groups = [], {}
    for l in leaves:
        dt = jnp.result_type(l)
        assert jnp.issubdtype(dt, jnp.floating), (
            f"heterogeneous stage stacking carries params through flat"
            f" per-dtype vectors; non-float leaf {dt} is not supported")
        metas.append((tuple(l.shape), dt))
        groups.setdefault(jnp.dtype(dt).name, []).append(
            jnp.asarray(l).reshape(-1))
    vecs = {k: jnp.concatenate(v) for k, v in groups.items()}
    return vecs, (treedef, metas)


def unflatten_stage(vecs, spec, cast=True):
    """Inverse of _flatten_stage given the stage's static spec. ``vecs``
    is the per-dtype vector dict; leaves are carved in flatten order with
    an independent running offset per dtype. ``cast=False`` keeps the
    vectors' own dtype (grad carving: f32 accumulators stay f32)."""
    treedef, metas = spec
    leaves, offs = [], {}
    for shape, dtype in metas:
        k = jnp.dtype(dtype).name
        n = int(_np.prod(shape)) if shape else 1
        off = offs.get(k, 0)
        leaf = vecs[k][off:off + n].reshape(shape)
        leaves.append(leaf.astype(dtype) if cast else leaf)
        offs[k] = off + n
    return jax.tree_util.tree_unflatten(treedef, leaves)


def flatten_stage_params(per_stage_params: Sequence[Any], mesh: Mesh,
                         pp_axis: str = "pp"):
    """Flatten+pad+stack P heterogeneous stage pytrees ->
    ({dtype_name: [P, Lmax_dt] NATIVE-dtype array sharded over pp},
    per-stage specs). Params stay in their own dtype in the stacked copy
    (bf16 costs bf16 bytes); a stage missing a dtype contributes a
    zero-padded row for that key."""
    pairs = [_flatten_stage(p) for p in per_stage_params]
    key_dtypes = {}
    for vecs, _ in pairs:
        for k, v in vecs.items():
            key_dtypes.setdefault(k, v.dtype)
    stacked = {}
    for k in sorted(key_dtypes):
        vs = [vecs.get(k, jnp.zeros((0,), key_dtypes[k]))
              for vecs, _ in pairs]
        L = max(v.shape[0] for v in vs)
        stacked[k] = jnp.stack([jnp.pad(v, (0, L - v.shape[0]))
                                for v in vs])
    try:
        sh = NamedSharding(mesh, P(pp_axis, None))
        stacked = {k: jax.device_put(a, sh) for k, a in stacked.items()}
    except Exception:
        pass
    return stacked, [s for _, s in pairs]


def unflatten_stage_grads(dvec, specs):
    """{dtype_name: [P, Lmax_dt]} grads -> list of per-stage pytrees
    (leaves keep the accumulators' dtype — f32 from the hand-written
    schedules — via ``unflatten_stage(cast=False)``)."""
    return [unflatten_stage({k: v[s] for k, v in dvec.items()}, spec,
                            cast=False)
            for s, spec in enumerate(specs)]


def _hetero_apply(stage_fns, specs, stage_id, vec_me, x_in):
    """lax.switch over per-stage branches; each branch statically unflattens
    its own spec. All branches must return the carry shape/dtype."""
    branches = [
        (lambda args, s=s: stage_fns[s](
            unflatten_stage(args[0], specs[s]), args[1]))
        for s in range(len(stage_fns))]
    return lax.switch(stage_id, branches, (vec_me, x_in))


def pipeline_hetero(stage_fns: Sequence[Callable], stacked_vec, specs,
                    microbatches, mesh: Mesh, pp_axis: str = "pp"):
    """GPipe wavefront over heterogeneous stages (AD gives the backward).

    stage_fns[s](stage_params, x) -> y, all sharing the carry shape;
    microbatches [M, ...] must already be carry-shaped (embed outside).
    Differentiable w.r.t. stacked_vec and microbatches.
    """
    num_stages = mesh.shape[pp_axis]
    assert len(stage_fns) == num_stages == len(specs)
    M = microbatches.shape[0]
    T = M + num_stages - 1
    manual = frozenset({pp_axis})

    def per_device(vec_local, mb_local):
        vec_me = jax.tree.map(lambda a: a[0], vec_local)
        stage_id = lax.axis_index(pp_axis)
        perm_fwd = [(i, (i + 1) % num_stages) for i in range(num_stages)]
        x0 = jnp.zeros_like(mb_local[0])

        def tick(carry, t):
            recv = carry
            feed = mb_local[jnp.minimum(t, M - 1)]
            x_in = jnp.where(stage_id == 0, feed, recv)
            y = _hetero_apply(stage_fns, specs, stage_id, vec_me, x_in)
            nxt = lax.ppermute(y, pp_axis, perm_fwd)
            return nxt, y

        _, ys = lax.scan(tick, x0, jnp.arange(T))
        outs = lax.dynamic_slice_in_dim(ys, num_stages - 1, M, axis=0)
        mask = (stage_id == num_stages - 1).astype(outs.dtype)
        return _psum_act(outs * mask, pp_axis, mesh)

    fn = jax.shard_map(
        per_device, mesh=mesh, axis_names=manual,
        in_specs=(P(pp_axis, None), P()), out_specs=P(), check_vma=False)
    return fn(stacked_vec, microbatches)


def pipeline_hetero_1f1b(stage_fns: Sequence[Callable], loss_fn: Callable,
                         stacked_vec, specs, head_params, microbatches,
                         labels, mesh: Mesh, pp_axis: str = "pp",
                         defer_dw: bool = False):
    """1F1B / zero-bubble over heterogeneous stages.

    Same schedule + memory contract as ``pipeline_1f1b`` (depth-bounded
    activation ring; defer_dw hoists dW out of the scan), with the
    stacked-pytree stage params replaced by the per-dtype flattened
    {dtype: [P, Lmax_dt]} vectors + lax.switch dispatch. Returns
    (mean_loss, d_stacked {dtype: [P, Lmax_dt] f32}, d_head_params,
    d_microbatches).
    """
    num_stages = mesh.shape[pp_axis]
    assert len(stage_fns) == num_stages == len(specs)
    M = microbatches.shape[0]
    T = M + 2 * num_stages - 2
    R = 2 * num_stages - 1
    manual = frozenset({pp_axis})
    inv_m = 1.0 / M

    def per_device(vec_local, head, mb_local, lab_local):
        vec_me = jax.tree.map(lambda a: a[0], vec_local)
        stage = lax.axis_index(pp_axis)
        last = num_stages - 1
        perm_f = [(i, (i + 1) % num_stages) for i in range(num_stages)]
        perm_b = [(i, (i - 1) % num_stages) for i in range(num_stages)]

        def apply(v, x):
            return _hetero_apply(stage_fns, specs, stage, v, x)

        zero_x = jnp.zeros_like(mb_local[0])
        ring0 = jnp.zeros((R,) + zero_x.shape, zero_x.dtype)
        dw0 = jax.tree.map(lambda v: jnp.zeros(v.shape, jnp.float32),
                           vec_me)
        dhead0 = jax.tree.map(lambda a: jnp.zeros(a.shape, jnp.float32),
                              head)
        dx0 = jnp.zeros((M,) + zero_x.shape, jnp.float32)

        def tick(carry, t):
            (f_rc, b_rc, ring, dw, dhead, dx_out, loss_acc) = carry

            m_f = t - stage
            f_on = (m_f >= 0) & (m_f < M)
            feed = lax.dynamic_index_in_dim(
                mb_local, jnp.clip(m_f, 0, M - 1), 0, keepdims=False)
            x_in = jnp.where(stage == 0, feed, f_rc)
            y = apply(vec_me, x_in)
            slot_f = jnp.mod(t, R)
            ring = jnp.where(
                f_on,
                lax.dynamic_update_index_in_dim(ring, x_in, slot_f, 0),
                ring)

            lab = jax.tree.map(
                lambda l: lax.dynamic_index_in_dim(
                    l, jnp.clip(m_f, 0, M - 1), 0, keepdims=False),
                lab_local)
            lval, head_vjp = jax.vjp(lambda hp, yy: loss_fn(hp, yy, lab),
                                     head, y)
            dhead_c, dy_self = head_vjp(jnp.asarray(inv_m, jnp.float32))
            on_last = f_on & (stage == last)
            loss_acc = loss_acc + jnp.where(on_last, lval, 0.0)
            dhead = jax.tree.map(
                lambda acc, g: acc + jnp.where(on_last, g, 0.0),
                dhead, dhead_c)

            m_b = t - (2 * last - stage)
            b_on = (m_b >= 0) & (m_b < M)
            slot_b = jnp.mod(stage + jnp.clip(m_b, 0, M - 1), R)
            x_sv = lax.dynamic_index_in_dim(ring, slot_b, 0, keepdims=False)
            dy_in = jnp.where(stage == last, dy_self.astype(b_rc.dtype),
                              b_rc)
            _, stage_vjp = jax.vjp(apply, vec_me, x_sv)
            dv_c, dx_c = stage_vjp(dy_in)
            if not defer_dw:
                dw = jax.tree.map(
                    lambda acc, g: acc + jnp.where(
                        b_on, g.astype(jnp.float32), 0.0),
                    dw, dv_c)
            dx_out = jnp.where(
                b_on & (stage == 0),
                lax.dynamic_update_index_in_dim(
                    dx_out, dx_c.astype(jnp.float32),
                    jnp.clip(m_b, 0, M - 1), 0),
                dx_out)

            f_nx = lax.ppermute(y, pp_axis, perm_f)
            b_nx = lax.ppermute(dx_c.astype(b_rc.dtype), pp_axis, perm_b)
            stash = (x_sv, dy_in, b_on) if defer_dw else None
            return (f_nx, b_nx, ring, dw, dhead, dx_out, loss_acc), stash

        init = (zero_x, jnp.zeros_like(zero_x), ring0, dw0, dhead0,
                dx0, jnp.float32(0.0))
        (_, _, _, dw, dhead, dx_out, loss_acc), stash = lax.scan(
            tick, init, jnp.arange(T))

        if defer_dw:
            # scan-accumulated (not vmapped) for O(1) dW memory — see
            # pipeline_1f1b's defer_dw note
            xs, dys, mask = stash

            def acc_one(acc, xdm):
                x_sv, dy, on = xdm
                _, vjp = jax.vjp(apply, vec_me, x_sv)
                dv = vjp(dy)[0]
                return jax.tree.map(
                    lambda a, g: a + jnp.where(on, g.astype(jnp.float32),
                                               0.0), acc, dv), None
            dw, _ = lax.scan(acc_one, dw, (xs, dys, mask))

        lastf = (stage == last).astype(jnp.float32)
        loss_mean = lax.psum(loss_acc * lastf, pp_axis) * inv_m
        dhead = jax.tree.map(lambda g: lax.psum(g * lastf, pp_axis), dhead)
        dx_out = lax.psum(
            dx_out * (stage == 0).astype(jnp.float32), pp_axis)
        return loss_mean, jax.tree.map(lambda a: a[None], dw), dhead, \
            dx_out

    fn = jax.shard_map(
        per_device, mesh=mesh, axis_names=manual,
        in_specs=(P(pp_axis, None), P(), P(), P()),
        out_specs=(P(), P(pp_axis, None), P(), P()),
        check_vma=False)
    return fn(stacked_vec, head_params, microbatches, labels)


def flatten_stage_params_interleaved(per_stage_params: Sequence[Any],
                                     mesh: Mesh, num_chunks: int,
                                     pp_axis: str = "pp"):
    """Heterogeneous VPP stacking: V = P*num_chunks virtual-stage pytrees
    flatten to per-dtype vectors, pad to the longest, and stack
    {dtype: [P, num_chunks, Lmax_dt]} in the Megatron round-robin layout
    (virtual stage s = chunk s//P on device s%P). Returns (stacked, specs)
    with specs in CANONICAL virtual stage order (index s)."""
    P_ = mesh.shape[pp_axis]
    V = P_ * num_chunks
    assert len(per_stage_params) == V
    # reuse the canonical flatten/pad/stack, then fold [V, L] into the
    # round-robin [P, chunks, L] layout (canonical v -> [v % P, v // P])
    flat, specs = flatten_stage_params(per_stage_params, mesh, pp_axis)
    stacked = jax.tree.map(
        lambda a: jnp.transpose(
            a.reshape(num_chunks, P_, a.shape[-1]), (1, 0, 2)), flat)
    try:
        sh = NamedSharding(mesh, P(pp_axis, None, None))
        stacked = jax.tree.map(lambda a: jax.device_put(a, sh), stacked)
    except Exception:
        pass
    return stacked, specs


def pipeline_hetero_interleave(stage_fns: Sequence[Callable], stacked_vec,
                               specs, microbatches, mesh: Mesh,
                               num_chunks: int, pp_axis: str = "pp"):
    """Interleaved (VPP) wavefront over heterogeneous virtual stages.

    Same closed-form schedule as :func:`pipeline_interleave`; the virtual
    stage applied at a tick is ``v = c*P + d`` (a traced value), so the
    per-stage function/spec dispatch is a ``lax.switch`` over all V
    branches — branch v statically unflattens specs[v] from the chunk's
    padded vector. stage_fns are indexed by canonical virtual stage.
    """
    num_stages = mesh.shape[pp_axis]
    V = num_stages * num_chunks
    assert len(stage_fns) == V == len(specs)
    M = microbatches.shape[0]
    assert M % num_stages == 0, (
        f"interleaved schedule needs microbatches ({M}) % pp stages "
        f"({num_stages}) == 0")
    T = M * num_chunks + num_stages - 1
    manual = frozenset({pp_axis})

    def per_device(vec_local, mb_local):
        # {dtype: [num_chunks, Lmax_dt]}
        vec_me = jax.tree.map(lambda a: a[0], vec_local)
        stage = lax.axis_index(pp_axis)
        perm = [(i, (i + 1) % num_stages) for i in range(num_stages)]
        x0 = jnp.zeros_like(mb_local[0])
        out0 = jnp.zeros((M,) + mb_local.shape[1:], mb_local.dtype)

        def apply_virtual(c, x_in):
            v_id = c * num_stages + stage
            vec_c = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0,
                                                   keepdims=False),
                vec_me)
            branches = [
                (lambda args, s=s: stage_fns[s](
                    unflatten_stage(args[0], specs[s]), args[1]))
                for s in range(V)]
            return lax.switch(v_id, branches, (vec_c, x_in))

        def tick(carry, t):
            x_rc, out_buf = carry
            u = t - stage
            vP = V
            g = jnp.where(u >= 0, u // vP, 0)
            rem = jnp.clip(u - g * vP, 0, vP - 1)
            c = rem // num_stages
            m = jnp.clip(g * num_stages + rem % num_stages, 0, M - 1)
            active = (u >= 0) & (u < M * num_chunks)

            feed = lax.dynamic_index_in_dim(mb_local, m, 0, keepdims=False)
            x_in = jnp.where((stage == 0) & (c == 0), feed, x_rc)
            y = apply_virtual(c, x_in)
            y = jnp.where(active, y, x_in)

            emit = active & (stage == num_stages - 1) & \
                (c == num_chunks - 1)
            upd = lax.dynamic_update_index_in_dim(
                out_buf, y.astype(out_buf.dtype), m, 0)
            out_buf = jnp.where(emit, upd, out_buf)

            x_nx = lax.ppermute(y, pp_axis, perm)
            return (x_nx, out_buf), None

        (_, outs), _ = lax.scan(tick, (x0, out0), jnp.arange(T))
        mask = (stage == num_stages - 1).astype(outs.dtype)
        return _psum_act(outs * mask, pp_axis, mesh)

    fn = jax.shard_map(
        per_device, mesh=mesh, axis_names=manual,
        in_specs=(P(pp_axis, None, None), P()), out_specs=P(),
        check_vma=False)
    return fn(stacked_vec, microbatches)


def pipeline_hetero_interleave_1f1b(stage_fns: Sequence[Callable],
                                    loss_fn: Callable, stacked_vec, specs,
                                    head_params, microbatches, labels,
                                    mesh: Mesh, num_chunks: int,
                                    pp_axis: str = "pp",
                                    defer_dw: bool = False):
    """Heterogeneous VPP with the hand-written depth-bounded backward —
    ``pipeline_interleave_1f1b``'s schedule (same shared
    ``_interleave_1f1b_core``) over the per-dtype flattened carrier +
    lax.switch virtual-stage dispatch of the hetero tier.

    stacked_vec: {dtype: [P, num_chunks, Lmax_dt]} (round-robin layout
    from ``flatten_stage_params_interleaved``); specs in canonical
    virtual-stage order. Returns (mean_loss, d_stacked {dtype:
    [P, num_chunks, Lmax_dt] f32}, d_head_params, d_microbatches).
    Requires M % P == 0.
    """
    num_stages = mesh.shape[pp_axis]
    V = num_stages * num_chunks
    assert len(stage_fns) == V == len(specs)

    def apply_chunk(vme, c, x, d):
        vec_c = jax.tree.map(
            lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
            vme)
        v_id = c * num_stages + d
        branches = [
            (lambda args, s=s: stage_fns[s](
                unflatten_stage(args[0], specs[s]), args[1]))
            for s in range(V)]
        return lax.switch(v_id, branches, (vec_c, x))

    return _interleave_1f1b_core(
        apply_chunk, stacked_vec, head_params, microbatches, labels,
        mesh, num_chunks, pp_axis, loss_fn, P(pp_axis, None, None),
        defer_dw=defer_dw)
