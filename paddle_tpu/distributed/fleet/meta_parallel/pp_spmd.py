"""SPMD pipeline parallelism: stacked stages + ppermute rotation.

This is the TPU-native execution of pipeline parallelism — the counterpart
of the reference's multi-process 1F1B engine
(reference: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:575 forward_backward_pipeline + pp_utils/
p2p_communication.py eager NCCL p2p). The reference pipelines across
*processes*; XLA pipelines across *mesh coordinates inside one program*:

- Each coordinate of the ``pp`` mesh axis holds ONE stage's weights: every
  homogeneous-stage parameter is stacked with a leading ``[num_stages]``
  axis sharded over ``pp``.
- A ``lax.scan`` runs M + P - 1 ticks. Per tick each stage applies its
  layer block, then activations rotate one hop along the pp ring via
  ``lax.ppermute`` (ICI neighbour traffic only). Stage 0 feeds a fresh
  microbatch each tick; stage P-1 emits a finished microbatch from tick
  P-1 on — the classic GPipe wavefront.
- Differentiating through the scan + ppermute gives the reverse wavefront
  (ppermute transposes to the opposite rotation, scan reverses time): the
  backward pipeline the reference hand-schedules falls out of AD.
- Other mesh axes (dp/mp/...) are listed outside ``axis_names`` so GSPMD
  keeps auto-sharding them inside the manual pp program (jax.shard_map
  partial-manual mode).

Zero-bubble-style schedules reorder backward-weight vs backward-input work;
XLA's scheduler already overlaps the transposed scan's collectives with
compute, and the bubble fraction here matches GPipe: (P-1)/(M+P-1) — driven
down by raising the microbatch count M, the same lever the reference's
1F1B/VPP passes pull.
"""
from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(per_stage_params: Sequence[Any], mesh: Mesh,
                       pp_axis: str = "pp"):
    """Stack per-stage pytrees into leading-[P] arrays sharded over pp."""
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0),
                           *per_stage_params)

    def place(x):
        spec = [pp_axis] + [None] * (x.ndim - 1)
        try:
            return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        except Exception:
            return x
    return jax.tree.map(place, stacked)


def pipeline_spmd(stage_fn: Callable, stacked_params, microbatches,
                  mesh: Mesh, pp_axis: str = "pp",
                  last_fn: Optional[Callable] = None):
    """Run the GPipe wavefront over the pp axis.

    stage_fn(stage_params, x) -> y         (uniform across stages)
    stacked_params: pytree, leading dim [P] sharded over pp_axis
    microbatches:   [M, mb, ...] input activations for stage 0
    last_fn(y) -> z (optional): applied to finished microbatches
    returns [M, ...] outputs of the last stage.
    """
    num_stages = mesh.shape[pp_axis]
    M = microbatches.shape[0]
    T = M + num_stages - 1
    manual = frozenset({pp_axis})

    def per_device(params_local, mb_local):
        # params_local: my stage's params (leading dim 1) ; squeeze it
        params_me = jax.tree.map(lambda x: x[0], params_local)
        stage_id = lax.axis_index(pp_axis)
        perm_fwd = [(i, (i + 1) % num_stages) for i in range(num_stages)]

        x0 = jnp.zeros_like(mb_local[0])

        def tick(carry, t):
            recv = carry
            feed = mb_local[jnp.minimum(t, M - 1)]
            x_in = jnp.where(stage_id == 0, feed, recv)
            y = stage_fn(params_me, x_in)
            nxt = lax.ppermute(y, pp_axis, perm_fwd)
            return nxt, y

        _, ys = lax.scan(tick, x0, jnp.arange(T))
        # finished microbatches leave the last stage at ticks [P-1, T-1]
        outs = lax.dynamic_slice_in_dim(ys, num_stages - 1, M, axis=0)
        # broadcast last-stage outputs to all pp coords so the result is
        # replicated over pp (callers compute loss once)
        mask = (stage_id == num_stages - 1).astype(outs.dtype)
        outs = lax.psum(outs * mask, pp_axis)
        return outs

    fn = jax.shard_map(
        per_device, mesh=mesh, axis_names=manual,
        in_specs=(jax.tree.map(lambda _: P(pp_axis), stacked_params), P()),
        out_specs=P(), check_vma=False)
    outs = fn(stacked_params, microbatches)
    if last_fn is not None:
        outs = jax.vmap(last_fn)(outs)
    return outs


def pipeline_loss_spmd(stage_fn: Callable, loss_fn: Callable,
                       stacked_params, head_params, microbatches, labels,
                       mesh: Mesh, pp_axis: str = "pp"):
    """Pipeline + per-microbatch loss, averaged — the training objective.

    loss_fn(head_params, y, label) -> scalar loss for one microbatch.
    Returns mean loss over microbatches; differentiable w.r.t. both
    stacked_params and head_params.
    """
    outs = pipeline_spmd(stage_fn, stacked_params, microbatches, mesh,
                         pp_axis)
    losses = jax.vmap(lambda y, l: loss_fn(head_params, y, l))(outs, labels)
    return jnp.mean(losses)
