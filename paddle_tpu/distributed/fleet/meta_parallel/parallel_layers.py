"""Pipeline model description: LayerDesc / SegmentLayers / PipelineLayer.

Re-design of the reference's pp_layers
(reference: python/paddle/distributed/fleet/meta_parallel/parallel_layers/
pp_layers.py — LayerDesc:57, SharedLayerDesc:77, SegmentLayers:93,
PipelineLayer:258).

The reference instantiates only the local stage's layers per rank. Under the
single-controller model every layer exists once; PipelineLayer records the
stage partition so the schedule (pipeline_parallel.py) and the SPMD
stacked-stage path can address per-stage sublists.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from ...._core.tensor import Tensor
from ....nn.layer.layers import Layer, LayerList


class LayerDesc:
    """reference: pp_layers.py:57."""

    def __init__(self, layer_cls, *inputs, **kwargs):
        if not issubclass(layer_cls, Layer):
            raise TypeError("layer_cls must be a Layer subclass")
        self.layer_cls = layer_cls
        self.inputs = inputs
        self.kwargs = kwargs

    def build_layer(self) -> Layer:
        return self.layer_cls(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_cls.__name__})"


class SharedLayerDesc(LayerDesc):
    """reference: pp_layers.py:77 — a layer shared between stages (e.g.
    tied embedding/head). Single-controller: the first build within one
    PipelineLayer is reused, so weight tying is object identity (no
    grad-sync ties needed). The registry is scoped to the owning
    PipelineLayer — two models with the same key do NOT alias."""

    def __init__(self, key, layer_cls, forward_func=None,
                 shared_weight_attr="weight", *inputs, **kwargs):
        super().__init__(layer_cls, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr

    def build_layer(self, registry=None) -> Layer:
        if registry is None:
            return super().build_layer()
        if self.layer_name not in registry:
            registry[self.layer_name] = super().build_layer()
        return registry[self.layer_name]


class SegmentLayers:
    """reference: pp_layers.py:93 — split N layers into num_parts stages,
    uniformly or by a seg_method ("layer:<ClassName>" segments at class
    boundaries; "uniform" by count)."""

    def __init__(self, layers_desc, num_parts, method="uniform",
                 num_virtual_pipeline_stage=None):
        self._layers = layers_desc
        self.num_parts = num_parts
        self.method = method

    def do_segment(self) -> List[int]:
        n = len(self._layers)
        if self.method == "uniform" or self.num_parts <= 1:
            return self.uniform(n, self.num_parts)
        if self.method.startswith("layer:"):
            cls_name = self.method.split(":", 1)[1]
            marks = [i for i, d in enumerate(self._layers)
                     if self._name_of(d) == cls_name]
            if len(marks) < self.num_parts:
                raise ValueError(
                    f"only {len(marks)} '{cls_name}' layers for "
                    f"{self.num_parts} stages")
            # distribute marked layers evenly; boundaries at marks
            per = len(marks) // self.num_parts
            extra = len(marks) % self.num_parts
            bounds = [0]
            idx = 0
            for s in range(self.num_parts):
                take = per + (1 if s < extra else 0)
                idx += take
                bounds.append(marks[idx - 1] + 1 if s < self.num_parts - 1
                              else n)
            bounds[1] = max(bounds[1], marks[0] + 1)
            bounds[-1] = n
            return bounds
        raise ValueError(f"unknown seg method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts) -> List[int]:
        result = [0] * (num_parts + 1)
        size = num_items // num_parts
        extra = num_items % num_parts
        for i in range(num_parts):
            result[i + 1] = result[i] + size + (1 if i < extra else 0)
        return result

    @staticmethod
    def _name_of(desc):
        if isinstance(desc, LayerDesc):
            return desc.layer_cls.__name__
        return type(desc).__name__


class PipelineLayer(Layer):
    """reference: pp_layers.py:258."""

    def __init__(self, layers, num_stages=None, topology=None,
                 loss_fn=None, seg_method="uniform",
                 recompute_interval=0, recompute_ctx=None,
                 num_virtual_pipeline_stages=None):
        super().__init__()
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        if num_stages is None:
            if topology is not None:
                num_stages = topology.get_dim("pp")
            else:
                from ..fleet import get_hybrid_communicate_group
                hcg = get_hybrid_communicate_group()
                num_stages = (hcg.get_pipe_parallel_world_size()
                              if hcg else 1)
        self._num_stages = int(num_stages)
        self._num_virtual = int(num_virtual_pipeline_stages or 1)
        self._layers_desc = list(layers)
        # VPP segments into num_stages * num_virtual chunks (reference:
        # pp_layers.py PipelineLayer._num_virtual_pipeline_stages)
        self._segment = SegmentLayers(
            self._layers_desc, self._num_stages * self._num_virtual,
            seg_method).do_segment()
        built = []
        shared_registry = {}
        for d in self._layers_desc:
            if isinstance(d, SharedLayerDesc):
                built.append(d.build_layer(shared_registry))
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            else:
                built.append(d)
        self.run_function = LayerList([l for l in built
                                       if isinstance(l, Layer)])
        self._built = built  # may include plain callables

    @property
    def num_stages(self):
        return self._num_stages

    def get_num_stages(self):
        return self._num_stages

    def stage_layers(self, stage: int):
        lo, hi = self._segment[stage], self._segment[stage + 1]
        return self._built[lo:hi]

    def segment_bounds(self):
        return list(self._segment)

    def forward(self, x):
        from ..recompute.recompute import recompute
        for i, l in enumerate(self._built):
            if self._recompute_interval and isinstance(l, Layer) and \
                    i % self._recompute_interval == 0:
                x = recompute(l, *(x if isinstance(x, tuple) else (x,)))
            else:
                x = l(*x) if isinstance(x, tuple) else l(x)
        return x
