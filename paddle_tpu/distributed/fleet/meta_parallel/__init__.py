"""Mirrors ``paddle.distributed.fleet.meta_parallel``
(reference: python/paddle/distributed/fleet/meta_parallel/__init__.py)."""
from ..layers.mpu.mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy,
)
from ..layers.mpu.random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)
from .parallel_layers import (  # noqa: F401
    LayerDesc, SharedLayerDesc, SegmentLayers, PipelineLayer,
)
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .engines import (  # noqa: F401
    TensorParallel, ShardingParallel, SegmentParallel,
)
from .pp_spmd import (  # noqa: F401
    pipeline_spmd, pipeline_loss_spmd, stack_stage_params,
)
from .context_parallel import (  # noqa: F401
    ring_attention, ulysses_attention,
)
