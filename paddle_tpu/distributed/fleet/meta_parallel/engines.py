"""Parallel engine wrappers (non-pipeline).

Re-design of the reference's meta_parallel engines
(reference: python/paddle/distributed/fleet/meta_parallel/
tensor_parallel.py:28, sharding_parallel.py:25, segment_parallel.py:26).

The reference engines broadcast parameters/inputs across their groups at
construction and install grad-sync hooks. Single-controller TPU: parameters
have one source of truth and grad sync is compiled into the backward, so
these wrappers carry the API surface (and the input/activation sharding
policy for their axis) with no eager communication.
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...._core.tensor import Tensor
from ....nn.layer.layers import Layer


class MetaParallelBase(Layer):
    """reference: meta_parallel/meta_parallel_base.py MetaParallelBase."""

    def __init__(self, layers: Layer, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


class TensorParallel(MetaParallelBase):
    """reference: meta_parallel/tensor_parallel.py:28 — broadcasts inputs
    and syncs params across the mp group. Under GSPMD both are implicit in
    the shardings installed by the mpu layers."""


class ShardingParallel(MetaParallelBase):
    """reference: meta_parallel/sharding_parallel.py:25."""


class SegmentParallel(MetaParallelBase):
    """reference: meta_parallel/segment_parallel.py:26 — sequence split
    across the sep axis: inputs get their sequence dim sharded over 'sep'.
    """

    def forward(self, *inputs, **kwargs):
        hcg = self._hcg
        n = hcg.get_sep_parallel_world_size()
        if n > 1:
            mesh = hcg.mesh

            def place(x):
                if isinstance(x, Tensor) and x.ndim >= 2 and \
                        x.shape[1] % n == 0:
                    # [b, s, ...]: shard seq dim over sep
                    spec = [None] * x.ndim
                    spec[1] = "sep"
                    try:
                        return Tensor(jax.device_put(
                            x._value, NamedSharding(mesh, P(*spec))),
                            _internal=True)
                    except Exception:
                        return x
                return x
            inputs = tuple(place(x) for x in inputs)
        return self._layers(*inputs, **kwargs)
