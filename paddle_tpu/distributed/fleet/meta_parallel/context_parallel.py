"""Context parallelism: ring attention + Ulysses (DeepSpeed-style) alltoall.

The reference snapshot has NO ring attention / Ulysses (SURVEY §2.3 CP row:
"Not present"); its long-context story is SEP + Megatron-SP +
FlashAttention. CP is nonetheless first-class here (SURVEY §7 hard part 8):
long sequences shard along a "cp"/"sep" mesh axis and attention runs as a
ring of `ppermute` steps over ICI, overlapping compute with neighbor
transfers, or as Ulysses head↔seq `all_to_all` swaps.

Both functions are *collective* ops: they must be called inside
``shard_map`` (or an equivalent SPMD region) with the sequence dimension
sharded over ``axis_name``. Layout: (B, S_local, H, D).

Numerics: blockwise online softmax in fp32 with a custom VJP whose backward
re-runs the ring (kv + traveling dk/dv buffers), so peak memory stays
O(S_local) — the point of ring attention (Liu et al. 2023).
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def _chunk_scores(q, k, scale, causal, qi, kj, s_loc):
    """q (B,H,S,D) x k (B,H,S,D) -> masked fp32 scores (B,H,S,S).

    qi/kj: ring positions of the q and kv chunks along the cp axis (traced
    ints); global token index = chunk_pos * s_loc + local index.
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        qpos = qi * s_loc + lax.broadcasted_iota(jnp.int32, s.shape, 2)
        kpos = kj * s_loc + lax.broadcasted_iota(jnp.int32, s.shape, 3)
        s = jnp.where(qpos >= kpos, s, _NEG)
    return s


def _rep_heads(t, rep):
    """Local GQA head repeat (B,Hk,S,D) -> (B,Hk*rep,S,D). Lives INSIDE
    the ring body so the traveling kv buffers stay unrepeated — ICI
    moves h/hk× less data per step."""
    return t if rep == 1 else jnp.repeat(t, rep, axis=1)


def _ring_fwd_scan(q, k, v, axis_name, causal, scale):
    """Returns (out fp32 (B,H,S,D), lse (B,H,S)). k/v may carry fewer
    (GQA) heads than q."""
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    rep = H // k.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]  # kv travels to next rank

    def body(carry, step):
        acc, m, l, kc, vc = carry
        src = (me - step) % n          # ring position of current kv chunk
        s = _chunk_scores(q, _rep_heads(kc, rep), scale, causal, me, src,
                          S)
        mj = jnp.max(s, axis=-1)                     # (B,H,S)
        m_new = jnp.maximum(m, mj)
        # fully-masked rows keep m=_NEG; guard exp of (-inf - -inf)
        safe_m = jnp.where(m_new <= _NEG, 0.0, m_new)
        p = jnp.exp(s - safe_m[..., None])
        p = jnp.where(s <= _NEG, 0.0, p)
        alpha = jnp.where(m <= _NEG, 0.0, jnp.exp(m - safe_m))
        l_new = alpha * l + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p,
            _rep_heads(vc, rep).astype(jnp.float32),
            preferred_element_type=jnp.float32)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (acc_new, m_new, l_new, kc, vc), None

    init = (jnp.zeros((B, H, S, D), jnp.float32),
            jnp.full((B, H, S), _NEG, jnp.float32),
            jnp.zeros((B, H, S), jnp.float32), k, v)
    (acc, m, l, _, _), _ = lax.scan(body, init, jnp.arange(n))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe[..., None]
    lse = jnp.where(l == 0.0, _NEG, m + jnp.log(l_safe))
    return out, lse


def _flash_ring_ok(q) -> bool:
    """Static gate: use the Pallas flash kernel for the per-chunk
    attention inside the ring (the einsum path materializes a fp32
    (B,H,S,S) score block per ring step — the flash partials never do)."""
    from ....ops.pallas import flash_attention as fa
    B, H, S, D = q.shape
    return fa.available() and S % 128 == 0 and D >= 64


def _ring_fwd_flash(q, k, v, axis_name, causal, scale):
    """Flash-partial ring: step 0 runs the SELF chunk (statically causal
    when ``causal``), later steps run full-attention partials whose lse
    is knocked to -1e30 on ranks where the chunk is future context; the
    online log-sum-exp merge combines normalized partials exactly.
    Returns (out fp32, lse) — same contract as :func:`_ring_fwd_scan`,
    so the einsum backward (which only consumes q,k,v,out,lse) is
    untouched."""
    from ....ops.pallas import flash_attention as fa
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    rep = H // k.shape[1]
    qf = q.reshape(B * H, S, D)

    def chunk(kc, vc, is_causal):
        # fp32 partials: rounding each chunk's output to bf16 before the
        # cross-chunk merge would compound error ~n times vs the einsum
        # ring's end-to-end fp32 accumulation. GQA kv stays UNREPEATED —
        # the kernel's kv index map divides by rep (no HBM duplication)
        Hk = kc.shape[1]
        o, l = fa._fwd(qf, kc.reshape(B * Hk, S, D),
                       vc.reshape(B * Hk, S, D),
                       scale, is_causal, 512, 1024,
                       out_dtype=jnp.float32, kv_rep=rep)
        return o.reshape(B, H, S, D), l.reshape(B, H, S)

    perm = [(i, (i + 1) % n) for i in range(n)]
    acc, m = chunk(k, v, causal)            # self chunk: never all-masked
    ssum = jnp.ones_like(m)
    # prologue rotate; the scan body computes on the CARRIED chunk and
    # permutes at the tail, so the next chunk's ICI transfer overlaps the
    # current chunk's kernel (same schedule as the einsum ring)
    kc = lax.ppermute(k, axis_name, perm)
    vc = lax.ppermute(v, axis_name, perm)

    def body(carry, step):
        acc, m, ssum, kc, vc = carry
        src = (me - step) % n               # ring position of this chunk
        oj, lj = chunk(kc, vc, False)
        if causal:
            lj = jnp.where(src < me, lj, _NEG)   # future chunks: no mass
        m2 = jnp.maximum(m, lj)
        a = jnp.exp(m - m2)                 # m is finite from step 0 on
        bw = jnp.exp(lj - m2)               # exp(-1e30 - m2) == 0
        acc = acc * a[..., None] + oj * bw[..., None]
        ssum = ssum * a + bw
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (acc, m2, ssum, kc, vc), None

    (acc, m, ssum, _, _), _ = lax.scan(
        body, (acc, m, ssum, kc, vc), jnp.arange(1, n))
    return acc / ssum[..., None], m + jnp.log(ssum)


def _ring_fwd(q, k, v, axis_name, causal, scale):
    if _flash_ring_ok(q):
        return _ring_fwd_flash(q, k, v, axis_name, causal, scale)
    return _ring_fwd_scan(q, k, v, axis_name, causal, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_attn_bhsd(q, k, v, axis_name, causal, scale):
    out, _ = _ring_fwd(q, k, v, axis_name, causal, scale)
    return out.astype(q.dtype)


def _ring_attn_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_fwd(q, k, v, axis_name, causal, scale)
    out = out.astype(q.dtype)
    return out, (q, k, v, out, lse)


def _ring_attn_bwd(axis_name, causal, scale, res, do):
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    Hk = k.shape[1]
    rep = H // Hk
    perm = [(i, (i + 1) % n) for i in range(n)]
    do32 = do.astype(jnp.float32)
    delta = jnp.sum(do32 * out.astype(jnp.float32), axis=-1)  # (B,H,S)

    def gqa_sum(g):  # (B,H,S,D) grads -> (B,Hk,S,D) traveling layout
        return g if rep == 1 else g.reshape(B, Hk, rep, S, D).sum(2)

    def body(carry, step):
        dq, kc, vc, dkc, dvc = carry
        src = (me - step) % n
        kr = _rep_heads(kc, rep)
        s = _chunk_scores(q, kr, scale, causal, me, src, S)
        safe_lse = jnp.where(lse <= _NEG, 0.0, lse)
        p = jnp.exp(s - safe_lse[..., None])
        p = jnp.where(s <= _NEG, 0.0, p)
        dvc = dvc + gqa_sum(jnp.einsum(
            "bhqk,bhqd->bhkd", p, do32,
            preferred_element_type=jnp.float32))
        dp = jnp.einsum("bhqd,bhkd->bhqk", do32,
                        _rep_heads(vc, rep).astype(jnp.float32),
                        preferred_element_type=jnp.float32)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, kr.astype(jnp.float32),
                             preferred_element_type=jnp.float32)
        dkc = dkc + gqa_sum(jnp.einsum(
            "bhqk,bhqd->bhkd", ds, q.astype(jnp.float32),
            preferred_element_type=jnp.float32))
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dkc = lax.ppermute(dkc, axis_name, perm)
        dvc = lax.ppermute(dvc, axis_name, perm)
        return (dq, kc, vc, dkc, dvc), None

    init = (jnp.zeros((B, H, S, D), jnp.float32), k, v,
            jnp.zeros((B, Hk, S, D), jnp.float32),
            jnp.zeros((B, Hk, S, D), jnp.float32))
    (dq, _, _, dk, dv), _ = lax.scan(body, init, jnp.arange(n))
    # after n ppermute hops the traveling dk/dv buffers are home again
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attn_bhsd.defvjp(_ring_attn_fwd, _ring_attn_bwd)


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """Ring attention over sequence-sharded q/k/v (B, S_local, H, D).

    Call inside ``shard_map`` with seq sharded over ``axis_name``. GQA:
    the UNREPEATED kv heads travel the ring (h/hk× less ICI traffic);
    the per-chunk compute repeats them locally.
    """
    b, s, h, d = q.shape
    hk = k.shape[2]
    assert h % hk == 0
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = _ring_attn_bhsd(qt, kt, vt, axis_name, causal, sc)
    return out.transpose(0, 2, 1, 3)


def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale: Optional[float] = None, attn_fn=None):
    """Ulysses/DeepSpeed sequence parallelism: all_to_all swaps the sharded
    dim from seq to heads, runs FULL-sequence attention locally (any
    attn_fn, e.g. the Pallas flash kernel), and swaps back.

    Requires num_heads % cp == 0. q/k/v: (B, S_local, H, D) inside
    shard_map.
    """
    n = lax.psum(1, axis_name)
    b, s, h, d = q.shape
    if h % n != 0:
        raise ValueError(
            f"ulysses_attention: num_heads ({h}) must be divisible by the "
            f"context-parallel degree ({n}) — the all_to_all splits the "
            f"head dim across cp ranks")
    hk = k.shape[2]
    if hk != h:
        assert h % hk == 0
        # GQA: repeat kv only enough for the head dim to split over n
        # ranks — the local attention maps q-head groups to kv heads, so
        # the all_to_all moves up to h/hk× less kv than a full repeat.
        # Custom attn_fn gets the full repeat (its GQA support is
        # unknown; the default _attention and the flash wrapper repeat
        # residual groups themselves).
        need = n // math.gcd(hk, n)
        hk2 = hk * need
        if attn_fn is None and hk2 <= h and h % hk2 == 0:
            rep = need
        else:
            rep = h // hk
        if rep > 1:
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)

    def seq2head(t):  # (B, S/n, H, D) -> (B, S, H/n, D)
        return lax.all_to_all(t, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    def head2seq(t):  # (B, S, H/n, D) -> (B, S/n, H, D)
        return lax.all_to_all(t, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    qg, kg, vg = seq2head(q), seq2head(k), seq2head(v)
    if attn_fn is None:
        from ....models.llama import _attention
        og = _attention(qg, kg, vg, causal=causal)
    else:
        og = attn_fn(qg, kg, vg, causal=causal)
    return head2seq(og)
