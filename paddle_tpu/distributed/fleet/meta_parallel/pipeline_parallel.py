"""Pipeline-parallel engine.

Re-design of the reference's PipelineParallel
(reference: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py — PipelineParallel:255, forward_backward_pipeline:575
(1F1B), train_batch:820, interleave:1174, FthenB:2256; p2p plumbing
pp_utils/p2p_communication.py:573).

TPU-native design. The reference runs one process per stage and threads
activations through eager NCCL p2p; its 1F1B order exists to bound
in-flight activations per worker. Under XLA's single-program model the
schedule is expressed differently:

- **train_batch** keeps the reference's CONTRACT: split the batch into
  ``accumulate_steps`` microbatches, accumulate grads across them, average
  the loss — bit-parity with the reference's loss math (microbatch loop =
  gradient accumulation; XLA already overlaps compute/comm within each
  compiled step).
- **The true pipelined execution** (stages resident on different devices,
  microbatches in flight across the `pp` mesh axis) lives in
  :mod:`pp_spmd` — a shard_map program where each pp coordinate holds its
  stage's (stacked) weights and activations rotate via ``ppermute``; the
  reverse pass of the differentiated scan IS the backward pipeline. The
  flagship Llama path and ``dryrun_multichip`` use it.
"""
from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ...._core.tensor import Tensor
from ...._core.autograd import backward as _tape_backward
from .engines import MetaParallelBase
from .parallel_layers import PipelineLayer


class PipelineParallel(MetaParallelBase):
    """reference: meta_parallel/pipeline_parallel.py:255."""

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        pc = (strategy.pipeline_configs if strategy is not None else
              {"accumulate_steps": 1})
        self.accumulate_steps = int(pc.get("accumulate_steps", 1))
        self.micro_batch_size = int(pc.get("micro_batch_size", 1))
        self.total_loss = None

    def _split_micro(self, data):
        """Split [B, ...] inputs into accumulate_steps microbatches."""
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return [tuple(p[i] for p in parts)
                    for i in range(self.accumulate_steps)]
        if not isinstance(data, Tensor):
            return [data] * self.accumulate_steps
        b = data.shape[0]
        m = self.accumulate_steps
        if b % m != 0:
            raise ValueError(f"batch {b} not divisible by "
                             f"accumulate_steps {m}")
        sz = b // m
        return [Tensor(data._value[i * sz:(i + 1) * sz], _internal=True)
                for i in range(m)]

    def forward_backward_pipeline(self, data, scaler=None):
        """reference: pipeline_parallel.py:575 — 1F1B. Grad-accumulation
        semantics (identical loss/grads); see module docstring for where
        the spatial pipelining happens."""
        inputs, labels = data
        micro_in = self._split_micro(inputs)
        micro_lb = self._split_micro(labels)
        total = None
        for x, y in zip(micro_in, micro_lb):
            out = self._layers(x)
            loss_fn = self._layers._loss_fn
            if loss_fn is None:
                raise RuntimeError("PipelineLayer needs loss_fn for "
                                   "train_batch")
            loss = loss_fn(out, y)
            scaled = loss / self.accumulate_steps
            if scaler is not None:
                scaled = scaler.scale(scaled)
            _tape_backward(scaled)
            total = loss if total is None else total + loss
        self.total_loss = total / self.accumulate_steps
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """reference: pipeline_parallel.py:820."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)
