"""Pipeline-parallel engine.

Re-design of the reference's PipelineParallel
(reference: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py — PipelineParallel:255, forward_backward_pipeline:575
(1F1B), train_batch:820, interleave:1174, FthenB:2256; p2p plumbing
pp_utils/p2p_communication.py:573).

TPU-native design. The reference runs one process per stage and threads
activations through eager NCCL p2p; its 1F1B order exists to bound
in-flight activations per worker. Under XLA's single-program model the
schedule is expressed differently:

- **The true pipelined execution** (stages resident on different devices,
  microbatches in flight across the `pp` mesh axis) lives in
  :mod:`pp_spmd` and is the DEFAULT whenever the PipelineLayer's stages are
  homogeneous (same per-stage parameter structure — the stacked-stage
  requirement) and the hybrid topology has a pp axis: the engine stacks
  per-stage params over the pp mesh axis, runs the schedule selected by
  ``strategy.pipeline_configs["schedule_mode"]`` ("1F1B" default,
  "F-then-B"/"gpipe" GPipe, "VPP" interleaved, "ZB" zero-bubble), and
  writes the resulting grads into each parameter's ``.grad`` slot so
  ``optimizer.step()`` works unchanged.
- **Heterogeneous stages** (embedding stage != decoder stage != head stage
  — the common real topology; reference pp_layers.py:93 SegmentLayers
  segments arbitrary layers): stage param pytrees are flattened to one
  padded f32 vector stacked [P, Lmax] over pp and dispatched per-stage via
  ``lax.switch`` (pp_spmd.pipeline_hetero*). Shape-changing entry layers
  (token embed) run before microbatching; shape-changing exit layers (final
  head) run inside the per-microbatch loss — the same decomposition the
  flagship pp step uses (models/train_pp.py).
- **Fallback** (pp degree 1, a GradScaler, or activations that change
  shape mid-ring): microbatch grad accumulation — the same loss/grad math
  without spatial parallelism; WARNS that it is de-pipelining.
"""
from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ...._core.tensor import Tensor
from ...._core.autograd import backward as _tape_backward
from ....observability import hooks as _obs
from .engines import MetaParallelBase
from .parallel_layers import PipelineLayer


def _aux_layers(layer):
    """Sublayers (incl. self) that report an MoE-style aux loss on
    ``_last_aux_loss`` after each forward (incubate MoELayer & friends)."""
    return [l for l in layer.sublayers(include_self=True)
            if hasattr(l, "_last_aux_loss")]


def _append_aux_slot(y, slot, aux):
    """Add ``aux`` (f32 scalar) into the carry's last-axis aux slot,
    spread uniformly so the slot's SUM recovers the accumulated total
    (bf16 transport keeps relative precision on a regularizer)."""
    import jax.numpy as jnp
    row = slot + (aux / slot.size).astype(y.dtype)
    return jnp.concatenate([y, row], axis=-1)


def _split_aux_slot(y):
    """(activations, accumulated f32 aux) from an aux-augmented carry."""
    import jax.numpy as jnp
    return y[..., :-1], jnp.sum(y[..., -1:].astype(jnp.float32))


class PipelineParallel(MetaParallelBase):
    """reference: meta_parallel/pipeline_parallel.py:255."""

    _SCHEDULES = {"1f1b": "1f1b", "f-then-b": "gpipe", "fthenb": "gpipe",
                  "gpipe": "gpipe", "vpp": "interleave",
                  "interleave": "interleave", "zb": "zero_bubble",
                  "zbh1": "zero_bubble", "zero_bubble": "zero_bubble"}

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        pc = (strategy.pipeline_configs if strategy is not None else
              {"accumulate_steps": 1})
        self.accumulate_steps = int(pc.get("accumulate_steps", 1))
        self.micro_batch_size = int(pc.get("micro_batch_size", 1))
        mode = str(pc.get("schedule_mode", "1F1B")).lower()
        if mode not in self._SCHEDULES:
            raise ValueError(f"unknown pipeline schedule_mode {mode!r}; "
                             f"one of {sorted(set(self._SCHEDULES))}")
        self.schedule = self._SCHEDULES[mode]
        self.total_loss = None
        self._spmd_step = None  # lazily-built jitted schedule program

    def _split_micro(self, data):
        """Split [B, ...] inputs into accumulate_steps microbatches."""
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return [tuple(p[i] for p in parts)
                    for i in range(self.accumulate_steps)]
        if not isinstance(data, Tensor):
            return [data] * self.accumulate_steps
        b = data.shape[0]
        m = self.accumulate_steps
        if b % m != 0:
            raise ValueError(f"batch {b} not divisible by "
                             f"accumulate_steps {m}")
        sz = b // m
        return [Tensor(data._value[i * sz:(i + 1) * sz], _internal=True)
                for i in range(m)]

    # ---------------- SPMD schedule path ----------------
    def _stage_param_lists(self):
        """Per-stage [stage][layer] name->Parameter dicts, or None when the
        stages are not homogeneous (the stacked-stage requirement)."""
        from ....nn.layer.layers import Layer
        num_seg = len(self._layers.segment_bounds()) - 1
        stages = []
        for s in range(num_seg):
            ls = self._layers.stage_layers(s)
            stages.append([(l, dict(l.named_parameters()))
                           for l in ls if isinstance(l, Layer)])
            if any(not isinstance(l, Layer) for l in ls):
                return None  # plain callables can't be stacked
        ref = [[sorted((k, tuple(p.shape), str(p.dtype))
                       for k, p in lp[1].items()) for lp in stages[0]]]
        for st in stages[1:]:
            sig = [sorted((k, tuple(p.shape), str(p.dtype))
                          for k, p in lp[1].items()) for lp in st]
            if sig != ref[0]:
                return None
        return stages

    def _can_spmd(self, scaler):
        if scaler is not None:
            return None
        hcg = self._hcg
        if hcg is None or hcg.get_pipe_parallel_world_size() < 2:
            return None
        mesh = getattr(hcg, "mesh", None)
        if mesh is None or "pp" not in mesh.axis_names:
            return None
        loss_layer = self._layers._loss_fn
        from ....nn.layer.layers import Layer
        if isinstance(loss_layer, Layer) and list(loss_layer.parameters()):
            return None  # parametric loss heads keep the accum path
        num_seg = len(self._layers.segment_bounds()) - 1
        pp = hcg.get_pipe_parallel_world_size()
        if num_seg % pp != 0:
            return None
        if num_seg != pp and self.schedule != "interleave":
            return None  # virtual chunks only make sense for VPP
        # every schedule consumes microbatches in pp-sized waves
        if self.accumulate_steps % pp != 0:
            return None
        return self._stage_param_lists()

    def _hetero_ok(self, scaler):
        """Gates shared with _can_spmd, minus the homogeneity requirement."""
        if scaler is not None:
            return False
        hcg = self._hcg
        if hcg is None or hcg.get_pipe_parallel_world_size() < 2:
            return False
        mesh = getattr(hcg, "mesh", None)
        if mesh is None or "pp" not in mesh.axis_names:
            return False
        loss_layer = self._layers._loss_fn
        from ....nn.layer.layers import Layer
        if isinstance(loss_layer, Layer) and list(loss_layer.parameters()):
            return False
        pp = hcg.get_pipe_parallel_world_size()
        return self.accumulate_steps % pp == 0

    def _stage_layers_hetero(self):
        """Per-stage layer lists for the heterogeneous SPMD path — no
        homogeneity requirement; num_seg == pp (plain schedules) or a
        multiple of pp (interleaved VPP over heterogeneous virtual
        stages), every member a Layer."""
        from ....nn.layer.layers import Layer
        num_seg = len(self._layers.segment_bounds()) - 1
        pp = self._hcg.get_pipe_parallel_world_size()
        if num_seg % pp != 0:
            return None
        if num_seg != pp and self.schedule != "interleave":
            return None
        stages = []
        for s in range(num_seg):
            ls = list(self._layers.stage_layers(s))
            if any(not isinstance(l, Layer) for l in ls):
                return None
            stages.append(ls)
        return stages

    def _spmd_forward_backward(self, stages, inputs, labels):
        """Run the selected pp_spmd schedule and write grads into .grad."""
        import jax
        import jax.numpy as jnp
        from . import pp_spmd

        mesh = self._hcg.mesh
        num_stages = self._hcg.get_pipe_parallel_world_size()
        num_seg = len(stages)
        num_chunks = num_seg // num_stages
        M = self.accumulate_steps
        loss_fn = self._layers._loss_fn
        schedule = self.schedule
        if schedule == "interleave" and num_chunks == 1:
            schedule = "gpipe"  # VPP with one chunk IS the plain wavefront

        def to_raw(t):
            return t._value if isinstance(t, Tensor) else t

        x = to_raw(inputs)
        lb = to_raw(labels)
        mbs = x.reshape((M, x.shape[0] // M) + x.shape[1:])
        lbs = lb.reshape((M, lb.shape[0] // M) + lb.shape[1:])

        per_stage = [[{k: jnp.asarray(p._value) for k, p in pd.items()}
                      for _, pd in st] for st in stages]

        # pp × MoE (round 5): MoE layers report their load-balance aux
        # loss on ``_last_aux_loss`` after each forward — eager users add
        # it to the objective via the ``aux_loss`` property. The pipeline
        # carry is one static-shape array, so when any ring layer
        # produces aux, the carry grows ONE extra last-axis slot that
        # accumulates each stage's aux (spread over the slot so bf16
        # transport stays precise); the head slices it off and adds it to
        # the loss. Gradients flow through the slice/concat under every
        # schedule.
        moe_aux = any(_aux_layers(layer)
                      for st in stages for layer, _ in st)

        def _apply_layers(layer_list, plist, t):
            aux = jnp.float32(0.0)
            for (layer, _), pd in zip(layer_list, plist):
                t = layer.functional_call(pd, t, training=True)
                for l in _aux_layers(layer):
                    a = l._last_aux_loss
                    if a is not None:
                        aux = aux + to_raw(a).astype(jnp.float32)
            return t, aux

        def stage_fn(stage_params, xin):
            x = xin[..., :-1] if moe_aux else xin
            t, aux = _apply_layers(stages[0], stage_params,
                                   Tensor(x, _internal=True))
            y = to_raw(t)
            if not moe_aux:
                return y
            return _append_aux_slot(y, xin[..., -1:], aux)

        def head_loss(_head, y, label):
            aux = jnp.float32(0.0)
            if moe_aux:
                y, aux = _split_aux_slot(y)
            out = loss_fn(Tensor(y, _internal=True),
                          Tensor(label, _internal=True))
            return to_raw(out) + aux

        if self._spmd_step is None:
            if schedule in ("1f1b", "zero_bubble"):
                def run(stacked, mb, lab):
                    loss, dw, _, _ = pp_spmd.pipeline_1f1b(
                        stage_fn, head_loss, stacked, {}, mb, lab, mesh,
                        defer_dw=(schedule == "zero_bubble"))
                    return loss, dw
            elif schedule == "interleave":
                # the reference's VPP training schedule IS interleaved
                # 1F1B (pipeline_parallel.py:1174) — use the hand-written
                # depth-bounded backward (round 5), not AD through the
                # wavefront, whose residency grows with accumulate_steps
                def run(stacked, mb, lab):
                    loss, dw, _, _ = pp_spmd.pipeline_interleave_1f1b(
                        stage_fn, head_loss, stacked, {}, mb, lab, mesh,
                        num_chunks)
                    return loss, dw
            else:  # gpipe
                def run(stacked, mb, lab):
                    def total(sp):
                        return pp_spmd.pipeline_loss_spmd(
                            stage_fn, head_loss, sp, {}, mb, lab, mesh)
                    return jax.value_and_grad(total)(stacked)
            self._spmd_step = jax.jit(run)

        step = self._spmd_step
        with _obs.span("PP.spmd.stack", "Operator"):
            if schedule == "interleave":
                stacked = pp_spmd.stack_stage_params_interleaved(
                    per_stage, mesh, num_chunks)
            else:
                stacked = pp_spmd.stack_stage_params(per_stage, mesh)
        if moe_aux:  # zeroed aux slot on the carry's last axis
            pad = jnp.zeros(mbs.shape[:-1] + (1,), mbs.dtype)
            mbs = jnp.concatenate([mbs, pad], axis=-1)
        with _obs.span("PP.spmd.step", "Forward"):
            loss, dstacked = step(stacked, mbs, lbs)
        _obs.pp_step(schedule, num_stages, M, num_chunks)

        # scatter grads back into parameter .grad slots
        with _obs.span("PP.spmd.scatter", "Backward"):
            self._scatter_stacked_grads(stages, dstacked, schedule,
                                        num_stages)
        return Tensor(loss, _internal=True)

    def _scatter_stacked_grads(self, stages, dstacked, schedule,
                               num_stages):
        for s, st in enumerate(stages):
            for li, (_, pd) in enumerate(st):
                for k, p in pd.items():
                    if schedule == "interleave":
                        g = dstacked[li][k][s % num_stages, s // num_stages]
                    else:
                        g = dstacked[li][k][s]
                    g = Tensor(g, _internal=True)
                    p.grad = g if p.grad is None else p.grad + g

    # ---------------- heterogeneous SPMD path ----------------
    def _hetero_plan(self, stages, inputs):
        """Probe one microbatch through the stages to find the carry shape
        and the pre/head peel (module docstring). Returns
        (pre_layers, ring_stages, head_layers, carry_shape) or None when
        the activations change shape mid-ring (-> accum fallback)."""
        from ...._core.autograd import no_grad
        m = self.accumulate_steps
        sz = inputs.shape[0] // m
        probe = Tensor(inputs._value[:sz], _internal=True)
        shapes = []   # shapes[s][i] = act shape after layer i of stage s
        with no_grad():
            t = probe
            for st in stages:
                row = []
                for layer in st:
                    t = layer(t)
                    row.append(tuple(t.shape))
                shapes.append(row)
        carry = shapes[0][-1]
        in_shape = tuple(probe.shape)
        # pre peel: feed must be carry-shaped
        if in_shape == carry:
            pre, ring0 = [], list(stages[0])
        else:
            cut = next((i for i, s in enumerate(shapes[0]) if s == carry),
                       None)
            if cut is None:
                return None
            pre = list(stages[0][:cut + 1])
            ring0 = list(stages[0][cut + 1:])
        # head peel: ring's last stage must output carry
        last_shapes = shapes[-1]
        if last_shapes[-1] == carry:
            ringN, head = list(stages[-1]), []
        else:
            keep = 0
            for i, s in enumerate(last_shapes):
                if s == carry:
                    keep = i + 1
            ringN = list(stages[-1][:keep])
            head = list(stages[-1][keep:])
        # mid boundaries must all be carry
        for s in range(1, len(stages) - 1):
            if shapes[s][-1] != carry:
                return None
        ring = [ring0] + [list(st) for st in stages[1:-1]] + [ringN]
        return pre, ring, head, carry

    def _spmd_forward_backward_hetero(self, stages, inputs, labels):
        """Heterogeneous stages: flattened-vector stacking + lax.switch
        dispatch (pp_spmd.pipeline_hetero*); embed-like pre layers run
        before microbatching, head-like exit layers inside the loss."""
        import jax
        import jax.numpy as jnp
        from . import pp_spmd

        if getattr(self, "_hetero_plan_cache", None) is None:
            self._hetero_plan_cache = (self._hetero_plan(stages, inputs),)
        plan = self._hetero_plan_cache[0]
        if plan is None:
            return None
        pre, ring, head, carry = plan
        mesh = self._hcg.mesh
        pp = self._hcg.get_pipe_parallel_world_size()
        num_chunks = len(ring) // pp
        M = self.accumulate_steps
        loss_fn = self._layers._loss_fn
        schedule = self.schedule
        if schedule == "interleave" and num_chunks == 1:
            schedule = "gpipe"  # one stage per coord == plain wavefront

        def to_raw(t):
            return t._value if isinstance(t, Tensor) else t

        def params_of(layers):
            return [{k: jnp.asarray(p._value)
                     for k, p in dict(layer.named_parameters()).items()}
                    for layer in layers]

        # pp × MoE on the hetero path: same aux-slot carry trick as the
        # homogeneous engine (see _spmd_forward_backward) — MoE layers'
        # ``_last_aux_loss`` accumulates in one extra last-axis slot of
        # the static carry and lands in the head loss
        moe_aux = any(_aux_layers(layer)
                      for st in ([pre] + list(ring) + [head]) for layer
                      in st)

        def _apply_raw(layers, plist, t):
            aux = jnp.float32(0.0)
            for layer, pd in zip(layers, plist):
                t = layer.functional_call(pd, t, training=True)
                for l in _aux_layers(layer):
                    a = l._last_aux_loss
                    if a is not None:
                        aux = aux + to_raw(a).astype(jnp.float32)
            return t, aux

        def apply_layers(layers, plist, xin):
            """Ring-stage application over the (possibly aux-augmented)
            carry."""
            if not moe_aux:
                t, _ = _apply_raw(layers, plist,
                                  Tensor(xin, _internal=True))
                return to_raw(t)
            x = xin[..., :-1]
            t, aux = _apply_raw(layers, plist, Tensor(x, _internal=True))
            return _append_aux_slot(to_raw(t), xin[..., -1:], aux)

        x = to_raw(inputs)
        lb = to_raw(labels)
        xmb = x.reshape((M, x.shape[0] // M) + x.shape[1:])
        lbs = lb.reshape((M, lb.shape[0] // M) + lb.shape[1:])

        ring_params = [params_of(st) for st in ring]
        pre_params = params_of(pre)
        head_params = params_of(head)
        if schedule == "interleave":
            vec, specs = pp_spmd.flatten_stage_params_interleaved(
                ring_params, mesh, num_chunks)
        else:
            vec, specs = pp_spmd.flatten_stage_params(ring_params, mesh)
        stage_fns = [
            (lambda plist, xin, st=st: apply_layers(st, plist, xin))
            for st in ring]

        def head_loss(hp, y, lab):
            aux = jnp.float32(0.0)
            if moe_aux:
                y, aux = _split_aux_slot(y)
            t, head_aux = _apply_raw(head, hp, Tensor(y, _internal=True))
            return to_raw(loss_fn(t, Tensor(lab, _internal=True))) + \
                aux + head_aux

        def pre_apply(pp_, mb):
            def one(xi):
                t, aux = _apply_raw(pre, pp_, Tensor(xi, _internal=True))
                y = to_raw(t)
                if not moe_aux:
                    return y
                return _append_aux_slot(
                    y, jnp.zeros(y.shape[:-1] + (1,), y.dtype), aux)
            return jax.vmap(one)(mb)

        # Gradients must ACCUMULATE in f32 even for bf16 params: cotangents
        # match the primal dtype, so the differentiated-against trees are
        # f32 VIEWS, cast back to native dtype before compute (the stored
        # params stay native; the f32 copies are in-graph only).
        def f32_view(tree):
            return jax.tree.map(
                lambda a: a.astype(jnp.float32)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, tree)

        def native_cast(tree, ref):
            return jax.tree.map(lambda a, r: a.astype(r.dtype), tree, ref)

        if self._spmd_step is None:
            if schedule in ("1f1b", "zero_bubble", "interleave"):
                # hand-written depth-bounded backwards. "interleave"
                # (VPP) uses the round-5 interleaved-1F1B program — the
                # reference's VPP training schedule — instead of AD
                # through the wavefront, whose residency grows with
                # accumulate_steps
                def run(v, prp, hdp, mb, lab):
                    mbs, vjp_pre = jax.vjp(
                        lambda q: pre_apply(native_cast(q, prp), mb),
                        f32_view(prp))
                    if schedule == "interleave":
                        loss, dv, dhead, dmbs = \
                            pp_spmd.pipeline_hetero_interleave_1f1b(
                                stage_fns, head_loss, v, specs, hdp,
                                mbs, lab, mesh, num_chunks)
                    else:
                        loss, dv, dhead, dmbs = \
                            pp_spmd.pipeline_hetero_1f1b(
                                stage_fns, head_loss, v, specs, hdp,
                                mbs, lab, mesh,
                                defer_dw=(schedule == "zero_bubble"))
                    dpre = vjp_pre(dmbs.astype(mbs.dtype))[0]
                    return loss, (dv, dpre, dhead)
            else:  # gpipe wavefront, AD backward
                def run(v, prp, hdp, mb, lab):
                    v32 = f32_view(v)

                    def total(v_, prp_, hdp_):
                        mbs = pre_apply(native_cast(prp_, prp), mb)
                        outs = pp_spmd.pipeline_hetero(
                            stage_fns, v_, specs, mbs, mesh)
                        hp = native_cast(hdp_, hdp)
                        losses = jax.vmap(
                            lambda y, l: head_loss(hp, y, l))(outs, lab)
                        return jnp.mean(losses)
                    return jax.value_and_grad(total, argnums=(0, 1, 2))(
                        v32, f32_view(prp), f32_view(hdp))
            self._spmd_step = jax.jit(run)

        with _obs.span("PP.spmd.step", "Forward"):
            loss, (dv, dpre, dhead) = self._spmd_step(
                vec, pre_params, head_params, xmb, lbs)
        _obs.pp_step(schedule, pp, M, num_chunks)

        if schedule == "interleave":
            # {dt: [P, chunks, Lmax]} round-robin -> canonical [V, Lmax]
            dv = jax.tree.map(
                lambda a: jnp.transpose(a, (1, 0, 2)).reshape(
                    len(ring), a.shape[-1]), dv)
        dring = pp_spmd.unflatten_stage_grads(dv, specs)

        def scatter(layers, grads):
            for layer, gd in zip(layers, grads):
                for k, p in dict(layer.named_parameters()).items():
                    g = Tensor(gd[k], _internal=True)
                    p.grad = g if p.grad is None else p.grad + g
        with _obs.span("PP.spmd.scatter", "Backward"):
            for st, gst in zip(ring, dring):
                scatter(st, gst)
            scatter(pre, dpre)
            scatter(head, dhead)
        return Tensor(loss, _internal=True)

    def forward_backward_pipeline(self, data, scaler=None):
        """reference: pipeline_parallel.py:575. Dispatches to the pp_spmd
        schedule selected by pipeline_configs["schedule_mode"]: the
        stacked-stage program for homogeneous stages, the flattened-vector
        + lax.switch program for heterogeneous ones (module docstring);
        grad-accumulation semantics otherwise, with a warning."""
        inputs, labels = data
        simple = (isinstance(inputs, Tensor) and isinstance(labels, Tensor)
                  and inputs.shape[0] % self.accumulate_steps == 0)
        stages = self._can_spmd(scaler)
        if stages is not None and not simple:
            stages = None  # single-tensor, divisible batches only; the
            # accum path handles everything else (and raises clear errors)
        if stages is not None:
            try:
                self.total_loss = self._spmd_forward_backward(
                    stages, inputs, labels)
                return self.total_loss
            except Exception:
                self._spmd_step = None
                raise
        # heterogeneous stages: the vec+switch SPMD program
        if simple and self._hetero_ok(scaler):
            hstages = self._stage_layers_hetero()
            if hstages is not None:
                try:
                    loss = self._spmd_forward_backward_hetero(
                        hstages, inputs, labels)
                except Exception:
                    self._spmd_step = None
                    raise
                if loss is not None:
                    self.total_loss = loss
                    return self.total_loss
        if (self._hcg is not None
                and self._hcg.get_pipe_parallel_world_size() > 1
                and not getattr(self, "_warned_depipelined", False)):
            import warnings
            self._warned_depipelined = True
            warnings.warn(
                "PipelineParallel: stages cannot run the SPMD pipeline "
                "(shape-changing mid-ring activations, non-Layer stage "
                "members, GradScaler, or indivisible batch) — falling "
                "back to sequential gradient accumulation with NO "
                "pipeline parallelism.", stacklevel=2)
        micro_in = self._split_micro(inputs)
        micro_lb = self._split_micro(labels)
        pp_degree = (self._hcg.get_pipe_parallel_world_size()
                     if self._hcg is not None else 1)
        _obs.pp_step("accum", pp_degree, self.accumulate_steps)
        total = None
        for x, y in zip(micro_in, micro_lb):
            with _obs.span("PP.forward", "Forward"):
                out = self._layers(x)
            loss_fn = self._layers._loss_fn
            if loss_fn is None:
                raise RuntimeError("PipelineLayer needs loss_fn for "
                                   "train_batch")
            loss = loss_fn(out, y)
            # MoE layers' load-balance aux joins the objective here too —
            # the SPMD paths carry it in the pipeline carry's aux slot;
            # a fallback that dropped it would make the engine's loss
            # (and the routers' gradients) path-dependent
            for l in _aux_layers(self._layers):
                a = l._last_aux_loss
                if a is not None:
                    loss = loss + a
            scaled = loss / self.accumulate_steps
            if scaler is not None:
                scaled = scaler.scale(scaled)
            with _obs.span("PP.backward", "Backward"):
                _tape_backward(scaled)
            total = loss if total is None else total + loss
        self.total_loss = total / self.accumulate_steps
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """reference: pipeline_parallel.py:820."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)
