"""Pipeline-parallel engine.

Re-design of the reference's PipelineParallel
(reference: python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py — PipelineParallel:255, forward_backward_pipeline:575
(1F1B), train_batch:820, interleave:1174, FthenB:2256; p2p plumbing
pp_utils/p2p_communication.py:573).

TPU-native design. The reference runs one process per stage and threads
activations through eager NCCL p2p; its 1F1B order exists to bound
in-flight activations per worker. Under XLA's single-program model the
schedule is expressed differently:

- **The true pipelined execution** (stages resident on different devices,
  microbatches in flight across the `pp` mesh axis) lives in
  :mod:`pp_spmd` and is the DEFAULT whenever the PipelineLayer's stages are
  homogeneous (same per-stage parameter structure — the stacked-stage
  requirement) and the hybrid topology has a pp axis: the engine stacks
  per-stage params over the pp mesh axis, runs the schedule selected by
  ``strategy.pipeline_configs["schedule_mode"]`` ("1F1B" default,
  "F-then-B"/"gpipe" GPipe, "VPP" interleaved, "ZB" zero-bubble), and
  writes the resulting grads into each parameter's ``.grad`` slot so
  ``optimizer.step()`` works unchanged.
- **Fallback** (heterogeneous stages, pp degree 1, or a GradScaler):
  microbatch grad accumulation — the same loss/grad math without spatial
  parallelism.
"""
from __future__ import annotations

from typing import Any, List, Optional

import numpy as np

from ...._core.tensor import Tensor
from ...._core.autograd import backward as _tape_backward
from .engines import MetaParallelBase
from .parallel_layers import PipelineLayer


class PipelineParallel(MetaParallelBase):
    """reference: meta_parallel/pipeline_parallel.py:255."""

    _SCHEDULES = {"1f1b": "1f1b", "f-then-b": "gpipe", "fthenb": "gpipe",
                  "gpipe": "gpipe", "vpp": "interleave",
                  "interleave": "interleave", "zb": "zero_bubble",
                  "zbh1": "zero_bubble", "zero_bubble": "zero_bubble"}

    def __init__(self, layers: PipelineLayer, hcg, strategy):
        super().__init__(layers, hcg, strategy)
        pc = (strategy.pipeline_configs if strategy is not None else
              {"accumulate_steps": 1})
        self.accumulate_steps = int(pc.get("accumulate_steps", 1))
        self.micro_batch_size = int(pc.get("micro_batch_size", 1))
        mode = str(pc.get("schedule_mode", "1F1B")).lower()
        if mode not in self._SCHEDULES:
            raise ValueError(f"unknown pipeline schedule_mode {mode!r}; "
                             f"one of {sorted(set(self._SCHEDULES))}")
        self.schedule = self._SCHEDULES[mode]
        self.total_loss = None
        self._spmd_step = None  # lazily-built jitted schedule program

    def _split_micro(self, data):
        """Split [B, ...] inputs into accumulate_steps microbatches."""
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return [tuple(p[i] for p in parts)
                    for i in range(self.accumulate_steps)]
        if not isinstance(data, Tensor):
            return [data] * self.accumulate_steps
        b = data.shape[0]
        m = self.accumulate_steps
        if b % m != 0:
            raise ValueError(f"batch {b} not divisible by "
                             f"accumulate_steps {m}")
        sz = b // m
        return [Tensor(data._value[i * sz:(i + 1) * sz], _internal=True)
                for i in range(m)]

    # ---------------- SPMD schedule path ----------------
    def _stage_param_lists(self):
        """Per-stage [stage][layer] name->Parameter dicts, or None when the
        stages are not homogeneous (the stacked-stage requirement)."""
        from ....nn.layer.layers import Layer
        num_seg = len(self._layers.segment_bounds()) - 1
        stages = []
        for s in range(num_seg):
            ls = self._layers.stage_layers(s)
            stages.append([(l, dict(l.named_parameters()))
                           for l in ls if isinstance(l, Layer)])
            if any(not isinstance(l, Layer) for l in ls):
                return None  # plain callables can't be stacked
        ref = [[sorted((k, tuple(p.shape), str(p.dtype))
                       for k, p in lp[1].items()) for lp in stages[0]]]
        for st in stages[1:]:
            sig = [sorted((k, tuple(p.shape), str(p.dtype))
                          for k, p in lp[1].items()) for lp in st]
            if sig != ref[0]:
                return None
        return stages

    def _can_spmd(self, scaler):
        if scaler is not None:
            return None
        hcg = self._hcg
        if hcg is None or hcg.get_pipe_parallel_world_size() < 2:
            return None
        mesh = getattr(hcg, "mesh", None)
        if mesh is None or "pp" not in mesh.axis_names:
            return None
        loss_layer = self._layers._loss_fn
        from ....nn.layer.layers import Layer
        if isinstance(loss_layer, Layer) and list(loss_layer.parameters()):
            return None  # parametric loss heads keep the accum path
        num_seg = len(self._layers.segment_bounds()) - 1
        pp = hcg.get_pipe_parallel_world_size()
        if num_seg % pp != 0:
            return None
        if num_seg != pp and self.schedule != "interleave":
            return None  # virtual chunks only make sense for VPP
        # every schedule consumes microbatches in pp-sized waves
        if self.accumulate_steps % pp != 0:
            return None
        return self._stage_param_lists()

    def _spmd_forward_backward(self, stages, inputs, labels):
        """Run the selected pp_spmd schedule and write grads into .grad."""
        import jax
        import jax.numpy as jnp
        from . import pp_spmd

        mesh = self._hcg.mesh
        num_stages = self._hcg.get_pipe_parallel_world_size()
        num_seg = len(stages)
        num_chunks = num_seg // num_stages
        M = self.accumulate_steps
        loss_fn = self._layers._loss_fn
        schedule = self.schedule
        if schedule == "interleave" and num_chunks == 1:
            schedule = "gpipe"  # VPP with one chunk IS the plain wavefront

        def to_raw(t):
            return t._value if isinstance(t, Tensor) else t

        x = to_raw(inputs)
        lb = to_raw(labels)
        mbs = x.reshape((M, x.shape[0] // M) + x.shape[1:])
        lbs = lb.reshape((M, lb.shape[0] // M) + lb.shape[1:])

        per_stage = [[{k: jnp.asarray(p._value) for k, p in pd.items()}
                      for _, pd in st] for st in stages]

        def stage_fn(stage_params, xin):
            t = Tensor(xin, _internal=True)
            for (layer, _), pd in zip(stages[0], stage_params):
                t = layer.functional_call(pd, t, training=True)
            return to_raw(t)

        def head_loss(_head, y, label):
            out = loss_fn(Tensor(y, _internal=True),
                          Tensor(label, _internal=True))
            return to_raw(out)

        if self._spmd_step is None:
            if schedule in ("1f1b", "zero_bubble"):
                def run(stacked, mb, lab):
                    loss, dw, _, _ = pp_spmd.pipeline_1f1b(
                        stage_fn, head_loss, stacked, {}, mb, lab, mesh,
                        defer_dw=(schedule == "zero_bubble"))
                    return loss, dw
            elif schedule == "interleave":
                def run(stacked, mb, lab):
                    def total(sp):
                        outs = pp_spmd.pipeline_interleave(
                            stage_fn, sp, mb, mesh, num_chunks)
                        return jnp.mean(jax.vmap(
                            lambda y, l: head_loss({}, y, l))(outs, lab))
                    return jax.value_and_grad(total)(stacked)
            else:  # gpipe
                def run(stacked, mb, lab):
                    def total(sp):
                        return pp_spmd.pipeline_loss_spmd(
                            stage_fn, head_loss, sp, {}, mb, lab, mesh)
                    return jax.value_and_grad(total)(stacked)
            self._spmd_step = jax.jit(run)

        step = self._spmd_step
        if schedule == "interleave":
            stacked = pp_spmd.stack_stage_params_interleaved(
                per_stage, mesh, num_chunks)
        else:
            stacked = pp_spmd.stack_stage_params(per_stage, mesh)
        loss, dstacked = step(stacked, mbs, lbs)

        # scatter grads back into parameter .grad slots
        for s, st in enumerate(stages):
            for li, (_, pd) in enumerate(st):
                for k, p in pd.items():
                    if schedule == "interleave":
                        g = dstacked[li][k][s % num_stages, s // num_stages]
                    else:
                        g = dstacked[li][k][s]
                    g = Tensor(g, _internal=True)
                    p.grad = g if p.grad is None else p.grad + g
        return Tensor(loss, _internal=True)

    def forward_backward_pipeline(self, data, scaler=None):
        """reference: pipeline_parallel.py:575. Dispatches to the pp_spmd
        schedule selected by pipeline_configs["schedule_mode"] when the
        stages are stackable (module docstring); grad-accumulation
        semantics otherwise."""
        inputs, labels = data
        stages = self._can_spmd(scaler)
        if stages is not None and not (
                isinstance(inputs, Tensor) and isinstance(labels, Tensor)
                and inputs.shape[0] % self.accumulate_steps == 0):
            stages = None  # single-tensor, divisible batches only; the
            # accum path handles everything else (and raises clear errors)
        if stages is not None:
            try:
                self.total_loss = self._spmd_forward_backward(
                    stages, inputs, labels)
                return self.total_loss
            except Exception:
                self._spmd_step = None
                raise
        micro_in = self._split_micro(inputs)
        micro_lb = self._split_micro(labels)
        total = None
        for x, y in zip(micro_in, micro_lb):
            out = self._layers(x)
            loss_fn = self._layers._loss_fn
            if loss_fn is None:
                raise RuntimeError("PipelineLayer needs loss_fn for "
                                   "train_batch")
            loss = loss_fn(out, y)
            scaled = loss / self.accumulate_steps
            if scaler is not None:
                scaled = scaler.scale(scaled)
            _tape_backward(scaled)
            total = loss if total is None else total + loss
        self.total_loss = total / self.accumulate_steps
        return self.total_loss

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """reference: pipeline_parallel.py:820."""
        self._layers.train()
        loss = self.forward_backward_pipeline(data, scaler)
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss and self._layers._loss_fn is not None:
            return self._layers._loss_fn(out, labels)
        return out

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)
