"""Fleet: the hybrid-parallel orchestration entry.

Re-design of the reference's fleet
(reference: python/paddle/distributed/fleet/fleet.py:151 Fleet, init:218,
_init_hybrid_parallel_env:674, model dispatch fleet/model.py:142-174,
optimizer fleet/optimizer.py:24).

TPU-native: ``fleet.init`` builds ONE global jax Mesh whose axes are the
hybrid-parallel dimensions (default order [dp, pp, sharding, sep, mp] —
the reference's hybrid_parallel_order) and installs it process-wide. All
"subgroup creation" becomes axis views; parameter broadcast at init is
unnecessary (single controller = single source of truth).
"""
from __future__ import annotations

import threading
import time
from typing import Optional

import numpy as np
import jax

from ..._core.tensor import Tensor
from ...nn.layer.layers import Layer
from .base.strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup, AXES
from .. import mesh as _mesh

_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
    "role_maker": None,
}


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _fleet_state["hcg"]


# reference alias: fleet.get_hybrid_communicate_group()
def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """reference: fleet.py:218."""
    if strategy is None:
        strategy = DistributedStrategy()
    if _fleet_state["initialized"]:
        # RE-init starts a fresh topology generation: release the named
        # split-layer cache (mp_ops) so dead layers sharded over retired
        # meshes — whose keys pin those meshes alive — don't accumulate
        # in servers/tests that churn meshes. Loud, not silent: a
        # workflow relying on named-layer reuse ACROSS re-inits (the old
        # no-eviction behavior) would otherwise re-initialize trained
        # weights without a trace.
        from .layers.mpu.mp_ops import reset_split_layer_cache
        n = reset_split_layer_cache()
        if n:
            import warnings
            warnings.warn(
                f"fleet.init re-initialization released {n} named "
                "distributed.split layer(s); the next same-named split "
                "call re-creates them with FRESH weights. Hold trained "
                "layers on a module (or re-create them per generation) "
                "if you re-init fleet mid-run.", stacklevel=2)
    if role_maker is not None and not is_collective:
        # parameter-server mode (reference: fleet.init(role) + the_one_ps
        # runtime): no device mesh — roles split into servers hosting
        # tables and workers training against them over RPC
        _fleet_state["role_maker"] = role_maker
        _fleet_state.update(initialized=True, strategy=strategy, hcg=None)
        return fleet
    # collective mode: worker_num/worker_index must reflect the mesh, so a
    # role maker passed here must not shadow mesh world size/rank
    if role_maker is not None:
        import warnings
        warnings.warn(
            "fleet.init: role_maker is ignored in collective mode "
            "(is_collective=True); pass is_collective=False for "
            "parameter-server mode")
    _fleet_state["role_maker"] = None
    hc = strategy.hybrid_configs
    order = list(hc.get("order") or strategy.hybrid_parallel_order or
                 ["dp", "pp", "sharding", "sep", "mp"])
    degrees = {
        "dp": int(hc.get("dp_degree", 1)),
        "mp": int(hc.get("mp_degree", 1)),
        "pp": int(hc.get("pp_degree", 1)),
        "sharding": int(hc.get("sharding_degree", 1)),
        "sep": int(hc.get("sep_degree", 1)),
    }
    ndev = len(jax.devices())
    prod = int(np.prod([max(d, 1) for d in degrees.values()]))
    if prod > ndev:
        raise ValueError(
            f"hybrid degrees {degrees} need {prod} devices, "
            f"only {ndev} present")
    # fill dp to consume remaining devices (reference: dp_degree=-1 auto)
    if degrees["dp"] <= 0 or (hc.get("dp_degree") in (None, -1)):
        degrees["dp"] = ndev // (prod // max(degrees["dp"], 1))
    dims = [degrees[a] for a in order]
    topo = CommunicateTopology(order, dims)
    hcg = HybridCommunicateGroup(topo)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg)
    return fleet


def distributed_model(model: Layer):
    """reference: fleet/model.py:32 — dispatch on topology (model.py:142-174).
    """
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError("call fleet.init first")
    from ..parallel import DataParallel
    from .meta_parallel.pipeline_parallel import PipelineParallel
    from .meta_parallel.parallel_layers import PipelineLayer
    from .meta_parallel.engines import (TensorParallel, ShardingParallel,
                                        SegmentParallel)
    strategy = _fleet_state["strategy"]
    if hcg.get_pipe_parallel_world_size() > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError(
                "pipeline parallel requires the model to be a PipelineLayer "
                "(reference: meta_parallel/pipeline_parallel.py:255)")
        return PipelineParallel(model, hcg, strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, strategy)
    if hcg.get_sep_parallel_world_size() > 1:
        return SegmentParallel(model, hcg, strategy)
    if hcg.get_sharding_parallel_world_size() > 1:
        return ShardingParallel(model, hcg, strategy)
    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model, group=hcg.get_data_parallel_group())
    return model


def distributed_optimizer(optimizer, strategy=None):
    """reference: fleet/optimizer.py:24 -> HybridParallelOptimizer
    (meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:266);
    strategy-selected meta-optimizers mirror fleet/meta_optimizers/
    (dgc_optimizer.py, localsgd_optimizer.py)."""
    from .meta_optimizers.hybrid_parallel_optimizer import (
        HybridParallelOptimizer)
    hcg = get_hybrid_communicate_group()
    strategy = strategy or _fleet_state["strategy"]
    dp_group = hcg.get_data_parallel_group() if hcg is not None else None
    if strategy is not None and getattr(strategy, "dgc", False):
        from ...optimizer.optimizers import Momentum
        from .meta_optimizers.dgc_optimizer import DGCMomentumOptimizer
        if not isinstance(optimizer, Momentum):
            import warnings
            warnings.warn(
                "strategy.dgc=True requires a Momentum optimizer "
                f"(got {type(optimizer).__name__}); DGC is NOT applied "
                "(reference: DGCOptimizer._can_apply)")
        elif not isinstance(optimizer, DGCMomentumOptimizer):
            cfg = strategy.dgc_configs
            nranks = (dp_group.nranks if dp_group is not None
                      else worker_num())
            optimizer = DGCMomentumOptimizer(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                rampup_begin_step=cfg.get("rampup_begin_step", 0),
                rampup_step=cfg.get("rampup_step", 1),
                sparsity=cfg.get("sparsity", [0.999]),
                # keep param groups (per-group lr/weight_decay overrides)
                parameters=(optimizer._param_groups
                            or optimizer._parameter_list),
                use_nesterov=optimizer._nesterov,
                weight_decay=optimizer._weight_decay,
                grad_clip=optimizer._grad_clip,
                num_trainers=(max(1, nranks)
                              if optimizer._grad_clip is not None else None),
                group=dp_group)
    if strategy is not None and getattr(strategy, "adaptive_localsgd", False):
        from .meta_optimizers.localsgd_optimizer import (
            AdaptiveLocalSGDOptimizer)
        cfg = strategy.adaptive_localsgd_configs
        optimizer = AdaptiveLocalSGDOptimizer(
            optimizer, init_k_steps=cfg.get("init_k_steps", 1),
            begin_step=cfg.get("begin_step", 1), group=dp_group)
    elif strategy is not None and getattr(strategy, "localsgd", False):
        from .meta_optimizers.localsgd_optimizer import LocalSGDOptimizer
        cfg = strategy.localsgd_configs
        optimizer = LocalSGDOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            begin_step=cfg.get("begin_step", 1), group=dp_group)
    return HybridParallelOptimizer(optimizer, hcg, strategy)


def get_strategy():
    return _fleet_state["strategy"]


# ---- parameter-server mode lifecycle (reference: fleet.py init_server
# :1013, run_server:1049, init_worker:944, stop_worker:1084 — the_one_ps
# runtime over brpc; here PsServer/PsClient over the framework RPC) ----

_ps_stop = threading.Event()


def _role_maker():
    rm = _fleet_state.get("role_maker")
    if rm is None:
        raise RuntimeError(
            "PS mode needs fleet.init(role_maker, is_collective=False)")
    return rm


def is_server() -> bool:
    return _role_maker().is_server()


def is_worker() -> bool:
    return _role_maker().is_worker()


def server_num() -> int:
    return max(1, _role_maker()._server_num())


def _srv_shutdown() -> bool:
    """RPC-served: a worker asks this server process to leave run_server."""
    _ps_stop.set()
    return True


_done_lock = threading.Lock()
_done_ranks: set = set()


def _srv_trainer_done(rank: int) -> int:
    """RPC-served on server0: a trainer announces it has finished.
    IDEMPOTENT per rank — a retried post after a lost response must not
    double-count and release the barrier early. ``rank`` is REQUIRED: a
    rank-less caller (version skew) must fail loudly over RPC rather
    than silently collapse onto one set entry and hang the barrier."""
    with _done_lock:
        _done_ranks.add(int(rank))
        return len(_done_ranks)


def _srv_done_count() -> int:
    with _done_lock:
        return len(_done_ranks)


def init_server(*table_configs, model_dir: Optional[str] = None):
    """Start this server's RPC endpoint and host its tables. Extra tables
    arrive later via client ``create_table`` calls (the reference derives
    them from the program; here they are explicit configs).

    ``model_dir``: restore each declared table's shard saved by a prior
    ``save_persistables`` (reference: ``fleet.init_server(dirname)``
    warm-start). Missing shard files are skipped with a warning — a
    fresh table is not an error on first launch.
    """
    import os
    import warnings
    from .. import rpc
    from ..ps import PsServer
    from ..ps.the_one_ps import _tables
    rm = _role_maker()
    idx = rm.worker_index()
    # rendezvous on the servers only: workers register later (the
    # lifecycle guarantees it) and servers never call workers, so waiting
    # for worker .addr files would just eat the full rendezvous deadline
    rpc.init_rpc(f"server{idx}", rank=idx, world_size=server_num())
    _ps_stop.clear()
    with _done_lock:
        _done_ranks.clear()   # stale marks from a prior run must not
                              # satisfy the next run's trainer-done barrier
    _fleet_state["ps_server"] = PsServer(list(table_configs))
    if model_dir is not None:
        for cfg in table_configs:
            shard = os.path.join(model_dir, f"{cfg.name}.shard{idx}.npz")
            if os.path.exists(shard):
                _tables[cfg.name].load(shard)
            else:
                warnings.warn(f"init_server: no shard {shard} to "
                              f"warm-start table {cfg.name!r}; starting "
                              f"fresh")


def run_server():
    """Serve until a worker calls :func:`stop_worker` (which shuts the
    servers down) — reference ``fleet.run_server`` blocks the same way."""
    _ps_stop.wait()
    from .. import rpc
    rpc.shutdown()


def init_worker(*table_configs):
    """Connect to the servers, create the declared tables, and install
    the strategy-selected communicator (sync / async / geo —
    ``strategy.a_sync`` + ``a_sync_configs['k_steps']``)."""
    from .. import rpc
    from ..ps import PsClient, create_communicator
    rm = _role_maker()
    n_srv = server_num()
    idx = rm.worker_index()
    # wait for the servers + this worker; sibling workers are never
    # called directly, so don't block on their registration
    rpc.init_rpc(f"worker{idx}", rank=n_srv + idx,
                 world_size=n_srv + 1)
    server_names = [f"server{i}" for i in range(n_srv)]
    # the count wait can be satisfied by sibling workers racing ahead of
    # a slow server — insist on the actual server names
    rpc.wait_for_workers(server_names)
    client = PsClient(server_names)
    comm = create_communicator(client, _fleet_state["strategy"],
                               trainer_num=rm.worker_num())
    for cfg in table_configs:
        comm.create_table(cfg)   # geo records the table lr here
    _fleet_state["ps_comm"] = comm
    return comm


def get_ps_client():
    """The worker-side communicator installed by :func:`init_worker`."""
    return _fleet_state.get("ps_comm")


def save_persistables(dirname: str, *args, **kwargs):
    """Persist every table to per-server shard files (reference:
    fleet.save_persistables). Geo/async state is synced/flushed first so
    the checkpoint reflects all trainer movement."""
    from ..ps import AsyncCommunicator, GeoCommunicator
    comm = _fleet_state.get("ps_comm")
    if comm is None:
        raise RuntimeError("fleet.init_worker first")
    if isinstance(comm, GeoCommunicator):
        comm.sync()
    elif isinstance(comm, AsyncCommunicator):
        comm.flush()
    comm.save_persistables(dirname)


def load_persistables(dirname: str, *args, **kwargs):
    """Restore tables from shard files (reference: fleet PS load — the
    shard partition is the mod-hash, so the server count must match)."""
    from ..ps import AsyncCommunicator, GeoCommunicator
    comm = _fleet_state.get("ps_comm")
    if comm is None:
        raise RuntimeError("fleet.init_worker first")
    if isinstance(comm, AsyncCommunicator):
        comm.flush()   # queued pre-load grads must not land on top of
                       # the restored tables
    comm.load_persistables(dirname)
    if isinstance(comm, GeoCommunicator):
        comm.invalidate()   # local copies predate the load


def stop_worker(barrier_timeout: float = 120.0):
    """Flush/stop the communicator, rendezvous all trainers, then ask the
    servers to shut down (first worker only, mirroring the reference's
    barrier-then-stop in ``fleet.stop_worker``), release RPC.

    The rendezvous rides server0 as a counter host: every trainer posts
    ``_srv_trainer_done``; the first worker waits until the count reaches
    ``worker_num()`` so it cannot shut the servers down while a sibling
    trainer is still pushing/pulling."""
    import warnings
    from .. import rpc
    from ..ps import AsyncCommunicator, GeoCommunicator
    comm = _fleet_state.pop("ps_comm", None)
    if isinstance(comm, GeoCommunicator):
        comm.sync()
    elif isinstance(comm, AsyncCommunicator):
        comm.stop()
    rm = _fleet_state.get("role_maker")
    if rm is not None:
        n_trainers = worker_num()
        # post this trainer's done mark; retry briefly — a connect burst
        # of N trainers can reset a connection, and a lost post either
        # defeats the barrier (first worker) or stalls it (sibling)
        posted = False
        my_rank = rm.worker_index()
        for _ in range(5):
            try:
                # idempotent per rank: a retry after a LOST RESPONSE (the
                # request may have executed) cannot double-count
                rpc.rpc_sync("server0", _srv_trainer_done,
                             args=(my_rank,),
                             timeout=max(min(barrier_timeout, 10.0), 1.0))
                posted = True
                break
            except Exception:
                time.sleep(0.2)
        if not posted:
            warnings.warn("stop_worker: could not post trainer-done to "
                          "server0 after retries; the barrier will wait "
                          "for the sibling trainers only")
        if rm.is_first_worker():
            # without our own post the count tops out at n_trainers-1 —
            # still wait for every SIBLING before shutting servers down
            target = n_trainers if posted else n_trainers - 1
            if n_trainers > 1 and target > 0:
                deadline = time.time() + barrier_timeout
                consec_fail = 0
                while time.time() < deadline:
                    remaining = max(deadline - time.time(), 1.0)
                    try:
                        if rpc.rpc_sync("server0", _srv_done_count,
                                        timeout=min(remaining, 10.0)) \
                                >= target:
                            break
                        consec_fail = 0
                    except Exception:
                        # transient resets recover; a dead server0 fails
                        # every poll — give up after a short streak
                        # instead of riding out the full deadline
                        consec_fail += 1
                        if consec_fail >= 20:
                            warnings.warn(
                                "stop_worker: server0 unreachable for 20 "
                                "consecutive barrier polls; assuming "
                                "servers are gone")
                            break
                    time.sleep(0.1)
                else:
                    warnings.warn(
                        "stop_worker: trainer barrier timed out after "
                        f"{barrier_timeout}s; shutting servers down anyway")
            for i in range(server_num()):
                try:
                    rpc.rpc_sync(f"server{i}", _srv_shutdown)
                except Exception:
                    pass  # server already gone
    rpc.shutdown()


def worker_num() -> int:
    rm = _fleet_state.get("role_maker")
    return rm.worker_num() if rm is not None else _mesh.get_world_size()


def worker_index() -> int:
    rm = _fleet_state.get("role_maker")
    return rm.worker_index() if rm is not None else _mesh.get_rank()


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker():
    _mesh.barrier()


class _FleetModule:
    """Callable-attribute facade matching ``paddle.distributed.fleet``."""
    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    get_hybrid_communicate_group = staticmethod(get_hybrid_communicate_group)
    DistributedStrategy = DistributedStrategy
    worker_num = staticmethod(worker_num)
    worker_index = staticmethod(worker_index)
    is_first_worker = staticmethod(is_first_worker)
    barrier_worker = staticmethod(barrier_worker)
    # PS mode
    is_server = staticmethod(is_server)
    is_worker = staticmethod(is_worker)
    server_num = staticmethod(server_num)
    init_server = staticmethod(init_server)
    run_server = staticmethod(run_server)
    init_worker = staticmethod(init_worker)
    get_ps_client = staticmethod(get_ps_client)
    stop_worker = staticmethod(stop_worker)
    save_persistables = staticmethod(save_persistables)
    load_persistables = staticmethod(load_persistables)


fleet = _FleetModule()
