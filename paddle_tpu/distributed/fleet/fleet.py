"""Fleet: the hybrid-parallel orchestration entry.

Re-design of the reference's fleet
(reference: python/paddle/distributed/fleet/fleet.py:151 Fleet, init:218,
_init_hybrid_parallel_env:674, model dispatch fleet/model.py:142-174,
optimizer fleet/optimizer.py:24).

TPU-native: ``fleet.init`` builds ONE global jax Mesh whose axes are the
hybrid-parallel dimensions (default order [dp, pp, sharding, sep, mp] —
the reference's hybrid_parallel_order) and installs it process-wide. All
"subgroup creation" becomes axis views; parameter broadcast at init is
unnecessary (single controller = single source of truth).
"""
from __future__ import annotations

from typing import Optional

import numpy as np
import jax

from ..._core.tensor import Tensor
from ...nn.layer.layers import Layer
from .base.strategy import DistributedStrategy
from .base.topology import CommunicateTopology, HybridCommunicateGroup, AXES
from .. import mesh as _mesh

_fleet_state = {
    "initialized": False,
    "strategy": None,
    "hcg": None,
}


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _fleet_state["hcg"]


# reference alias: fleet.get_hybrid_communicate_group()
def init(role_maker=None, is_collective: bool = True,
         strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """reference: fleet.py:218."""
    if strategy is None:
        strategy = DistributedStrategy()
    hc = strategy.hybrid_configs
    order = list(hc.get("order") or strategy.hybrid_parallel_order or
                 ["dp", "pp", "sharding", "sep", "mp"])
    degrees = {
        "dp": int(hc.get("dp_degree", 1)),
        "mp": int(hc.get("mp_degree", 1)),
        "pp": int(hc.get("pp_degree", 1)),
        "sharding": int(hc.get("sharding_degree", 1)),
        "sep": int(hc.get("sep_degree", 1)),
    }
    ndev = len(jax.devices())
    prod = int(np.prod([max(d, 1) for d in degrees.values()]))
    if prod > ndev:
        raise ValueError(
            f"hybrid degrees {degrees} need {prod} devices, "
            f"only {ndev} present")
    # fill dp to consume remaining devices (reference: dp_degree=-1 auto)
    if degrees["dp"] <= 0 or (hc.get("dp_degree") in (None, -1)):
        degrees["dp"] = ndev // (prod // max(degrees["dp"], 1))
    dims = [degrees[a] for a in order]
    topo = CommunicateTopology(order, dims)
    hcg = HybridCommunicateGroup(topo)
    _fleet_state.update(initialized=True, strategy=strategy, hcg=hcg)
    return fleet


def distributed_model(model: Layer):
    """reference: fleet/model.py:32 — dispatch on topology (model.py:142-174).
    """
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        raise RuntimeError("call fleet.init first")
    from ..parallel import DataParallel
    from .meta_parallel.pipeline_parallel import PipelineParallel
    from .meta_parallel.parallel_layers import PipelineLayer
    from .meta_parallel.engines import (TensorParallel, ShardingParallel,
                                        SegmentParallel)
    strategy = _fleet_state["strategy"]
    if hcg.get_pipe_parallel_world_size() > 1:
        if not isinstance(model, PipelineLayer):
            raise TypeError(
                "pipeline parallel requires the model to be a PipelineLayer "
                "(reference: meta_parallel/pipeline_parallel.py:255)")
        return PipelineParallel(model, hcg, strategy)
    if hcg.get_model_parallel_world_size() > 1:
        return TensorParallel(model, hcg, strategy)
    if hcg.get_sep_parallel_world_size() > 1:
        return SegmentParallel(model, hcg, strategy)
    if hcg.get_sharding_parallel_world_size() > 1:
        return ShardingParallel(model, hcg, strategy)
    if hcg.get_data_parallel_world_size() > 1:
        return DataParallel(model, group=hcg.get_data_parallel_group())
    return model


def distributed_optimizer(optimizer, strategy=None):
    """reference: fleet/optimizer.py:24 -> HybridParallelOptimizer
    (meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:266);
    strategy-selected meta-optimizers mirror fleet/meta_optimizers/
    (dgc_optimizer.py, localsgd_optimizer.py)."""
    from .meta_optimizers.hybrid_parallel_optimizer import (
        HybridParallelOptimizer)
    hcg = get_hybrid_communicate_group()
    strategy = strategy or _fleet_state["strategy"]
    dp_group = hcg.get_data_parallel_group() if hcg is not None else None
    if strategy is not None and getattr(strategy, "dgc", False):
        from ...optimizer.optimizers import Momentum
        from .meta_optimizers.dgc_optimizer import DGCMomentumOptimizer
        if not isinstance(optimizer, Momentum):
            import warnings
            warnings.warn(
                "strategy.dgc=True requires a Momentum optimizer "
                f"(got {type(optimizer).__name__}); DGC is NOT applied "
                "(reference: DGCOptimizer._can_apply)")
        elif not isinstance(optimizer, DGCMomentumOptimizer):
            cfg = strategy.dgc_configs
            nranks = (dp_group.nranks if dp_group is not None
                      else worker_num())
            optimizer = DGCMomentumOptimizer(
                learning_rate=optimizer._learning_rate,
                momentum=optimizer._momentum,
                rampup_begin_step=cfg.get("rampup_begin_step", 0),
                rampup_step=cfg.get("rampup_step", 1),
                sparsity=cfg.get("sparsity", [0.999]),
                # keep param groups (per-group lr/weight_decay overrides)
                parameters=(optimizer._param_groups
                            or optimizer._parameter_list),
                use_nesterov=optimizer._nesterov,
                weight_decay=optimizer._weight_decay,
                grad_clip=optimizer._grad_clip,
                num_trainers=(max(1, nranks)
                              if optimizer._grad_clip is not None else None),
                group=dp_group)
    if strategy is not None and getattr(strategy, "adaptive_localsgd", False):
        from .meta_optimizers.localsgd_optimizer import (
            AdaptiveLocalSGDOptimizer)
        cfg = strategy.adaptive_localsgd_configs
        optimizer = AdaptiveLocalSGDOptimizer(
            optimizer, init_k_steps=cfg.get("init_k_steps", 1),
            begin_step=cfg.get("begin_step", 1), group=dp_group)
    elif strategy is not None and getattr(strategy, "localsgd", False):
        from .meta_optimizers.localsgd_optimizer import LocalSGDOptimizer
        cfg = strategy.localsgd_configs
        optimizer = LocalSGDOptimizer(
            optimizer, k_steps=cfg.get("k_steps", 1),
            begin_step=cfg.get("begin_step", 1), group=dp_group)
    return HybridParallelOptimizer(optimizer, hcg, strategy)


def get_strategy():
    return _fleet_state["strategy"]


def worker_num() -> int:
    return _mesh.get_world_size()


def worker_index() -> int:
    return _mesh.get_rank()


def is_first_worker() -> bool:
    return worker_index() == 0


def barrier_worker():
    _mesh.barrier()


class _FleetModule:
    """Callable-attribute facade matching ``paddle.distributed.fleet``."""
    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    get_hybrid_communicate_group = staticmethod(get_hybrid_communicate_group)
    DistributedStrategy = DistributedStrategy
    worker_num = staticmethod(worker_num)
    worker_index = staticmethod(worker_index)
    is_first_worker = staticmethod(is_first_worker)
    barrier_worker = staticmethod(barrier_worker)


fleet = _FleetModule()
