"""paddle_tpu.distributed.fleet — hybrid-parallel orchestration.

Mirrors ``paddle.distributed.fleet``
(reference: python/paddle/distributed/fleet/__init__.py).
"""
from .fleet import (  # noqa: F401
    init, distributed_model, distributed_optimizer,
    get_hybrid_communicate_group, get_strategy, worker_num, worker_index,
    is_first_worker, barrier_worker,
    # PS-mode lifecycle (reference: fleet.init_server/run_server/
    # init_worker/stop_worker)
    is_server, is_worker, server_num, init_server, run_server,
    init_worker, get_ps_client, stop_worker, save_persistables,
    load_persistables,
)
from .base.strategy import DistributedStrategy  # noqa: F401
from .base.topology import (  # noqa: F401
    CommunicateTopology, HybridCommunicateGroup,
)
from . import meta_parallel  # noqa: F401
from .recompute.recompute import recompute, recompute_sequential  # noqa: F401
from .utils import sequence_parallel_utils  # noqa: F401
from .base.role_maker import (  # noqa: F401
    Role, PaddleCloudRoleMaker, UserDefinedRoleMaker,
)
from .base.util_base import UtilBase  # noqa: F401
from .dataset import InMemoryDataset, QueueDataset, DatasetBase  # noqa: F401
from .data_generator import (  # noqa: F401
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)
from .fleet import _FleetModule as Fleet  # noqa: F401
# util singleton (reference: fleet.util is a UtilBase)
util = UtilBase()
