"""Recompute (activation checkpointing).

Re-design of the reference's recompute
(reference: python/paddle/distributed/fleet/recompute/recompute.py —
RecomputeFunction:124 (PyLayer saving inputs + RNG state, replaying forward
in backward), recompute:455).

TPU-native: ``jax.checkpoint`` (remat) IS this mechanism, applied at trace
level — the compiled backward recomputes the block instead of storing
activations, trading MXU FLOPs for HBM. RNG parity comes free: random draws
inside the block bake their (eagerly drawn) keys into the trace, so the
remat replay sees identical randomness — the reference's
preserve_rng_state=True contract without state snapshots.

Parameters of a wrapped Layer are passed explicitly into the rematted
function so the tape differentiates through them (they would otherwise be
closure constants).
"""
from __future__ import annotations

from typing import Any, Callable

import jax

from ...._core.tensor import Tensor
from ...._core import autograd as ag
from ....nn.layer.layers import Layer


def recompute(function: Callable, *args, preserve_rng_state: bool = True,
              use_reentrant: bool = True, **kwargs):
    """reference: recompute.py:455."""
    layer = None
    if isinstance(function, Layer):
        layer = function
    elif hasattr(function, "__self__") and isinstance(function.__self__,
                                                      Layer):
        layer = function.__self__

    named = dict(layer.named_parameters()) if layer is not None else {}
    pnames = list(named)
    ptensors = [named[k] for k in pnames]

    tensor_idx = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    tensor_args = [args[i] for i in tensor_idx]
    kw_keys = [k for k, v in kwargs.items() if isinstance(v, Tensor)]
    kw_tensors = [kwargs[k] for k in kw_keys]

    def raw_fn(*raws):
        n_in = len(tensor_idx)
        n_kw = len(kw_keys)
        in_vals = raws[:n_in]
        kw_vals = raws[n_in:n_in + n_kw]
        p_vals = raws[n_in + n_kw:]
        call_args = list(args)
        for j, i in enumerate(tensor_idx):
            t = Tensor(in_vals[j], _internal=True)
            t.stop_gradient = args[i].stop_gradient
            call_args[i] = t
        call_kwargs = dict(kwargs)
        for j, k in enumerate(kw_keys):
            t = Tensor(kw_vals[j], _internal=True)
            t.stop_gradient = kwargs[k].stop_gradient
            call_kwargs[k] = t
        params = {k: v for k, v in zip(pnames, p_vals)}

        def run():
            return function(*call_args, **call_kwargs)

        if layer is not None:
            out = layer.functional_call(params, forward_fn=run)
        else:
            out = run()
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out

    ckpt = jax.checkpoint(raw_fn)
    return ag.apply(ckpt, *tensor_args, *kw_tensors, *ptensors,
                    name="recompute")


def recompute_sequential(ctx, functions, *args, **kwargs):
    """reference: recompute.py recompute_sequential — chunked Sequential
    recompute."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    chunk = max(len(layers) // max(segments, 1), 1)
    out = args
    i = 0
    while i < len(layers):
        seg = layers[i:i + chunk]

        class _Seg(Layer):
            def __init__(self, ls):
                super().__init__()
                from ....nn.layer.layers import LayerList
                self.ls = LayerList(ls)

            def forward(self, *xs):
                y = xs
                for l in self.ls:
                    y = l(*y) if isinstance(y, tuple) else l(y)
                return y

        seg_layer = _Seg(seg)
        res = recompute(seg_layer, *(out if isinstance(out, tuple) else
                                     (out,)), **kwargs)
        out = res
        i += chunk
    return out
