"""Preemption-safe rolling checkpointer + the elastic train loop glue.

reference: the reference couples fleet/elastic/manager.py (etcd scale
events, ELASTIC_EXIT_CODE relaunch) with per-job checkpoint scripts; it has
no built-in checkpoint-on-signal. On TPU, preemption (maintenance events /
spot reclaim) is the common failure, so the loop is first-class here:

    ckpt = ElasticCheckpointer(dir)
    manager.on_preemption(lambda: ckpt.save(step, state_fn()))
    start = ckpt.latest_step() + 1  # resume point after relaunch

Writes are atomic (tmp file + rename) so a kill mid-save can never corrupt
the latest checkpoint; ``keep`` old checkpoints are retained.
"""
from __future__ import annotations

import os
import re
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

_CKPT_RE = re.compile(r"^ckpt_(\d+)\.pdparams$")


class ElasticCheckpointer:
    def __init__(self, directory: str, keep: int = 2):
        self.dir = directory
        self.keep = int(keep)
        self._lock = threading.RLock()
        os.makedirs(directory, exist_ok=True)

    def _path(self, step: int) -> str:
        return os.path.join(self.dir, f"ckpt_{step}.pdparams")

    def steps(self) -> List[int]:
        out = []
        for fn in os.listdir(self.dir):
            m = _CKPT_RE.match(fn)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_step(self) -> int:
        s = self.steps()
        return s[-1] if s else -1

    def save(self, step: int, state: Dict[str, Any]):
        """Atomic: write tmp, fsync, rename. The RLock makes the SIGTERM
        handler's save safe even when it interrupts a periodic save on the
        main thread (signal handlers run on the thread that holds the
        lock — a plain Lock would self-deadlock)."""
        from ....framework.io import save as _save
        with self._lock:
            tmp = self._path(step) + ".tmp"
            _save(state, tmp)
            with open(tmp, "rb") as f:
                os.fsync(f.fileno())
            os.replace(tmp, self._path(step))
            for s in self.steps()[:-self.keep]:
                try:
                    os.remove(self._path(s))
                except OSError:
                    pass

    def load_latest(self) -> Tuple[int, Optional[Dict[str, Any]]]:
        from ....framework.io import load as _load
        with self._lock:
            s = self.latest_step()
            if s < 0:
                return -1, None
            return s, _load(self._path(s))


def elastic_train(train_one_step: Callable[[int], Any],
                  state_fn: Callable[[], Dict[str, Any]],
                  restore_fn: Callable[[Dict[str, Any]], None],
                  num_steps: int,
                  checkpointer: ElasticCheckpointer,
                  manager=None,
                  save_every: int = 0,
                  watch_scale: bool = False,
                  scale_interval: float = 2.0,
                  scale_ttl: float = 60.0) -> int:
    """Run ``train_one_step(step)`` for steps [resume..num_steps), with
    preemption-safe checkpointing:

    - on entry, restores the latest checkpoint (the post-relaunch resume);
    - installs a SIGTERM handler that requests a checkpoint; at the NEXT
      step boundary the consistent state is saved and the process exits
      with ELASTIC_EXIT_CODE=101 (the launch controller relaunches);
    - optionally checkpoints every ``save_every`` steps as well;
    - ``watch_scale=True``: registers this rank in the manager's
      registry, heartbeats every step, and watches for N→M world-size
      changes (a dead rank past its TTL, or a joiner) — a scale event
      records the new np for the launch controller and takes the same
      checkpoint-then-exit-101 path, so the relaunch re-forms the mesh
      at the new size and resumes from the shared checkpoint
      (reference: fleet/elastic/manager.py:125 etcd scale watch).

    Returns the first step that was NOT run (== num_steps on completion).
    """
    import os as _os
    from .manager import ElasticManager, ELASTIC_EXIT_CODE
    if manager is None:
        manager = ElasticManager()
    start, state = checkpointer.load_latest()
    if state is not None:
        restore_fn(state)

    # SIGTERM only SETS A FLAG; the save happens at the next step boundary.
    # Saving inside the signal handler would capture a torn state (the
    # handler can interrupt optimizer.step mid-parameter-update).
    preempted = {"flag": False}
    manager.on_preemption(lambda: preempted.update(flag=True),
                          exit_after=False)
    hb_stop = None
    if watch_scale:
        manager.register()

        def on_scale(n, survivors):
            manager.write_scale_event(n, survivors)
            preempted.update(flag=True)
        manager.watch_scale(on_scale, interval=scale_interval,
                            ttl=scale_ttl)
        # heartbeat on its OWN thread: a step longer than the TTL must
        # not read as this rank's death
        hb_stop = threading.Event()
        hb_period = max(min(scale_ttl / 4.0, 5.0), 0.05)

        def _beat():
            while not hb_stop.is_set():
                manager.heartbeat()
                hb_stop.wait(hb_period)
        hb_thread = threading.Thread(target=_beat, daemon=True)
        hb_thread.start()
    try:
        for step in range(start + 1, num_steps):
            train_one_step(step)
            if preempted["flag"]:
                checkpointer.save(step, state_fn())
                _os._exit(ELASTIC_EXIT_CODE)
            if save_every and (step + 1) % save_every == 0:
                checkpointer.save(step, state_fn())
        checkpointer.save(num_steps - 1, state_fn())
    finally:
        if hb_stop is not None:
            hb_stop.set()
            # a racing beat could re-register and erase the tombstone
            # AFTER exit() below — join first
            hb_thread.join(timeout=5)
    if watch_scale:
        manager.exit()   # tombstone: completion is not a scale event
    return num_steps
