"""Elastic training manager (reference: python/paddle/distributed/fleet/
elastic/manager.py:125 ElasticManager — etcd rank registry, scale watch,
ELASTIC_EXIT_CODE=101 relaunch :33; LauncherInterface :57).

TPU-native: the registry is a filesystem KV (shared FS / GCS-fuse mount,
the TPU-pod idiom) instead of etcd, and the hot signal is *preemption*:
Cloud TPU VMs receive a maintenance-event notice; ``ElasticManager``
watches for it (env hook) and triggers checkpoint-then-exit(101), which
the launch controller turns into a relaunch that resumes from the last
checkpoint.
"""
from __future__ import annotations

import enum
import json
import os
import signal
import threading
import time
from typing import Callable, Optional

ELASTIC_EXIT_CODE = 101


class ElasticStatus(enum.Enum):
    """reference: manager.py ElasticStatus."""
    COMPLETED = 0
    ERROR = 1
    HOLD = 2
    RESTART = 3
    EXIT = 4


class ElasticManager:
    def __init__(self, args=None, registry_dir: Optional[str] = None,
                 job_id: Optional[str] = None,
                 np: Optional[int] = None):
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        self.np = np or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.registry_dir = registry_dir or os.environ.get(
            "PADDLE_ELASTIC_REGISTRY", "/tmp/paddle_tpu_elastic")
        self.enable = self.np > 1 or bool(
            os.environ.get("PADDLE_ELASTIC_SERVER"))
        self._stop = threading.Event()
        self._preempt_cb: Optional[Callable] = None
        self._watcher: Optional[threading.Thread] = None

    # ---- registry (reference: etcd node registration) ----
    def _node_path(self, rank):
        return os.path.join(self.registry_dir, self.job_id,
                            f"rank_{rank}.json")

    def register(self):
        path = self._node_path(self.rank)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"rank": self.rank, "pid": os.getpid(),
                       "ts": time.time()}, f)

    def deregister(self):
        try:
            os.remove(self._node_path(self.rank))
        except OSError:
            pass

    def alive_nodes(self, ttl: float = 60.0):
        base = os.path.join(self.registry_dir, self.job_id)
        out = []
        if not os.path.isdir(base):
            return out
        now = time.time()
        for fn in os.listdir(base):
            try:
                with open(os.path.join(base, fn)) as f:
                    d = json.load(f)
                if now - d["ts"] < ttl:
                    out.append(d["rank"])
            except Exception:
                pass
        return sorted(out)

    def heartbeat(self):
        self.register()

    # ---- health / scale decision (reference: manager._match) ----
    def match(self) -> bool:
        return len(self.alive_nodes()) == self.np

    def wait(self, timeout: float = 300.0) -> bool:
        t0 = time.time()
        while time.time() - t0 < timeout:
            if self.match():
                return True
            time.sleep(1.0)
        return False

    # ---- preemption (TPU maintenance events) ----
    def on_preemption(self, callback: Callable, exit_after: bool = True):
        """Register checkpoint-and-exit callback; triggered by SIGTERM (the
        Cloud TPU preemption notice) or the watch file. ``exit_after=False``
        only runs the callback (for loops that defer the checkpoint to a
        step boundary and exit themselves — see elastic_train)."""
        self._preempt_cb = callback
        self._exit_after = exit_after
        signal.signal(signal.SIGTERM, self._handle)

    def _handle(self, signum, frame):
        if self._preempt_cb:
            self._preempt_cb()
        if getattr(self, "_exit_after", True):
            os._exit(ELASTIC_EXIT_CODE)

    def watch_preemption_file(self, path: str, interval: float = 5.0):
        """Poll a maintenance-notice file (GCE metadata watcher writes it)."""
        def loop():
            while not self._stop.is_set():
                if os.path.exists(path):
                    self._handle(None, None)
                time.sleep(interval)
        self._watcher = threading.Thread(target=loop, daemon=True)
        self._watcher.start()

    def exit(self, completed: bool = True) -> ElasticStatus:
        self._stop.set()
        self.deregister()
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR
