"""Elastic training manager (reference: python/paddle/distributed/fleet/
elastic/manager.py:125 ElasticManager — etcd rank registry, scale watch,
ELASTIC_EXIT_CODE=101 relaunch :33; LauncherInterface :57).

TPU-native: the registry is a filesystem KV (shared FS / GCS-fuse mount,
the TPU-pod idiom) instead of etcd, and the hot signal is *preemption*:
Cloud TPU VMs receive a maintenance-event notice; ``ElasticManager``
watches for it (env hook) and triggers checkpoint-then-exit(101), which
the launch controller turns into a relaunch that resumes from the last
checkpoint.
"""
from __future__ import annotations

import enum
import json
import os
import signal
import threading
import time
from typing import Callable, Optional

ELASTIC_EXIT_CODE = 101


class ElasticStatus(enum.Enum):
    """reference: manager.py ElasticStatus."""
    COMPLETED = 0
    ERROR = 1
    HOLD = 2
    RESTART = 3
    EXIT = 4


class ElasticManager:
    def __init__(self, args=None, registry_dir: Optional[str] = None,
                 job_id: Optional[str] = None,
                 np: Optional[int] = None):
        self.job_id = job_id or os.environ.get("PADDLE_JOB_ID", "default")
        self.np = np or int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
        self.rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self.registry_dir = registry_dir or os.environ.get(
            "PADDLE_ELASTIC_REGISTRY", "/tmp/paddle_tpu_elastic")
        self.enable = self.np > 1 or bool(
            os.environ.get("PADDLE_ELASTIC_SERVER"))
        self._stop = threading.Event()
        self._preempt_cb: Optional[Callable] = None
        self._watcher: Optional[threading.Thread] = None

    # ---- registry (reference: etcd node registration) ----
    def _node_path(self, rank):
        return os.path.join(self.registry_dir, self.job_id,
                            f"rank_{rank}.json")

    def register(self):
        path = self._node_path(self.rank)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            json.dump({"rank": self.rank, "pid": os.getpid(),
                       "ts": time.time()}, f)
        try:   # a fresh generation of this rank clears its tombstone
            os.remove(self._done_path(self.rank))
        except OSError:
            pass

    def deregister(self, completed: bool = False):
        """``completed=True`` leaves a tombstone so sibling watchers can
        tell normal completion from a crash — only a vanished rank with
        NO tombstone is a scale event."""
        try:
            os.remove(self._node_path(self.rank))
        except OSError:
            pass
        if completed:
            path = self._done_path(self.rank)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                json.dump({"rank": self.rank, "ts": time.time()}, f)

    def _done_path(self, rank):
        return os.path.join(self.registry_dir, self.job_id,
                            f"rank_{rank}.done")

    def done_ranks(self):
        """Ranks (< np) that completed normally this generation."""
        base = os.path.join(self.registry_dir, self.job_id)
        out = []
        if not os.path.isdir(base):
            return out
        for r in range(self.np):
            if os.path.exists(self._done_path(r)):
                out.append(r)
        return out

    def alive_nodes(self, ttl: float = 60.0):
        base = os.path.join(self.registry_dir, self.job_id)
        out = []
        if not os.path.isdir(base):
            return out
        now = time.time()
        for fn in os.listdir(base):
            # node files only — tombstones (.done) and scale records
            # share the directory and must not read as live ranks
            if not (fn.startswith("rank_") and fn.endswith(".json")):
                continue
            try:
                with open(os.path.join(base, fn)) as f:
                    d = json.load(f)
                if now - d["ts"] < ttl:
                    out.append(d["rank"])
            except Exception:
                pass
        return sorted(out)

    def heartbeat(self):
        self.register()

    # ---- health / scale decision (reference: manager._match) ----
    def match(self) -> bool:
        return len(self.alive_nodes()) == self.np

    def wait(self, timeout: float = 300.0) -> bool:
        t0 = time.time()
        while time.time() - t0 < timeout:
            if self.match():
                return True
            time.sleep(1.0)
        return False

    # ---- scale events: N -> M rank changes (reference: manager.py:125
    # watches etcd for node count changes and re-forms the job) ----
    def _scale_path(self) -> str:
        return os.path.join(self.registry_dir, self.job_id, "new_np")

    def write_scale_event(self, n: int, survivors=None):
        """Record the re-formed world for the launch controller(s):
        new size, the surviving GLOBAL ranks (so hosts can renumber
        contiguously and losers retire), and a timestamp (stale events
        from an aborted prior run must not shrink a fresh job)."""
        path = self._scale_path()
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"np": int(n),
                       "survivors": sorted(int(r) for r in survivors)
                       if survivors is not None else None,
                       "ts": time.time()}, f)
        os.replace(tmp, path)

    # back-compat spelling
    def write_new_np(self, n: int):
        self.write_scale_event(n)

    def read_scale_event(self, clear: bool = False,
                         max_age: float = 3600.0) -> Optional[dict]:
        """``clear=False`` lets every host's controller read the same
        event (multi-host); the writer's next generation or ``clear``
        removes it. Events older than ``max_age`` are discarded."""
        try:
            with open(self._scale_path()) as f:
                raw = f.read().strip()
        except OSError:
            return None
        try:
            ev = json.loads(raw)
            if not isinstance(ev, dict):
                raise ValueError(raw)
        except ValueError:
            try:
                ev = {"np": int(raw), "survivors": None, "ts": None}
            except ValueError:
                return None
        stale = ev.get("ts") is not None and \
            time.time() - ev["ts"] > max_age
        if clear or stale:
            try:
                os.remove(self._scale_path())
            except OSError:
                pass
        return None if stale else ev

    def read_new_np(self, clear: bool = False) -> Optional[int]:
        ev = self.read_scale_event(clear=clear)
        return None if ev is None else ev.get("np")

    def watch_scale(self, on_scale: Optional[Callable] = None,
                    interval: float = 2.0, ttl: float = 60.0,
                    settle: int = 2, arm_timeout: float = 300.0):
        """Background watch for the alive-node count departing from
        ``self.np`` (a rank died past its heartbeat TTL, or a new one
        joined). After ``settle`` consecutive differing polls,
        ``on_scale(new_np)`` fires ONCE — the default records the new
        world size (:meth:`write_new_np`) and triggers the preemption
        path (checkpoint → exit 101 → controller relaunch at new np).

        The watch ARMS only after it has seen the full world once
        (slow-starting ranks must not read as a scale-down); if the
        world never assembles within ``arm_timeout`` it fires with
        whoever showed up — the rendezvous-timeout re-form. A rank that
        completed normally left a tombstone (:meth:`deregister` with
        ``completed=True``) and does NOT count as a death.

        ``on_scale(new_np, survivors)`` — survivors are the alive
        global ranks at fire time."""
        def default(n, survivors):
            self.write_scale_event(n, survivors)
            self._handle(None, None)

        cb = on_scale or default

        def loop():
            consec = 0
            armed = False
            t0 = time.time()
            while not self._stop.is_set():
                alive = self.alive_nodes(ttl)
                n = len(alive)
                # completed ranks shrink the expected RUNNING world; a
                # joiner grows n past it — both directions are events
                expected = self.np - len(self.done_ranks())
                if n == expected:
                    armed = True
                    consec = 0
                elif not armed:
                    if time.time() - t0 > arm_timeout and n > 0:
                        cb(n, alive)
                        return
                else:
                    consec = consec + 1 if n > 0 else 0
                    if consec >= settle:
                        cb(n, alive)
                        return
                time.sleep(interval)
        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._scale_watcher = t
        return t

    # ---- preemption (TPU maintenance events) ----
    def on_preemption(self, callback: Callable, exit_after: bool = True):
        """Register checkpoint-and-exit callback; triggered by SIGTERM (the
        Cloud TPU preemption notice) or the watch file. ``exit_after=False``
        only runs the callback (for loops that defer the checkpoint to a
        step boundary and exit themselves — see elastic_train)."""
        self._preempt_cb = callback
        self._exit_after = exit_after
        signal.signal(signal.SIGTERM, self._handle)

    def _handle(self, signum, frame):
        if self._preempt_cb:
            self._preempt_cb()
        if getattr(self, "_exit_after", True):
            os._exit(ELASTIC_EXIT_CODE)

    def watch_preemption_file(self, path: str, interval: float = 5.0):
        """Poll a maintenance-notice file (GCE metadata watcher writes it)."""
        def loop():
            while not self._stop.is_set():
                if os.path.exists(path):
                    self._handle(None, None)
                time.sleep(interval)
        self._watcher = threading.Thread(target=loop, daemon=True)
        self._watcher.start()

    def exit(self, completed: bool = True) -> ElasticStatus:
        self._stop.set()
        self.deregister(completed=completed)
        return ElasticStatus.COMPLETED if completed else ElasticStatus.ERROR
