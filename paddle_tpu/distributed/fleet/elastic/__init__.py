from .manager import ElasticManager, ElasticStatus, ELASTIC_EXIT_CODE  # noqa: F401
from .checkpointer import ElasticCheckpointer, elastic_train  # noqa: F401
