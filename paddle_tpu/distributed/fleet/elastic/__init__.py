from .manager import ElasticManager, ElasticStatus, ELASTIC_EXIT_CODE  # noqa: F401
