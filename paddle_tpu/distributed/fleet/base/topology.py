"""Hybrid-parallel topology over the global device mesh.

Re-design of the reference's CommunicateTopology/HybridCommunicateGroup
(reference: python/paddle/distributed/fleet/base/topology.py:70,189). The
reference builds an N-D cartesian rank grid and creates an NCCL subgroup per
axis. TPU-native: the grid IS a ``jax.sharding.Mesh`` whose named axes are
the parallelism dimensions — "creating a subgroup" is just viewing one axis;
XLA lowers any collective over that axis onto the right ICI ring.

Default axis order follows the reference: [data, pipe, sharding, sep, model]
(fleet.py:702-724 hybrid_parallel_order).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

from ... import mesh as _mesh
from ...mesh import Group

# canonical axis names (reference uses dp/pp/sharding/sep/mp internally)
AXES = ("dp", "pp", "sharding", "sep", "mp")


class CommunicateTopology:
    """reference: fleet/base/topology.py:70."""

    def __init__(self, hybrid_group_names: Sequence[str] = AXES,
                 dims: Sequence[int] = (1, 1, 1, 1, 1)):
        self._names = list(hybrid_group_names)
        self._dims = [int(d) for d in dims]
        self._world = int(np.prod(self._dims))
        self._grid = np.arange(self._world).reshape(self._dims)

    def get_hybrid_group_names(self) -> List[str]:
        return list(self._names)

    def get_dim(self, name) -> int:
        return self._dims[self._names.index(name)]

    get_dim_size = get_dim

    def world_size(self) -> int:
        return self._world

    def get_rank(self, **kwargs) -> int:
        idx = tuple(kwargs[n] for n in self._names)
        return int(self._grid[idx])

    def get_coord(self, rank: int):
        return tuple(int(c) for c in
                     np.argwhere(self._grid == rank)[0])

    def get_axis_list(self, axis_name: str, index: int) -> List[int]:
        ax = self._names.index(axis_name)
        sl = [slice(None)] * len(self._names)
        sl[ax] = index
        return [int(r) for r in self._grid[tuple(sl)].ravel()]

    def get_comm_list(self, axis_name: str) -> List[List[int]]:
        ax = self._names.index(axis_name)
        moved = np.moveaxis(self._grid, ax, -1)
        return [[int(r) for r in row]
                for row in moved.reshape(-1, self._dims[ax])]


class HybridCommunicateGroup:
    """reference: fleet/base/topology.py:189 — per-axis group accessors.

    Holds the jax Mesh (axes ordered [dp, pp, sharding, sep, mp]) and hands
    out axis-view Groups.
    """

    def __init__(self, topology: CommunicateTopology,
                 mesh: Optional[Mesh] = None):
        self._topo = topology
        dims = [topology.get_dim(n) for n in topology.get_hybrid_group_names()]
        if mesh is None:
            devs = np.asarray(jax.devices()[:topology.world_size()],
                              dtype=object).reshape(dims)
            mesh = Mesh(devs, tuple(topology.get_hybrid_group_names()))
        self._mesh = mesh
        self._groups: Dict[str, Group] = {}
        _mesh.set_mesh(mesh)

    @property
    def topology(self) -> CommunicateTopology:
        return self._topo

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    def _axis_group(self, name: str) -> Group:
        if name not in self._groups:
            self._groups[name] = _mesh.new_group(axis_name=name)
        return self._groups[name]

    # ---- degrees ----
    def get_data_parallel_world_size(self):
        return self._topo.get_dim("dp")

    def get_model_parallel_world_size(self):
        return self._topo.get_dim("mp")

    def get_pipe_parallel_world_size(self):
        return self._topo.get_dim("pp")

    def get_sharding_parallel_world_size(self):
        return self._topo.get_dim("sharding")

    def get_sep_parallel_world_size(self):
        return self._topo.get_dim("sep")

    # ---- groups ----
    def get_data_parallel_group(self) -> Group:
        return self._axis_group("dp")

    def get_model_parallel_group(self) -> Group:
        return self._axis_group("mp")

    def get_pipe_parallel_group(self) -> Group:
        return self._axis_group("pp")

    def get_sharding_parallel_group(self) -> Group:
        return self._axis_group("sharding")

    def get_sep_parallel_group(self) -> Group:
        return self._axis_group("sep")

    def get_check_parallel_group(self) -> Group:
        return _mesh.get_world_group()

    # ---- ranks (0 on the single controller; axis_index when mapped) ----
    def _axis_rank(self, name: str) -> int:
        try:
            return int(jax.lax.axis_index(name))
        except Exception:
            return 0

    def get_data_parallel_rank(self):
        return self._axis_rank("dp")

    def get_model_parallel_rank(self):
        return self._axis_rank("mp")

    def get_stage_id(self):
        return self._axis_rank("pp")

    def get_sharding_parallel_rank(self):
        return self._axis_rank("sharding")

    def get_sep_parallel_rank(self):
        return self._axis_rank("sep")

    def get_global_rank(self):
        return _mesh.get_rank()

    # pipeline helpers (reference: topology.py is_first_stage/is_last_stage)
    @property
    def is_first_stage(self):
        return self.get_stage_id() == 0

    @property
    def is_last_stage(self):
        return self.get_stage_id() == self.get_pipe_parallel_world_size() - 1

    def get_p2p_groups(self):
        return self.get_pipe_parallel_group()

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank(dp=0, pp=stage_id, sharding=0, sep=0,
                                   mp=0)
