"""DistributedStrategy: training-strategy configuration.

Re-design of the reference's protobuf-backed DistributedStrategy
(reference: paddle/fluid/framework/distributed_strategy.proto,
python/paddle/distributed/fleet/base/distributed_strategy.py:284). The
reference serializes to protobuf for the static-graph compiler; here the
strategy is a plain validated config consumed by fleet.init and the jit
train-step builder.
"""
from __future__ import annotations

from typing import Any, Dict


_HYBRID_DEFAULTS = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
}


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = dict(_HYBRID_DEFAULTS)
        # amp (reference: distributed_strategy.proto amp_configs)
        self.amp = False
        self.amp_configs: Dict[str, Any] = {
            "init_loss_scaling": 32768.0, "use_pure_fp16": False,
            "use_bf16": True,
        }
        # recompute
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = {"checkpoints": []}
        # sharding (ZeRO)
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = {"stage": 1,
                                                 "offload": False}
        # pipeline
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = {
            "accumulate_steps": 1, "schedule_mode": "1F1B",
            "micro_batch_size": 1,
        }
        # gradient merge
        self.gradient_merge = False
        self.gradient_merge_configs: Dict[str, Any] = {"k_steps": 1}
        # DGC (reference: distributed_strategy.proto dgc_configs)
        self.dgc = False
        self.dgc_configs: Dict[str, Any] = {
            "rampup_begin_step": 0, "rampup_step": 1, "sparsity": [0.999],
        }
        # LocalSGD (reference: distributed_strategy.proto localsgd_configs)
        self.localsgd = False
        self.localsgd_configs: Dict[str, Any] = {"k_steps": 1,
                                                 "begin_step": 1}
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs: Dict[str, Any] = {
            "init_k_steps": 1, "begin_step": 1,
        }
        # parameter-server modes (reference: distributed_strategy.proto
        # a_sync + a_sync_configs; k_steps>0 selects geo-SGD)
        self.a_sync = False
        self.a_sync_configs: Dict[str, Any] = {"k_steps": 0}
        self.find_unused_parameters = False
        self.hybrid_parallel_order = list(_HYBRID_DEFAULTS["order"])

    def __setattr__(self, k, v):
        if k == "hybrid_configs" and isinstance(v, dict) and \
                "hybrid_configs" in self.__dict__:
            merged = dict(self.__dict__["hybrid_configs"])
            merged.update(v)
            object.__setattr__(self, k, merged)
            return
        object.__setattr__(self, k, v)

    def __repr__(self):
        return (f"DistributedStrategy(hybrid={self.hybrid_configs}, "
                f"amp={self.amp}, recompute={self.recompute}, "
                f"sharding={self.sharding})")
