"""Role makers (reference: python/paddle/distributed/fleet/base/
role_maker.py — Role:40, PaddleCloudRoleMaker:548,
UserDefinedRoleMaker:1213).

Roles are resolved from the same PADDLE_* launch env the reference's
cloud role maker reads; on a single-controller TPU pod every process is a
WORKER (the parameter-server split only appears when distributed.ps is
launched in server mode).
"""
from __future__ import annotations

import os
from typing import List, Optional


class Role:
    """reference: base/role_maker.py:40."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER

    def _worker_index(self) -> int:
        raise NotImplementedError

    def _worker_num(self) -> int:
        raise NotImplementedError

    def _is_worker(self) -> bool:
        return self._role == Role.WORKER

    def _is_server(self) -> bool:
        return self._role == Role.SERVER

    def _is_first_worker(self) -> bool:
        return self._is_worker() and self._worker_index() == 0

    # public aliases (reference exposes both)
    def worker_index(self):
        return self._worker_index()

    def worker_num(self):
        return self._worker_num()

    def is_worker(self):
        return self._is_worker()

    def is_server(self):
        return self._is_server()

    def is_first_worker(self):
        return self._is_first_worker()


class PaddleCloudRoleMaker(RoleMakerBase):
    """reference: base/role_maker.py:548 — resolve the role from the
    PADDLE_* env set by the launcher."""

    def __init__(self, is_collective: bool = False, **kwargs):
        super().__init__()
        self._is_collective = is_collective
        training_role = os.environ.get("TRAINING_ROLE", "TRAINER")
        self._role = Role.SERVER if training_role == "PSERVER" \
            else Role.WORKER
        self._rank = int(os.environ.get(
            "PADDLE_TRAINER_ID", os.environ.get("PADDLE_RANK", "0")))
        self._size = int(os.environ.get(
            "PADDLE_TRAINERS_NUM", os.environ.get("PADDLE_WORLD_SIZE",
                                                  "1")))
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        self._worker_endpoints: List[str] = [e for e in eps.split(",") if e]
        seps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
        self._server_endpoints: List[str] = [e for e in seps.split(",")
                                             if e]

    def _worker_index(self) -> int:
        return self._rank

    def _worker_num(self) -> int:
        return self._size

    def _server_num(self) -> int:
        return len(self._server_endpoints)

    def _get_trainer_endpoints(self) -> List[str]:
        return self._worker_endpoints

    def _get_pserver_endpoints(self) -> List[str]:
        return self._server_endpoints


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """reference: base/role_maker.py:1213 — explicit role/rank/size
    instead of env discovery."""

    def __init__(self, is_collective: bool = False,
                 current_id: int = 0, role: int = Role.WORKER,
                 worker_num: int = 1,
                 server_endpoints: Optional[List[str]] = None, **kwargs):
        super().__init__(is_collective=is_collective)
        self._role = role
        self._rank = current_id
        self._size = worker_num
        self._server_endpoints = list(server_endpoints or [])
