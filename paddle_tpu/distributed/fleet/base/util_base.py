"""UtilBase (reference: python/paddle/distributed/fleet/base/
util_factory.py:65 — cross-worker utility ops + file sharding)."""
from __future__ import annotations

import os
from typing import Any, List, Sequence

import numpy as np


class UtilBase:
    """reference: base/util_factory.py:65."""

    def __init__(self, role_maker=None):
        self.role_maker = role_maker

    def _rank_size(self):
        if self.role_maker is not None:
            return (self.role_maker._worker_index(),
                    self.role_maker._worker_num())
        from .. import fleet as _fleet_mod
        try:
            return (_fleet_mod.worker_index(), _fleet_mod.worker_num())
        except Exception:
            return 0, 1

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        """reference :81 — numpy all-reduce across workers."""
        from ... import collective as _c
        from ...mesh import ReduceOp, get_world_group
        from ...._core.tensor import Tensor
        g = get_world_group()
        arr = np.asarray(input)
        if g is None or g.nranks <= 1:
            return arr
        op = {"sum": ReduceOp.SUM, "max": ReduceOp.MAX,
              "min": ReduceOp.MIN}[mode]
        t = Tensor(arr.astype(np.float64 if arr.dtype.kind == "f"
                              else arr.dtype))
        try:
            _c.all_reduce(t, op=op, group=g)
            return np.asarray(t._value)
        except Exception:
            # single-controller replicated host value: reduce of n equal
            # copies
            if mode == "sum":
                return arr * g.nranks
            return arr

    def barrier(self, comm_world="worker"):
        from ...mesh import barrier as _b
        _b()

    def all_gather(self, input, comm_world="worker") -> List[Any]:
        from ..fleet import worker_num
        try:
            n = worker_num()
        except Exception:
            n = 1
        return [input] * n

    def get_file_shard(self, files: Sequence[str]) -> List[str]:
        """reference :257 — contiguous block split of the file list over
        workers (first ``len % n`` workers get one extra)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file need to be read")
        rank, size = self._rank_size()
        n = len(files)
        base, extra = divmod(n, size)
        start = rank * base + min(rank, extra)
        count = base + (1 if rank < extra else 0)
        return list(files[start:start + count])

    def print_on_rank(self, message: str, rank_id: int):
        rank, _ = self._rank_size()
        if rank == rank_id:
            print(message)
