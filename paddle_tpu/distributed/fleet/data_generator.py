"""Fleet data generators (reference: python/paddle/distributed/fleet/
data_generator/data_generator.py — DataGenerator:25,
MultiSlotStringDataGenerator:237, MultiSlotDataGenerator).

Emit the MultiSlot text format consumed by fleet.dataset
(``<n> v1 ... vn`` per slot per sample) from user ``generate_sample``
overrides — the exact pipeline contract the reference's C++ datafeed
reads."""
from __future__ import annotations

import sys
from typing import Iterable, List, Sequence, Tuple


class DataGenerator:
    """reference: data_generator.py:25."""

    def __init__(self):
        self.batch_size_ = 32

    def set_batch(self, batch_size: int):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        """Override: map a raw input line to
        ``[(slot_name, [values...]), ...]`` or a generator thereof."""
        raise NotImplementedError(
            "generate_sample() must be overridden by the user")

    def generate_batch(self, samples):
        """Override for batch-level processing (reference default: yield
        samples through)."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, line) -> str:
        raise NotImplementedError(
            "pls use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def _iter_outputs(self, lines: Iterable[str]):
        batch = []
        for line in lines:
            gen = self.generate_sample(line)
            it = gen() if callable(gen) else iter([gen])
            for sample in it:
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    for s in self.generate_batch(batch)():
                        yield self._gen_str(s)
                    batch = []
        if batch:
            for s in self.generate_batch(batch)():
                yield self._gen_str(s)

    def run_from_stdin(self):
        for out in self._iter_outputs(sys.stdin):
            sys.stdout.write(out)

    def run_from_files(self, filelist: Sequence[str], output):
        """Convenience (beyond the reference's stdin pipe): render slot
        files directly, for use with fleet.dataset.set_filelist."""
        def lines():
            for p in filelist:
                with open(p, "r", encoding="utf-8",
                          errors="replace") as f:
                    yield from f
        for out in self._iter_outputs(lines()):
            output.write(out)


class MultiSlotStringDataGenerator(DataGenerator):
    """reference: data_generator.py:237 — values pass through as strings."""

    def _gen_str(self, line) -> str:
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        parts: List[str] = []
        for _name, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotDataGenerator(DataGenerator):
    """reference: data_generator.py MultiSlotDataGenerator — values are
    ints (sparse ids) or floats (dense); slot arity is validated to stay
    consistent across samples."""

    def __init__(self):
        super().__init__()
        self._proto_info: List[Tuple[str, str]] = []

    def _gen_str(self, line) -> str:
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "the output of process() must be in list or tuple type")
        if not self._proto_info:
            for name, values in line:
                kind = "float" if any(isinstance(v, float) for v in values) \
                    else "uint64"
                self._proto_info.append((name, kind))
        elif len(self._proto_info) != len(line):
            raise ValueError(
                f"the complete field set of two given line are "
                f"inconsistent ({len(self._proto_info)} vs {len(line)})")
        parts: List[str] = []
        for (name, values), (_pname, _kind) in zip(line, self._proto_info):
            if not values:
                raise ValueError(f"the input feasign of slot {name} is "
                                 "empty")
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"
