"""reference: python/paddle/distributed/spawn.py — multiprocess launcher.

TPU-native: forks N python processes running ``func(rank)`` with the
PADDLE_* env wired, each on a forced single-device CPU backend (chips
cannot be shared between processes; real multi-host uses one process per
host via the launch CLI)."""
from __future__ import annotations

import multiprocessing as mp
import os
from typing import Optional, Sequence


def _worker(func, rank, nprocs, args, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["JAX_PLATFORMS"] = "cpu"
    func(*args)


def spawn(func, args=(), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    ctx = mp.get_context("spawn")
    procs = []
    env = {k: v for k, v in os.environ.items() if k.startswith("PADDLE_")}
    for rank in range(nprocs):
        p = ctx.Process(target=_worker,
                        args=(func, rank, nprocs, args, env), daemon=daemon)
        p.start()
        procs.append(p)

    class Ctx:
        processes = procs

        def join(self, timeout: Optional[float] = None):
            for p in procs:
                p.join(timeout)
            for p in procs:
                if p.exitcode not in (0, None):
                    raise RuntimeError(
                        f"spawned process exited with {p.exitcode}")

    c = Ctx()
    if join:
        c.join()
    return c
