"""Host-RAM offload of optimizer state (ZeRO-3 offload).

reference: python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_stage3.py (offload=True keeps fp32 masters + moments on CPU)
and paddle/fluid/distributed/collective/async_load.cc (dedicated-stream
cudaMemcpyAsync H2D/D2H with event sync).

TPU-native design. Optimizer state (moments + fp32 master weights) lives in
host RAM between steps; parameters stay device-resident. Each step runs TWO
kinds of compiled programs instead of the fused one:

  1. ``grad_fn`` — forward + loss + backward -> grads (device).
  2. per-chunk ``update_fn`` — (params_c, grads_c, state_c) -> updated.

The trainable params are split into K size-balanced chunks. The host loop
enqueues, for chunk i: H2D(state_i) -> update_i -> async D2H(new_state_i).
Because JAX dispatch is asynchronous, chunk i+1's H2D overlaps chunk i's
update on the TPU transfer engines and the D2H rides behind — the double
buffering the reference hand-rolls with streams/events falls out of the
dispatch queue. For sharded params (ZeRO-3 layouts) each state leaf is
H2D-placed with its parameter's own NamedSharding, so every host exchanges
only its shard.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..._core.tensor import Tensor
from ..._core.random import next_rng_key


def _chunk_keys(params: Dict[str, Any], n_chunks: int) -> List[List[str]]:
    """Contiguous size-balanced split of param names into n_chunks groups."""
    keys = sorted(params)
    sizes = {k: int(np.prod(jnp.shape(params[k]) or (1,))) for k in keys}
    total = sum(sizes.values())
    target = total / max(1, n_chunks)
    chunks: List[List[str]] = [[]]
    acc = 0
    for k in keys:
        if acc >= target * len(chunks) and len(chunks) < n_chunks:
            chunks.append([])
        chunks[-1].append(k)
        acc += sizes[k]
    return [c for c in chunks if c]


class OffloadTrainStep:
    """Compiled train step with optimizer state offloaded to host RAM.

    Numerically identical to :class:`paddle_tpu.jit.TrainStep` (same
    ``optimizer.build_functional`` update rule); only the residency of the
    state differs. Supports a GradScaler (non-finite steps skip the update
    without touching host state); gradient accumulation is not supported —
    accumulation keeps extra device buffers alive, which contradicts
    offloading's purpose.
    """

    def __init__(self, model, loss_fn, optimizer, scaler=None, chunks=2):
        from ...jit.api import (_build_forward_loss, _snapshot_model,
                                _capture_amp_state, _unscale_and_check)
        self.model = model
        self.optimizer = optimizer
        self.scaler = scaler if (scaler is not None and
                                 getattr(scaler, "_enable", True)) else None
        (named, self._trainable, self._frozen, self.params,
         self.buffers) = _snapshot_model(model)
        # one jit program needs one device set: params too small to shard
        # (ZeRO-3 skips non-divisible shapes) get replicated onto the mesh
        # the sharded ones live on
        from jax.sharding import NamedSharding, PartitionSpec
        mesh = next((v.sharding.mesh for v in self.params.values()
                     if isinstance(v.sharding, NamedSharding)), None)
        if mesh is not None:
            repl = NamedSharding(mesh, PartitionSpec())
            self.params = {
                k: (v if isinstance(v.sharding, NamedSharding)
                    else jax.device_put(v, repl))
                for k, v in self.params.items()}
            self.buffers = {
                k: (v if isinstance(v.sharding, NamedSharding)
                    else jax.device_put(v, repl))
                for k, v in self.buffers.items()}
            self._frozen = {
                k: (v if isinstance(v.sharding, NamedSharding)
                    else jax.device_put(v, repl))
                for k, v in self._frozen.items()}
        init_state, self._opt_update = optimizer.build_functional(named)
        amp_state = _capture_amp_state()
        use_scaler = self.scaler is not None

        self._chunks = _chunk_keys(self.params, int(chunks))
        # state starts device-side (cheap: zeros + param casts), is pulled
        # host-side once, then lives there
        dev_state = init_state(self.params)
        self.state_host: List[Dict[str, Any]] = []
        self._state_shardings: List[Dict[str, Any]] = []
        for keys in self._chunks:
            chunk = {k: dev_state[k] for k in keys}
            self.state_host.append(jax.tree_util.tree_map(
                lambda v: np.asarray(v), chunk))
            self._state_shardings.append({
                k: jax.tree_util.tree_map(
                    lambda v, s=self.params[k].sharding: s, dev_state[k])
                for k in keys})
        del dev_state

        forward_loss = _build_forward_loss(
            model, loss_fn, self._frozen, amp_state, use_scaler)

        def grad_fn(params, buffers, rng, inputs, labels, scale):
            (_, (new_buffers, out_vals, loss_val)), grads = \
                jax.value_and_grad(forward_loss, has_aux=True)(
                    params, buffers, rng, inputs, labels, scale)
            grads, found_inf = _unscale_and_check(grads, scale, use_scaler)
            return loss_val, grads, new_buffers, found_inf

        opt_update = self._opt_update

        def update_fn(params_c, grads_c, state_c, step, lr):
            return opt_update(params_c, grads_c, state_c, step, lr)

        self._grad_fn = jax.jit(grad_fn)
        # donate old params + in-flight device state; both are replaced
        self._update_fn = jax.jit(update_fn, donate_argnums=(0, 2))
        self._step_count = 0

    def __call__(self, inputs, labels=()):
        if isinstance(inputs, Tensor):
            inputs = (inputs,)
        if isinstance(labels, Tensor):
            labels = (labels,)

        def raw(x):
            return x._value if isinstance(x, Tensor) else x
        self._step_count += 1
        lr = jnp.float32(self.optimizer.get_lr())
        rng = next_rng_key()
        scale = jnp.float32(self.scaler.get_scale()) if self.scaler \
            else jnp.float32(1.0)
        loss, grads, self.buffers, found_inf = self._grad_fn(
            self.params, self.buffers, rng,
            tuple(raw(b) for b in inputs), tuple(raw(l) for l in labels),
            scale)
        if self.scaler is not None:
            # host sync on one scalar: the offload loop needs to know
            # whether to skip before touching host state
            if bool(found_inf):
                self.scaler._found_inf = True
                self.scaler.update()
                return Tensor(loss, _internal=True)
            self.scaler._found_inf = False

        pending = []
        for ci, keys in enumerate(self._chunks):
            params_c = {k: self.params[k] for k in keys}
            grads_c = {k: grads[k] for k in keys}
            # async H2D with the params' layouts (sharded states move
            # shard-wise over ICI-local hosts)
            state_c = jax.device_put(self.state_host[ci],
                                     self._state_shardings[ci])
            new_p, new_s = self._update_fn(params_c, grads_c, state_c,
                                           self._step_count, lr)
            self.params.update(new_p)
            for leaf in jax.tree_util.tree_leaves(new_s):
                leaf.copy_to_host_async()
            pending.append((ci, new_s))
        for ci, new_s in pending:
            self.state_host[ci] = jax.tree_util.tree_map(
                lambda v: np.asarray(v), new_s)
        if self.scaler is not None:
            self.scaler.update()
        return Tensor(loss, _internal=True)

    def sync_to_model(self):
        for k, p in self._trainable.items():
            p._inplace_assign(jnp.array(self.params[k]))
        namedb = dict(self.model.named_buffers())
        for k, v in self.buffers.items():
            namedb[k]._inplace_assign(jnp.array(v))
        self.sync_optimizer_state()

    def sync_optimizer_state(self):
        from ...jit.api import _write_back_opt_state
        state = {k: v for chunk in self.state_host for k, v in chunk.items()}
        _write_back_opt_state(self.optimizer, self._trainable, state,
                              self._step_count)

    def host_state_bytes(self) -> int:
        return sum(v.nbytes for c in self.state_host
                   for v in jax.tree_util.tree_leaves(c))


def offload_optimizer_states(optimizer):
    """Eager-path offload: after every ``optimizer.step()`` the accumulator
    Tensors are re-hosted as numpy arrays (freeing device HBM); the next
    step's math transparently re-uploads them on use.

    reference: group_sharded_stage3.py _offload_* helpers. This covers the
    eager/dygraph path; compiled training uses :class:`OffloadTrainStep`.
    """
    if getattr(optimizer, "_offload_wrapped", False):
        return optimizer
    orig_step = optimizer.step

    def step():
        orig_step()
        for slot in optimizer._accumulators.values():
            for t in slot.values():
                v = t._value
                if not isinstance(v, np.ndarray):
                    t._inplace_assign(np.asarray(v))
    optimizer.step = step
    optimizer._offload_wrapped = True
    optimizer._zero_offload = True
    return optimizer
