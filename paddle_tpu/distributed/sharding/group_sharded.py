"""Group-sharded (ZeRO) data parallelism.

Re-design of the reference's group_sharded stack
(reference: python/paddle/distributed/sharding/group_sharded.py:50
group_sharded_parallel; stages: meta_parallel/sharding/
group_sharded_optimizer_stage2.py, group_sharded_stage2.py,
group_sharded_stage3.py (1,219 lines), group_sharded_storage.py).

The reference manually slices params/grads/states into rank buffers, tracks
ownership, reduce-scatters grads and broadcasts updated shards. TPU-native,
ZeRO is a LAYOUT, not a protocol:

  stage 1 (os)     : optimizer state sharded over the sharding axis
  stage 2 (os_g)   : + gradients materialize reduce-scattered (XLA emits
                     psum_scatter in the compiled backward)
  stage 3 (p_g_os) : + parameters stored sharded, all-gathered on use
                     (GSPMD inserts the gather; donation frees the full
                     buffer after the step)

``group_sharded_parallel`` installs these layouts: device_put on params
(stage 3), an accumulator wrapper on the optimizer (all stages), and a
``_zero_stage`` tag the jit train-step builder reads to set grad
out-shardings (stage 2+).
"""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..._core.tensor import Tensor
from ...nn.layer.layers import Layer
from ..fleet.meta_optimizers.hybrid_parallel_optimizer import _shard_state_over
from .. import mesh as _mesh

_LEVELS = {"os": 1, "os_g": 2, "p_g_os": 3}


def _sharding_axis(group):
    if group is not None:
        return group.axis_names[0], group.mesh
    from ..fleet.fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    if hcg is not None and hcg.get_sharding_parallel_world_size() > 1:
        return "sharding", hcg.mesh
    m = _mesh.get_mesh()
    if m is None:
        _mesh.init_parallel_env()
        m = _mesh.get_mesh()
    return m.axis_names[0], m


def group_sharded_parallel(model: Layer, optimizer, level: str,
                           scaler=None, group=None, offload=False,
                           sync_buffers=False, buffer_max_size=2**23,
                           segment_size=2**20, sync_comm=False,
                           dp_group=None, exclude_layer=None):
    """reference: sharding/group_sharded.py:50."""
    if level not in _LEVELS:
        raise ValueError(f"level must be one of {list(_LEVELS)}")
    stage = _LEVELS[level]
    axis, mesh = _sharding_axis(group)
    n = mesh.shape[axis]

    # stage >= 1: shard optimizer state
    optimizer._acc = _shard_state_over(axis, mesh)(optimizer._acc)
    optimizer._zero_stage = stage
    optimizer._zero_axis = axis
    if offload:
        # eager-path host offload; compiled steps use OffloadTrainStep
        from .offload import offload_optimizer_states
        offload_optimizer_states(optimizer)

    model._zero_stage = stage
    model._zero_axis = axis

    if stage >= 3 and n > 1:
        # parameters stored sharded; XLA all-gathers on use
        for p in model.parameters():
            if p.ndim >= 1 and p.shape[0] % n == 0:
                spec = [None] * p.ndim
                spec[0] = axis
                try:
                    p._inplace_assign(jax.device_put(
                        p._value, NamedSharding(mesh, P(*spec))))
                except Exception:
                    pass
    return model, optimizer, scaler


def save_group_sharded_model(model, output, optimizer=None):
    """reference: sharding/group_sharded.py:199 — states are global arrays,
    so plain save covers all stages."""
    from ...framework.io import save
    import os
    os.makedirs(output, exist_ok=True)
    tgt = model._layers if hasattr(model, "_layers") else model
    save(tgt.state_dict(), os.path.join(output, "model.pdparams"))
    if optimizer is not None:
        save(optimizer.state_dict(), os.path.join(output, "model.pdopt"))
