from .group_sharded import (  # noqa: F401
    group_sharded_parallel, save_group_sharded_model,
)
from .offload import (  # noqa: F401
    OffloadTrainStep, offload_optimizer_states,
)
