"""Semi-auto parallel API: shard_tensor / reshard / shard_layer.

Re-design of the reference's DistTensor stack
(reference: python/paddle/distributed/auto_parallel/api.py — shard_tensor:220,
dtensor_from_local:647, reshard:733, shard_layer:844; C++ DistTensor
paddle/phi/core/distributed/auto_parallel/dist_tensor.h:39; reshard rules
paddle/phi/core/distributed/auto_parallel/reshard/).

TPU-native mapping:
- DistTensor = ordinary :class:`Tensor` whose ``_value`` is a GLOBAL jax
  array laid out with a ``NamedSharding`` derived from (ProcessMesh,
  placements). Sharding propagation through ops is done by XLA GSPMD at
  trace time — the compiler plays the role of the reference's 115 C++ SPMD
  rules, inserting collectives over ICI as needed.
- ``reshard`` = ``jax.device_put`` with the target sharding (XLA emits the
  minimal collective: slice/all-gather/all-to-all/permute), covering the
  reference's pairwise p/r/s reshard transfer matrix.
- ``Partial`` placements keep the *combined* (already-reduced) global value
  in ``_value`` (so downstream math is always correct) plus the unreduced
  per-coordinate pieces in ``_partial_pieces`` for exact p→x reshard
  semantics and local-view parity.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..._core.tensor import Tensor, Parameter
from .process_mesh import ProcessMesh
from .placement import Placement, Shard, Replicate, Partial


def _placements_to_spec(placements: Sequence[Placement], mesh: ProcessMesh,
                        ndim: int, shape=None) -> PartitionSpec:
    """placements (one per mesh dim) -> PartitionSpec (one entry per tensor
    dim listing the mesh axes that shard it). Dims not divisible by the mesh
    axis degrade to a replicated LAYOUT (the logical placement metadata is
    kept; the reference supports uneven shards, XLA does not)."""
    per_dim: List[List[str]] = [[] for _ in range(ndim)]
    sized = list(shape) if shape is not None else None
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            if p.dim >= ndim:
                raise ValueError(
                    f"Shard(dim={p.dim}) out of range for ndim={ndim}")
            n = mesh.shape[mesh_dim]
            if sized is not None and sized[p.dim] % n != 0:
                continue
            if sized is not None:
                sized[p.dim] //= n
            per_dim[p.dim].append(mesh.dim_names[mesh_dim])
    entries = [tuple(axes) if len(axes) > 1 else (axes[0] if axes else None)
               for axes in per_dim]
    while entries and entries[-1] is None:
        entries.pop()
    return PartitionSpec(*entries)


def _sharding_for(mesh: ProcessMesh, placements: Sequence[Placement],
                  ndim: int, shape=None) -> NamedSharding:
    return NamedSharding(mesh.to_jax_mesh(),
                         _placements_to_spec(placements, mesh, ndim, shape))


def _normalize_placements(placements, mesh: ProcessMesh):
    if placements is None:
        placements = [Replicate()] * mesh.ndim
    placements = list(placements)
    while len(placements) < mesh.ndim:
        placements.append(Replicate())
    return placements


def is_dist_tensor(t) -> bool:
    return isinstance(t, Tensor) and getattr(t, "_dist_mesh", None) is not None


def get_placements(t: Tensor):
    return list(getattr(t, "_dist_placements", []) or [])


def _mark(t: Tensor, mesh: ProcessMesh, placements, pieces=None) -> Tensor:
    t._dist_mesh = mesh
    t._dist_placements = tuple(placements)
    t._partial_pieces = pieces
    t.process_mesh = mesh
    t.placements = list(placements)
    return t


def shard_tensor(data, mesh: ProcessMesh, placements=None, *,
                 dtype=None, stop_gradient=None) -> Tensor:
    """reference: auto_parallel/api.py:220 shard_tensor — interpret ``data``
    as the GLOBAL tensor and lay it out across ``mesh`` per ``placements``.
    """
    placements = _normalize_placements(placements, mesh)
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError("shard_tensor does not accept Partial placements "
                         "(use dtensor_from_local)")
    src = data if isinstance(data, Tensor) else Tensor(data, dtype=dtype)
    sharding = _sharding_for(mesh, placements, src.ndim, src.shape)
    val = jax.device_put(src._value, sharding)
    out = Tensor(val, _internal=True)
    out.stop_gradient = src.stop_gradient if stop_gradient is None \
        else stop_gradient
    if isinstance(data, Parameter):
        data._inplace_assign(val)
        return _mark(data, mesh, placements)
    return _mark(out, mesh, placements)


def dtensor_from_local(local_tensor, mesh: ProcessMesh, placements=None,
                       local_rank: Optional[int] = None) -> Tensor:
    """reference: auto_parallel/api.py:647 dtensor_from_local — build the
    global DistTensor from this rank's local shard. Single-controller
    construction: the caller provides ONE local piece which is taken as the
    value at every mesh coordinate (tests construct per-coordinate data by
    calling with stacked arrays via :func:`dtensor_from_local_list`).
    """
    x = local_tensor._value if isinstance(local_tensor, Tensor) else \
        jnp.asarray(np.asarray(local_tensor))
    placements = _normalize_placements(placements, mesh)
    return dtensor_from_local_list(
        [x] * int(np.prod([mesh.shape[d] for d in range(mesh.ndim)] or [1])),
        mesh, placements)


def dtensor_from_local_list(local_list, mesh: ProcessMesh,
                            placements=None) -> Tensor:
    """Exact multi-rank construction: ``local_list[i]`` is the local piece of
    flat mesh coordinate i (row-major). This is the single-controller analog
    of every rank calling the reference's dtensor_from_local with its own
    value — used by the reshard transfer-matrix tests.
    """
    placements = _normalize_placements(placements, mesh)
    locals_ = [x._value if isinstance(x, Tensor) else
               jnp.asarray(np.asarray(x)) for x in local_list]
    shape = list(mesh.shape)
    n = int(np.prod(shape)) if shape else 1
    if len(locals_) != n:
        raise ValueError(f"need {n} local pieces, got {len(locals_)}")
    grid = np.empty(n, dtype=object)
    for i, x in enumerate(locals_):
        grid[i] = x
    grid = grid.reshape(tuple(shape) or (1,))

    # Fold mesh dims one at a time (innermost first): Shard(d) pieces
    # concatenate along tensor dim d; Replicate pieces must agree (take
    # first); Partial pieces sum (combined value) while recording the
    # unreduced stack.
    pieces_for_partial = None
    work = grid
    for mesh_dim in range(mesh.ndim - 1, -1, -1):
        p = placements[mesh_dim]
        moved = np.moveaxis(work, mesh_dim, -1)
        newshape = moved.shape[:-1]
        flat = moved.reshape(-1, moved.shape[-1])
        out = np.empty(flat.shape[0], dtype=object)
        for j in range(flat.shape[0]):
            row = list(flat[j])
            if isinstance(p, Shard):
                out[j] = jnp.concatenate(row, axis=p.dim)
            elif isinstance(p, Partial):
                stacked = jnp.stack(row, axis=0)
                if pieces_for_partial is None:
                    pieces_for_partial = stacked
                if p.reduce_type == "sum" or p.reduce_type == "avg":
                    s = sum(row[1:], row[0])
                    out[j] = s / len(row) if p.reduce_type == "avg" else s
                elif p.reduce_type == "max":
                    out[j] = jnp.stack(row).max(0)
                elif p.reduce_type == "min":
                    out[j] = jnp.stack(row).min(0)
                else:
                    raise ValueError(p.reduce_type)
            else:
                out[j] = row[0]
        work = out.reshape(newshape) if newshape else out.reshape(())
    glob = work.item() if work.ndim == 0 else work.ravel()[0]

    # lay out the combined global value per the non-partial placements
    lay = [pp if not isinstance(pp, Partial) else Replicate()
           for pp in placements]
    val = jax.device_put(glob, _sharding_for(mesh, lay, glob.ndim, glob.shape))
    out_t = Tensor(val, _internal=True)
    return _mark(out_t, mesh, placements, pieces=pieces_for_partial)


def dtensor_to_local(t: Tensor, mesh: Optional[ProcessMesh] = None,
                     placements=None, rank: int = 0) -> Tensor:
    """reference: auto_parallel/api.py dtensor_to_local — the local shard
    seen by flat mesh coordinate ``rank`` (default 0: the controller).
    ``mesh``/``placements`` override the tensor's own distribution when
    given (reinterpret the global value under that layout)."""
    if not is_dist_tensor(t) and mesh is None:
        return t
    if mesh is None:
        mesh = t._dist_mesh
    if placements is None:
        placements = t._dist_placements
    placements = _normalize_placements(list(placements), mesh)
    coords = np.unravel_index(rank, tuple(mesh.shape) or (1,))
    val = t._value
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            n = mesh.shape[mesh_dim]
            size = val.shape[p.dim] // n
            idx = [slice(None)] * val.ndim
            idx[p.dim] = slice(coords[mesh_dim] * size,
                               (coords[mesh_dim] + 1) * size)
            val = val[tuple(idx)]
        elif isinstance(p, Partial) and \
                getattr(t, "_partial_pieces", None) is not None:
            val = t._partial_pieces[coords[mesh_dim]]
    return Tensor(val, _internal=True)


def reshard(t: Tensor, mesh: ProcessMesh, placements) -> Tensor:
    """reference: auto_parallel/api.py:733 reshard + the C++ pairwise
    transfer matrix (reshard/*.cc). All of p↔r↔s (and cross-mesh
    same-status) reduce to one ``jax.device_put`` on the combined global
    value — XLA emits the minimal data movement — plus placement-metadata
    bookkeeping for Partial targets:

    - x → Partial (the reference's r_to_p): coordinate 0 keeps the value,
      other coordinates hold zeros.
    """
    placements = _normalize_placements(placements, mesh)
    if not isinstance(t, Tensor):
        t = Tensor(t)
    glob = t._value  # combined global value (see module docstring)
    pieces = None
    partial_dims = [i for i, p in enumerate(placements)
                    if isinstance(p, Partial)]
    if partial_dims:
        # r->p semantics: rank 0 along the partial axis keeps the value
        md = partial_dims[0]
        n = mesh.shape[md]
        pieces = jnp.concatenate(
            [glob[None], jnp.zeros((n - 1,) + glob.shape, glob.dtype)], 0)
    lay = [p if not isinstance(p, Partial) else Replicate()
           for p in placements]
    val = jax.device_put(glob, _sharding_for(mesh, lay, glob.ndim, glob.shape))
    out = Tensor(val, _internal=True)
    out.stop_gradient = t.stop_gradient
    return _mark(out, mesh, placements, pieces=pieces)


def unshard_dtensor(t: Tensor) -> Tensor:
    """reference: auto_parallel/api.py unshard_dtensor — back to replicated
    dense."""
    if not is_dist_tensor(t):
        return t
    out = Tensor(jax.device_put(
        t._value, NamedSharding(t._dist_mesh.to_jax_mesh(),
                                PartitionSpec())), _internal=True)
    out.stop_gradient = t.stop_gradient
    return out


def shard_layer(layer, process_mesh: ProcessMesh,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None):
    """reference: auto_parallel/api.py:844 shard_layer — apply ``shard_fn``
    (name, sublayer, mesh) to place every parameter; default replicates."""
    def default_fn(name, sublayer, mesh):
        for pname, p in list(sublayer._parameters.items()):
            if p is not None and not is_dist_tensor(p):
                shard_tensor(p, mesh, [Replicate()] * mesh.ndim)

    fn = shard_fn or default_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn: Optional[Callable] = None):
    """reference: auto_parallel/api.py shard_optimizer — optimizer state
    inherits each parameter's placements (ZeRO-style if shard_fn overrides).
    TPU-native: wrap accumulator creation so new state arrays are laid out
    with the parameter's NamedSharding.
    """
    orig_acc = optimizer._acc

    def _acc(name, p, init=None, dtype=None):
        t = orig_acc(name, p, init=init, dtype=dtype)
        if is_dist_tensor(p) and getattr(t, "_dist_mesh", None) is None:
            mesh, placements = p._dist_mesh, list(p._dist_placements)
            if shard_fn is not None:
                mesh, placements = shard_fn(name, p, mesh, placements)
            lay = [pp if not isinstance(pp, Partial) else Replicate()
                   for pp in placements]
            t._inplace_assign(jax.device_put(
                t._value, _sharding_for(mesh, lay, t.ndim, t.shape)))
            _mark(t, mesh, placements)
        return t

    optimizer._acc = _acc
    return optimizer
