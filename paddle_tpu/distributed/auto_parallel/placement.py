"""Placements: per-mesh-dim distribution states.

Re-design of the reference's placement types
(reference: paddle/phi/core/distributed/auto_parallel/placement_types.h,
python surface paddle.distributed.{Shard,Replicate,Partial}).

- ``Shard(dim)``   — tensor dim ``dim`` split across this mesh axis
- ``Replicate()``  — replicated across this mesh axis
- ``Partial(op)``  — each shard holds a partial reduction; the global value
                     is op-combined over the axis (pending a reshard).

Shard/Replicate lower directly to ``jax.sharding.PartitionSpec`` entries.
Partial has no NamedSharding representation in public JAX, so DistTensors
with Partial placements carry the *unreduced* value stacked along a hidden
leading axis (one slice per mesh coordinate) — exact semantics, resolved to
a reduction by ``reshard`` (the reference's p→r / p→s reshard functions,
paddle/phi/core/distributed/auto_parallel/reshard/).
"""
from __future__ import annotations


class Placement:
    def is_shard(self, dim=None):
        return False

    def is_replicated(self):
        return False

    def is_partial(self):
        return False


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = int(dim)

    def is_shard(self, dim=None):
        return True if dim is None else dim == self.dim

    def get_dim(self):
        return self.dim

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim

    def __hash__(self):
        return hash(("shard", self.dim))

    def __repr__(self):
        return f"Shard(dim={self.dim})"


class Replicate(Placement):
    def is_replicated(self):
        return True

    def __eq__(self, o):
        return isinstance(o, Replicate)

    def __hash__(self):
        return hash("replicate")

    def __repr__(self):
        return "Replicate()"


class Partial(Placement):
    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def is_partial(self):
        return True

    def __eq__(self, o):
        return isinstance(o, Partial) and o.reduce_type == self.reduce_type

    def __hash__(self):
        return hash(("partial", self.reduce_type))

    def __repr__(self):
        return f"Partial({self.reduce_type})"
