"""ProcessMesh: the logical device mesh of the semi-auto-parallel API.

Re-design of the reference's ProcessMesh
(reference: python/paddle/distributed/auto_parallel/process_mesh.py:85,
C++ paddle/phi/core/distributed/auto_parallel/process_mesh.h). Maps 1:1 onto
``jax.sharding.Mesh``: dim_names are mesh axis names, the process-id ndarray
selects/orders devices. All sharding propagation then rides XLA GSPMD
instead of the reference's 115 C++ SPMD rules.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh


class ProcessMesh:
    def __init__(self, mesh: Sequence, dim_names: Optional[List[str]] = None,
                 shape=None, process_ids=None):
        if shape is not None and process_ids is not None:
            arr = np.asarray(process_ids).reshape(tuple(shape))
        else:
            arr = np.asarray(mesh)
        self._ids = arr.astype(np.int64)
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(self._ids.ndim)]
        if len(dim_names) != self._ids.ndim:
            raise ValueError("dim_names must match mesh ndim")
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    @property
    def shape(self) -> List[int]:
        return list(self._ids.shape)

    @property
    def ndim(self) -> int:
        return self._ids.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def process_ids(self) -> List[int]:
        return [int(i) for i in self._ids.ravel()]

    @property
    def mesh(self) -> np.ndarray:
        return self._ids

    @property
    def size(self) -> int:
        return int(self._ids.size)

    def get_dim_size(self, name) -> int:
        return self._ids.shape[self._dim_names.index(name)]

    def get_rank_by_dim_and_process_id(self, dim, process_id):
        where = np.argwhere(self._ids == process_id)
        if where.size == 0:
            return -1
        return int(where[0][self._dim_names.index(dim)])

    # ---- jax bridge ----
    def to_jax_mesh(self) -> Mesh:
        if self._jax_mesh is None:
            devs = {d.id: d for d in jax.devices()}
            try:
                device_arr = np.vectorize(lambda i: devs[i])(self._ids)
            except KeyError as e:
                raise ValueError(
                    f"process id {e} not among jax.devices() "
                    f"({len(devs)} present)") from e
            self._jax_mesh = Mesh(device_arr, tuple(self._dim_names))
        return self._jax_mesh

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._ids, other._ids)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._ids.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, "
                f"dim_names={self._dim_names})")


def get_mesh_with_default(mesh: Optional[ProcessMesh]) -> ProcessMesh:
    if mesh is not None:
        return mesh
    n = len(jax.devices())
    return ProcessMesh(np.arange(n), ["world"])
