"""DistModel + distributed to_static (reference: python/paddle/distributed/
auto_parallel/api.py DistModel:2190, to_static:2798).

The reference converts a dynamic-graph layer + DistributedDataLoader into
a static distributed Program with mode-switched train/eval/predict
execution.  TPU-native: the "static graph" is a jit-compiled step built by
the Engine machinery (engine.py), and the strategy knobs (Strategy from
strategy.py) pick ZeRO level / recompute / amp at step-build time.
"""
from __future__ import annotations

from typing import Any, Optional

from .engine import Engine
from .strategy import Strategy


class DistModel:
    """reference: auto_parallel/api.py:2190.

    Call pattern parity: set a mode with ``train()``/``eval()``/
    ``predict()`` and invoke the model with ``dist_model(*batch)`` —
    train mode runs forward+backward+step and returns the loss, eval
    runs forward+loss, predict returns outputs.
    """

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy: Optional[Strategy] = None, metrics=None):
        self._engine = Engine(model=layer, loss=loss, optimizer=optimizer,
                              metrics=metrics, strategy=strategy)
        self._layer = layer
        self._loader = loader
        self._loss = loss
        self._optimizer = optimizer
        self._strategy = strategy or Strategy()
        if optimizer is not None and loss is not None:
            self._mode = "train"
        elif loss is not None:
            self._mode = "eval"
        else:
            self._mode = "predict"

    # ---- mode switching (reference :2200) ----
    def train(self):
        if self._loss is None or self._optimizer is None:
            raise ValueError(
                "train() needs both loss and optimizer (reference "
                "DistModel contract)")
        self._mode = "train"
        if hasattr(self._layer, "train"):
            self._layer.train()
        return self

    def _sync_from_train(self):
        """Push the compiled train step's functional params back onto the
        layer so eval/predict/state_dict see the trained weights."""
        ts = self._engine._train_step
        if ts is not None and hasattr(ts, "sync_to_model"):
            ts.sync_to_model()

    def eval(self):
        if self._loss is None:
            raise ValueError("eval() needs a loss")
        self._sync_from_train()
        self._mode = "eval"
        if hasattr(self._layer, "eval"):
            self._layer.eval()
        return self

    def predict(self):
        self._sync_from_train()
        self._mode = "predict"
        if hasattr(self._layer, "eval"):
            self._layer.eval()
        return self

    @property
    def mode(self) -> str:
        return self._mode

    def __call__(self, *args: Any):
        """One batch. ``args`` = (*inputs, *labels) in train/eval mode
        (labels = the last ``n_labels`` entries, default 1), inputs only
        in predict mode — the reference DistModel's convention."""
        if self._mode == "predict":
            return self._layer(*args)
        inputs, labels = self._engine._split(tuple(args), 1)
        inputs = self._engine._shard_batch(inputs)
        labels = self._engine._shard_batch(labels)
        from ..._core.tensor import Tensor
        if self._mode == "train":
            step = self._engine._ensure_train_step()
            out = step(inputs, labels)
            return out[0] if isinstance(out, tuple) else out
        eval_fn = self._engine._ensure_eval_step()
        out = eval_fn(*[Tensor(a, _internal=True) for a in inputs])
        outs = out if isinstance(out, (tuple, list)) else (out,)
        return self._loss(*outs, *[Tensor(l, _internal=True)
                                   for l in labels])

    # ---- state passthrough ----
    def state_dict(self, *a, **k):
        self._sync_from_train()
        return self._layer.state_dict(*a, **k)

    def set_state_dict(self, sd):
        return self._layer.set_state_dict(sd)

    def dist_main_program(self, mode=None):
        """reference returns the static Program; here the jit step is the
        program — exposed for introspection parity."""
        return self._engine

    def dist_startup_program(self, mode=None):
        return None


def to_static(layer, loader=None, loss=None, optimizer=None, strategy=None,
              input_spec=None):
    """reference: auto_parallel/api.py to_static:2798 — build a DistModel
    (and in the reference also a DistributedDataLoader; here the loader
    passes through — use ``paddle_tpu.distributed.shard_dataloader`` for
    dp-sharded batches)."""
    dm = DistModel(layer, loader=loader, loss=loss, optimizer=optimizer,
                   strategy=strategy)
    if loader is not None:
        return dm, loader
    return dm
