from .process_mesh import ProcessMesh  # noqa: F401
from .placement import Placement, Shard, Replicate, Partial  # noqa: F401
from .api import (  # noqa: F401
    shard_tensor, dtensor_from_local, dtensor_to_local, reshard, shard_layer,
    get_placements, is_dist_tensor, shard_optimizer, unshard_dtensor,
)
from .parallelize import (  # noqa: F401
    parallelize, parallelize_model, parallelize_optimizer, set_mesh, get_mesh,
    PlanBase, ColWiseParallel, RowWiseParallel, PrepareLayerInput,
    PrepareLayerOutput, SequenceParallelBegin, SequenceParallelEnd,
    SequenceParallelEnable, SequenceParallelDisable, SplitPoint,
)
from .engine import Engine  # noqa: F401
