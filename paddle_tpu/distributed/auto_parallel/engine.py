"""Semi-auto static Engine: whole-program compiled fit/evaluate/predict over
a parallelized model.

reference: python/paddle/distributed/auto_parallel/static/engine.py:98 —
there, Engine builds a static Program per mode, applies parallelization
passes, and drives an executor. TPU-native: the "program" is the jitted
train/eval/predict step (jit/api.py TrainStep/EvalStep — forward + loss +
grad + optimizer update in ONE XLA program); parallelization passes are the
NamedSharding layouts that parallelize() already stamped on the parameters,
propagated by GSPMD. Engine's own job reduces to (a) sharding each host
batch over the ``dp`` axis, (b) the epoch/step loop with logging + metrics,
(c) save/load.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

from ..._core.tensor import Tensor
from .process_mesh import ProcessMesh


def _to_batch_tuple(batch):
    if isinstance(batch, (list, tuple)):
        return tuple(batch)
    return (batch,)


def _metric_items(m):
    """name()/accumulate() can be parallel LISTS (e.g. Accuracy(topk=(1,5))
    -> ['acc_top1','acc_top5']); zip them like the reference hapi loop."""
    names = m.name()
    vals = m.accumulate()
    if isinstance(names, (list, tuple)):
        vals = vals if isinstance(vals, (list, tuple, np.ndarray)) \
            else [vals]
        return {n: float(v) for n, v in zip(names, vals)}
    return {names: vals}


class Engine:
    """reference: auto_parallel/static/engine.py:98 Engine(model, loss,
    optimizer, metrics, strategy). ``model`` should already be parallelized
    (or plain — then Engine is just a compiled training loop)."""

    def __init__(self, model=None, loss=None, optimizer=None, metrics=None,
                 cluster=None, strategy=None):
        self._model = model
        self._loss = loss
        self._optimizer = optimizer
        self._metrics = list(metrics) if isinstance(
            metrics, (list, tuple)) else ([metrics] if metrics else [])
        self._strategy = strategy
        self._mesh: Optional[ProcessMesh] = getattr(
            model, "_parallelize_mesh", None)
        self._train_step = None
        self._eval_step = None
        self.history: Dict[str, List[float]] = {"loss": []}

    # ---- batch sharding ----
    def _shard_batch(self, arrays):
        """Lay host batches out over the dp axis (the reference feeds each
        rank its own split; single-controller GSPMD feeds the global batch
        with a dp-sharded layout). Always returns raw jax arrays."""
        def raw(x):
            return x._value if isinstance(x, Tensor) else jnp.asarray(
                np.asarray(x))
        if self._mesh is None or "dp" not in self._mesh.dim_names:
            return tuple(raw(a) for a in arrays)
        jm = self._mesh.to_jax_mesh()
        dp_n = jm.shape["dp"]

        def place(x):
            v = raw(x)
            if v.ndim >= 1 and v.shape[0] % dp_n == 0:
                s = NamedSharding(jm, PartitionSpec("dp"))
            else:
                s = NamedSharding(jm, PartitionSpec())
            return jax.device_put(v, s)
        return tuple(place(a) for a in arrays)

    # ---- mode preparation ----
    def prepare(self, inputs_spec=None, labels_spec=None, mode="train"):
        """Build (and cache) the compiled step for ``mode``. Specs are
        accepted for API parity; compilation is shape-driven at first call.
        """
        if mode == "train":
            self._ensure_train_step()
        elif mode in ("eval", "predict"):
            self._ensure_eval_step()
        return self

    def _ensure_train_step(self):
        if self._train_step is None:
            if self._optimizer is None or self._loss is None:
                raise ValueError("Engine.fit needs loss and optimizer")
            if getattr(self._optimizer, "_zero_offload", False):
                # dp_config={"offload": True}: optimizer state lives in
                # host RAM between steps
                if self._metrics:
                    import warnings
                    warnings.warn(
                        "Engine metrics are not computed with "
                        "offload=True (OffloadTrainStep returns loss "
                        "only); evaluate() still reports them")
                from ..sharding.offload import OffloadTrainStep
                self._train_step = OffloadTrainStep(
                    self._model, self._loss, self._optimizer)
            else:
                from ...jit.api import TrainStep
                self._train_step = TrainStep(
                    self._model, self._loss, self._optimizer,
                    return_outputs=bool(self._metrics))
        return self._train_step

    def _ensure_eval_step(self):
        if self._eval_step is None:
            from ...jit.api import EvalStep
            self._eval_step = EvalStep(self._model)
        return self._eval_step

    # ---- dataloader ----
    def dataloader(self, dataset, batch_size=1, shuffle=False, drop_last=True,
                   collate_fn=None, num_workers=0, mode="train"):
        from ...io import DataLoader
        return DataLoader(dataset, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, collate_fn=collate_fn,
                          num_workers=num_workers)

    def _iter_data(self, data, batch_size, shuffle, drop_last):
        from ...io import DataLoader, Dataset
        if isinstance(data, DataLoader):
            return data
        if hasattr(data, "__getitem__") or hasattr(data, "__iter__"):
            if isinstance(data, Dataset) or (
                    hasattr(data, "__len__") and not isinstance(
                        data, (list, tuple))):
                return DataLoader(data, batch_size=batch_size,
                                  shuffle=shuffle, drop_last=drop_last)
        return data

    def _split(self, batch, n_labels):
        batch = _to_batch_tuple(batch)
        if n_labels == 0:
            return batch, ()
        return batch[:-n_labels], batch[-n_labels:]

    # ---- modes ----
    def fit(self, train_data, epochs=1, batch_size=1, steps_per_epoch=None,
            log_freq=10, shuffle=True, verbose=1, n_labels=1):
        """Epoch loop over dp-sharded batches through the compiled train
        step (reference: static/engine.py fit)."""
        step_fn = self._ensure_train_step()
        loader = self._iter_data(train_data, batch_size, shuffle, True)
        # hang diagnosis (reference: comm_task_manager.cc watchdog) — armed
        # via PADDLE_STEP_TIMEOUT seconds
        from ..watchdog import StepWatchdog
        wd = StepWatchdog.from_env(name="engine.fit")
        try:
            self._fit_loop(step_fn, loader, epochs, steps_per_epoch,
                           log_freq, verbose, n_labels, wd)
        finally:
            if wd is not None:
                wd.stop()
        return self.history

    def _fit_loop(self, step_fn, loader, epochs, steps_per_epoch, log_freq,
                  verbose, n_labels, wd):
        for epoch in range(epochs):
            for m in self._metrics:
                m.reset()
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                inputs, labels = self._split(batch, n_labels)
                inputs = self._shard_batch(inputs)
                labels = self._shard_batch(labels)
                out = step_fn(inputs, labels)
                if wd is not None:
                    wd.tick()
                loss = out[0] if isinstance(out, tuple) else out
                lv = float(np.asarray(loss._value if isinstance(
                    loss, Tensor) else loss))
                self.history["loss"].append(lv)
                logs = {"epoch": epoch, "step": step, "loss": lv}
                if self._metrics and isinstance(out, tuple):
                    for m in self._metrics:
                        pred = out[1][0]
                        if not isinstance(pred, Tensor):
                            pred = Tensor(pred, _internal=True)
                        corr = m.compute(pred,
                                         Tensor(labels[0], _internal=True))
                        m.update(*[np.asarray(c._value if isinstance(
                            c, Tensor) else c) for c in (
                            corr if isinstance(corr, (list, tuple))
                            else [corr])])
                        logs.update(_metric_items(m))
                if verbose and step % log_freq == 0:
                    kv = " ".join(f"{k}={v:.5g}" if isinstance(v, float)
                                  else f"{k}={v}" for k, v in logs.items())
                    print(f"[Engine.fit] {kv}")
            step_fn.sync_to_model()

    def evaluate(self, valid_data, batch_size=1, steps=None, verbose=1,
                 n_labels=1):
        eval_fn = self._ensure_eval_step()
        loader = self._iter_data(valid_data, batch_size, False, False)
        losses: List[float] = []
        for m in self._metrics:
            m.reset()
        for step, batch in enumerate(loader):
            if steps is not None and step >= steps:
                break
            inputs, labels = self._split(batch, n_labels)
            inputs = self._shard_batch(inputs)
            labels = self._shard_batch(labels)
            out = eval_fn(*[Tensor(a, _internal=True) for a in inputs])
            outs = out if isinstance(out, (tuple, list)) else (out,)
            if self._loss is not None and labels:
                loss = self._loss(*outs, *[Tensor(l, _internal=True)
                                           for l in labels])
                losses.append(float(np.asarray(
                    loss._value if isinstance(loss, Tensor) else loss)))
            if self._metrics and labels:
                for m in self._metrics:
                    corr = m.compute(
                        outs[0] if isinstance(outs[0], Tensor)
                        else Tensor(outs[0], _internal=True),
                        Tensor(labels[0], _internal=True))
                    m.update(*[np.asarray(
                        c._value if isinstance(c, Tensor) else c)
                        for c in (corr if isinstance(corr, (list, tuple))
                                  else [corr])])
        result = {"eval_loss": float(np.mean(losses)) if losses else None}
        for m in self._metrics:
            result.update(_metric_items(m))
        if verbose:
            print(f"[Engine.evaluate] {result}")
        return result

    def predict(self, test_data, batch_size=1, steps=None):
        eval_fn = self._ensure_eval_step()
        loader = self._iter_data(test_data, batch_size, False, False)
        outs: List[Any] = []
        for step, batch in enumerate(loader):
            if steps is not None and step >= steps:
                break
            inputs = self._shard_batch(_to_batch_tuple(batch))
            outs.append(eval_fn(*[Tensor(a, _internal=True)
                                  for a in inputs]))
        return outs

    # ---- state ----
    def save(self, path, training=True):
        from ...framework import io as fio
        if self._train_step is not None:
            self._train_step.sync_to_model()
        state = {"model": self._model.state_dict()}
        if training and self._optimizer is not None:
            state["optimizer"] = self._optimizer.state_dict()
        fio.save(state, path + ".pdparams")

    def load(self, path):
        from ...framework import io as fio
        state = fio.load(path + ".pdparams")
        self._model.set_state_dict(state["model"])
        if "optimizer" in state and self._optimizer is not None:
            self._optimizer.set_state_dict(state["optimizer"])
        self._train_step = None
        self._eval_step = None
