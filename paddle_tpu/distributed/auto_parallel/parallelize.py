"""Intermediate parallelize API: one call takes an UNANNOTATED Layer +
{dp,mp,pp} configs and applies sharding plans automatically.

reference: python/paddle/distributed/auto_parallel/intermediate/
parallelize.py:21 (parallelize / parallelize_model / parallelize_optimizer),
tensor_parallel.py (PlanBase/ColWiseParallel/RowWiseParallel/PrepareLayerInput/
PrepareLayerOutput/SequenceParallel*), sharded_data_parallel.py,
pipeline_parallel.py (SplitPoint).

TPU-native mapping (vs the reference's DistTensor conversion + NCCL groups):
- A "plan" rewrites nothing: it lays the matched layer's parameters out with a
  ``NamedSharding`` over the mesh's ``mp`` axis (via :func:`shard_tensor`).
  Inside ``jit``, XLA GSPMD propagates those shardings through the whole
  program and inserts the exact collectives the reference codes by hand
  (identity/allreduce pairs of mp_ops.py) over ICI.
- Sequence-parallel plans insert ``lax.with_sharding_constraint`` forward
  hooks on the matched layer's input/output, pinning the sequence dim to the
  ``mp`` axis — the scatter/gather pairs of the reference's
  sequence_parallel_utils.py become compiler-inserted reduce-scatters.
- Sharded data parallel levels map to ZeRO semantics: level 1/2 shard the
  optimizer state over ``dp`` (grad reduce-scatter falls out of GSPMD),
  level 3 additionally shards every parameter over ``dp`` (FSDP-style
  gather-on-use).
- Pipeline: ``split_spec`` segments the model and records a ``_pp_stage``
  attribute per sublayer. The scheduled (1F1B/interleave/zero-bubble)
  execution path is fleet's PipelineParallel / pp_spmd engines; at this API
  level stages execute in-place, which is numerically identical.
"""
from __future__ import annotations

import fnmatch
from enum import Enum
from typing import Any, Callable, Dict, List, Optional

import jax
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec

from .process_mesh import ProcessMesh
from .placement import Shard, Replicate
from .api import shard_tensor, shard_optimizer, is_dist_tensor

_global_mesh: Optional[ProcessMesh] = None


def set_mesh(mesh: ProcessMesh) -> None:
    """reference: auto_parallel/api.py set_mesh — install the global mesh
    used by parallelize when no mesh is passed."""
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh


def _default_mesh(mesh: Optional[ProcessMesh]) -> ProcessMesh:
    if mesh is not None:
        return mesh
    if _global_mesh is not None:
        return _global_mesh
    raise ValueError(
        "parallelize needs a mesh: pass mesh=... or call "
        "paddle_tpu.distributed.auto_parallel.set_mesh(ProcessMesh(...))")


def _axis_index(mesh: ProcessMesh, name: str) -> int:
    if name not in mesh.dim_names:
        raise ValueError(f"mesh {mesh} has no '{name}' axis")
    return mesh.dim_names.index(name)


def _shard_param(param, mesh: ProcessMesh, mesh_axis: str, tensor_dim: int):
    """Lay one parameter out with Shard(tensor_dim) over mesh_axis, merging
    with any placement it already carries (so dp-sharding + mp-sharding
    compose)."""
    if param is None:
        return
    ax = _axis_index(mesh, mesh_axis)
    if is_dist_tensor(param) and param._dist_mesh == mesh:
        placements = list(param._dist_placements)
    else:
        placements = [Replicate()] * mesh.ndim
    placements[ax] = Shard(tensor_dim)
    shard_tensor(param, mesh, placements)


class SplitPoint(Enum):
    """reference: intermediate/pipeline_parallel.py SplitPoint."""
    BEGINNING = 0
    END = 1


class PlanBase:
    """reference: intermediate/tensor_parallel.py:23 PlanBase."""

    def apply(self, layer, process_mesh: ProcessMesh,
              shard_weight: bool = True, shard_bias: bool = True):
        raise NotImplementedError


class ColWiseParallel(PlanBase):
    """Column-parallel Linear / Embedding (reference:
    intermediate/tensor_parallel.py:31). Linear weight is (in, out): the out
    dim shards over ``mp``; bias shards likewise. Embedding weight is
    (vocab, dim): the hidden dim shards.
    """

    def __init__(self, gather_output: bool = False):
        self.gather_output = gather_output

    def apply(self, layer, process_mesh, shard_weight=True, shard_bias=True):
        w = getattr(layer, "weight", None)
        b = getattr(layer, "bias", None)
        if w is None:
            raise ValueError(
                f"ColWiseParallel expects a Linear/Embedding-like layer with "
                f".weight, got {type(layer).__name__}")
        if shard_weight:
            _shard_param(w, process_mesh, "mp", w.ndim - 1)
        if shard_bias and b is not None:
            _shard_param(b, process_mesh, "mp", 0)
        if self.gather_output:
            def gather(l, inputs, output):
                # gather the mp-sharded output (last) dim; other dims keep
                # their layout
                return _constrain_tree(output, process_mesh, {-1: None})
            layer.register_forward_post_hook(gather)


class RowWiseParallel(PlanBase):
    """Row-parallel Linear / vocab-parallel Embedding (reference:
    intermediate/tensor_parallel.py:83). Linear weight shards the in dim;
    bias stays replicated. Embedding shards the vocab dim."""

    def __init__(self, is_input_parallel: bool = True):
        self.is_input_parallel = is_input_parallel

    def apply(self, layer, process_mesh, shard_weight=True, shard_bias=False):
        w = getattr(layer, "weight", None)
        if w is None:
            raise ValueError(
                f"RowWiseParallel expects a Linear/Embedding-like layer with "
                f".weight, got {type(layer).__name__}")
        if shard_weight:
            _shard_param(w, process_mesh, "mp", 0)
        # bias of a row-parallel linear applies after the (compiler-inserted)
        # allreduce -> replicated; nothing to do.


def _constrain_tree(x, mesh: ProcessMesh, dim_to_axis: Dict[int, str]):
    """with_sharding_constraint over every array in x: tensor dim d pinned to
    mesh axis dim_to_axis[d] (when divisible) or forced replicated (axis
    None); other dims stay UNCONSTRAINED (GSPMD keeps whatever layout flows
    in — e.g. the dp-sharded batch dim)."""
    jm = mesh.to_jax_mesh()
    rest = PartitionSpec.UNCONSTRAINED

    def one(v):
        val = v._value if hasattr(v, "_value") else v
        if not hasattr(val, "ndim"):
            return v
        entries: List[Any] = [rest] * val.ndim
        for d, ax in dim_to_axis.items():
            dd = d if d >= 0 else val.ndim + d
            if not 0 <= dd < val.ndim:
                continue
            if ax is None:  # force this dim replicated (gathered)
                entries[dd] = None
            elif val.shape[dd] % jm.shape[ax] == 0:
                entries[dd] = ax
        con = lax.with_sharding_constraint(
            val, NamedSharding(jm, PartitionSpec(*entries)))
        if hasattr(v, "_value"):
            from ..._core.tensor import Tensor
            out = Tensor(con, _internal=True)
            out.stop_gradient = v.stop_gradient
            return out
        return con
    return jax.tree_util.tree_map(
        one, x, is_leaf=lambda t: hasattr(t, "_value"))


class PrepareLayerInput(PlanBase):
    """reference: intermediate/tensor_parallel.py:129 — run ``fn(mesh)`` as a
    forward pre-hook on the matched layer."""

    def __init__(self, fn: Optional[Callable] = None):
        self.fn = fn

    def apply(self, layer, process_mesh, shard_weight=None, shard_bias=None):
        if self.fn is not None:
            layer.register_forward_pre_hook(self.fn(process_mesh))


class PrepareLayerOutput(PlanBase):
    """reference: intermediate/tensor_parallel.py:144."""

    def __init__(self, fn: Optional[Callable] = None):
        self.fn = fn

    def apply(self, layer, process_mesh, shard_weight=None, shard_bias=None):
        if self.fn is not None:
            layer.register_forward_post_hook(self.fn(process_mesh))


class SequenceParallelBegin(PlanBase):
    """Start sequence parallelism after this layer: its OUTPUT's sequence dim
    is pinned to the mp axis (reference: intermediate/tensor_parallel.py:209;
    the reference's split+transpose becomes a sharding constraint).
    ``need_transpose=True`` means activations are [batch, seq, hidden]
    (seq dim 1, the reference would transpose before splitting); False means
    they are already [seq, batch, hidden] (seq dim 0)."""

    def __init__(self, need_transpose: bool = True):
        self.seq_dim = 1 if need_transpose else 0

    def apply(self, layer, process_mesh, shard_weight=None, shard_bias=None):
        sd = self.seq_dim

        def hook(l, inputs, output):
            return _constrain_tree(output, process_mesh, {sd: "mp"})
        layer.register_forward_post_hook(hook)


class SequenceParallelEnd(PlanBase):
    """End sequence parallelism before this layer: its INPUT is constrained
    back to seq-sharded (the boundary where the compiler materialises the
    all-gather) (reference: intermediate/tensor_parallel.py:235)."""

    def __init__(self, need_transpose: bool = True):
        self.seq_dim = 1 if need_transpose else 0

    def apply(self, layer, process_mesh, shard_weight=None, shard_bias=None):
        sd = self.seq_dim

        def hook(l, inputs):
            return _constrain_tree(inputs, process_mesh, {sd: "mp"})
        layer.register_forward_pre_hook(hook)


class SequenceParallelEnable(PlanBase):
    """Run the matched layer itself under sequence parallelism: input and
    output both seq-sharded (reference: intermediate/tensor_parallel.py:261).
    """

    def apply(self, layer, process_mesh, shard_weight=None, shard_bias=None):
        def pre(l, inputs):
            return _constrain_tree(inputs, process_mesh, {1: "mp"})

        def post(l, inputs, output):
            return _constrain_tree(output, process_mesh, {1: "mp"})
        layer.register_forward_pre_hook(pre)
        layer.register_forward_post_hook(post)


class SequenceParallelDisable(PlanBase):
    """Opt the matched layer out: its input's seq dim is gathered back to
    replicated (reference: intermediate/tensor_parallel.py:296)."""

    def __init__(self, need_transpose: bool = True):
        self.seq_dim = 1 if need_transpose else 0

    def apply(self, layer, process_mesh, shard_weight=None, shard_bias=None):
        sd = self.seq_dim

        def pre(l, inputs):
            return _constrain_tree(inputs, process_mesh, {sd: None})
        layer.register_forward_pre_hook(pre)


# ---------------------------------------------------------------- passes ----

def tensor_parallel(model, optimizer=None, parallelize_plan=None, mesh=None):
    """Apply a {layer-name-pattern: plan} dict (reference:
    intermediate/tensor_parallel.py tensor_parallel). Patterns are matched
    fnmatch-style against ``named_sublayers`` names; a plan may also be a
    list of plans applied in order."""
    if parallelize_plan is None:
        return model, optimizer
    mesh = _default_mesh(mesh)
    _axis_index(mesh, "mp")  # validate early
    names = list(model.named_sublayers(include_self=True))
    for pattern, plan in parallelize_plan.items():
        plans = plan if isinstance(plan, (list, tuple)) else [plan]
        shard_weight, shard_bias = True, True
        if pattern.endswith(".weight"):
            pattern, shard_bias = pattern[:-len(".weight")], False
        elif pattern.endswith(".bias"):
            pattern, shard_weight = pattern[:-len(".bias")], False
        matched = [l for n, l in names if fnmatch.fnmatch(n, pattern)]
        if not matched:
            raise ValueError(
                f"parallelize_plan key {pattern!r} matched no sublayer "
                f"(names: {[n for n, _ in names][:20]}...)")
        for layer in matched:
            for p in plans:
                p.apply(layer, mesh, shard_weight, shard_bias)
    return model, optimizer


def sharded_data_parallel(model, optimizer=None, level=None, offload=False,
                          exclude_layer=None, mesh=None):
    """ZeRO levels over the ``dp`` axis (reference:
    intermediate/sharded_data_parallel.py). level 1/2: optimizer state
    sharded; level 3: parameters sharded too (gather-on-use by GSPMD).
    ``offload`` moves optimizer state to host RAM (pinned, streamed back per
    step) — see sharding.group_sharded for the mechanism."""
    mesh = _default_mesh(mesh)
    level = int(level or 0)
    excl = set(exclude_layer or [])

    def _excluded(name):
        return any(fnmatch.fnmatch(name, e) for e in excl)

    if level >= 3:
        dp_ax = _axis_index(mesh, "dp")
        dp_n = mesh.shape[dp_ax]
        for lname, sub in model.named_sublayers(include_self=True):
            if _excluded(lname):
                continue
            for pname, p in sub._parameters.items():
                if p is None:
                    continue
                if is_dist_tensor(p) and p._dist_mesh == mesh:
                    placements = list(p._dist_placements)
                else:
                    placements = [Replicate()] * mesh.ndim
                if not isinstance(placements[dp_ax], Replicate):
                    continue
                # first dim not already sharded & divisible
                used = {pl.dim for pl in placements if isinstance(pl, Shard)}
                for d in range(p.ndim):
                    if d not in used and p.shape[d] % dp_n == 0:
                        placements[dp_ax] = Shard(d)
                        shard_tensor(p, mesh, placements)
                        break
    if optimizer is not None and level >= 1:
        dp_ax = _axis_index(mesh, "dp")
        dp_n = mesh.shape[dp_ax]
        # mark every param as dist (replicated layout is a no-op) so the
        # optimizer-state hook fires for plain params too; collect the ids
        # of excluded layers' params (shard_fn receives the accumulator
        # slot name, not the layer name)
        excluded_pids = set()
        for lname, sub in model.named_sublayers(include_self=True):
            for p in sub._parameters.values():
                if p is None:
                    continue
                if _excluded(lname):
                    excluded_pids.add(id(p))
                if not (is_dist_tensor(p) and p._dist_mesh == mesh):
                    shard_tensor(p, mesh, [Replicate()] * mesh.ndim)

        def shard_fn(slot, p, pmesh, placements):
            if id(p) in excluded_pids:
                return pmesh, placements
            placements = list(placements)
            if isinstance(placements[dp_ax], Replicate):
                used = {pl.dim for pl in placements
                        if isinstance(pl, Shard)}
                for d in range(p.ndim):
                    if d not in used and p.shape[d] % dp_n == 0:
                        placements[dp_ax] = Shard(d)
                        break
            return pmesh, placements
        shard_optimizer(optimizer, shard_fn)
        optimizer._zero_offload = bool(offload)
        if offload:
            from ..sharding.offload import offload_optimizer_states
            offload_optimizer_states(optimizer)
    model._sharding_level = level
    return model, optimizer


def pipeline_parallel(model, optimizer=None, split_spec=None, mesh=None):
    """Segment the model into pp stages (reference:
    intermediate/pipeline_parallel.py). ``split_spec`` is either a
    {layer-name: SplitPoint} dict (a stage boundary at each named layer) or a
    string prefix naming a LayerList whose entries are split evenly.

    Stage ids are recorded as ``sublayer._pp_stage``; scheduled execution
    (GPipe/1F1B/interleave) is fleet's PipelineParallel + pp_spmd engines
    (fleet/meta_parallel/pp_spmd.py), which consume the same stage marking.
    In-place execution here is numerically identical to any schedule.
    """
    if split_spec is None:
        return model, optimizer
    mesh = _default_mesh(mesh)
    pp_n = mesh.shape[_axis_index(mesh, "pp")] if "pp" in mesh.dim_names \
        else None
    names = list(model.named_sublayers(include_self=False))
    if isinstance(split_spec, str):
        entries = [(n, l) for n, l in names
                   if n.startswith(split_spec + ".") and
                   n.count(".") == split_spec.count(".") + 1]
        if not entries:
            raise ValueError(f"split_spec prefix {split_spec!r} matched "
                             f"no sublayers")
        if pp_n is None:
            raise ValueError(
                "string split_spec splits evenly over the mesh's 'pp' axis, "
                f"but mesh {mesh} has none; pass an explicit "
                "{name: SplitPoint} dict instead")
        k = min(pp_n, len(entries))
        # balanced split into exactly k stages (remainder spread over the
        # first stages, np.array_split-style); boundary after each stage
        # except the last
        base, rem = divmod(len(entries), k)
        sizes = [base + 1] * rem + [base] * (k - rem)
        idx, boundaries = -1, set()
        for sz in sizes[:-1]:
            idx += sz
            boundaries.add(entries[idx][0])
        split_spec = {n: SplitPoint.END for n in boundaries}
    # DFS yields a split layer's descendants immediately after it; an END
    # boundary takes effect only once the walk leaves that subtree.
    stage, pending = 0, None
    for n, l in names:
        if pending is not None and not n.startswith(pending + "."):
            stage += 1
            pending = None
        if n in split_spec and split_spec[n] == SplitPoint.BEGINNING and \
                (pending is None or not n.startswith(pending + ".")):
            stage += 1
        l._pp_stage = stage
        if n in split_spec and split_spec[n] == SplitPoint.END:
            pending = n
    # a boundary with no layers after it creates no stage
    model._pp_num_stages = stage + 1
    return model, optimizer


def parallelize(model, optimizer=None, mesh=None, dp_config=None,
                mp_config=None, pp_config=None):
    """reference: intermediate/parallelize.py:21 — apply pp, then mp, then
    dp, then finalize."""
    mesh = _default_mesh(mesh)
    if pp_config is not None:
        assert isinstance(pp_config, dict)
        model, optimizer = pipeline_parallel(
            model, optimizer, pp_config.get("split_spec"), mesh)
    if mp_config is not None:
        assert isinstance(mp_config, dict)
        model, optimizer = tensor_parallel(
            model, optimizer, mp_config.get("parallelize_plan"), mesh)
    if dp_config is not None:
        assert isinstance(dp_config, dict)
        model, optimizer = sharded_data_parallel(
            model, optimizer,
            level=dp_config.get("sharding_level"),
            offload=bool(dp_config.get("offload")),
            exclude_layer=dp_config.get("exclude_layer"), mesh=mesh)
    model._parallelize_mesh = mesh
    return model, optimizer


def parallelize_model(model, mesh=None, dp_config=None, mp_config=None,
                      pp_config=None):
    model, _ = parallelize(model, None, mesh, dp_config, mp_config, pp_config)
    return model


def parallelize_optimizer(model, optimizer, mesh=None, dp_config=None,
                          mp_config=None, pp_config=None):
    level = dp_config.get("sharding_level") if dp_config else None
    _, optimizer = sharded_data_parallel(
        model, optimizer, level=level,
        offload=bool(dp_config.get("offload")) if dp_config else False,
        exclude_layer=dp_config.get("exclude_layer") if dp_config else None,
        mesh=_default_mesh(mesh))
    return optimizer
