"""Semi-auto parallel Strategy config (reference: python/paddle/distributed/
auto_parallel/strategy.py Strategy:191 — nested config bags: sharding, amp,
recompute, pipeline, fused_passes, gradient_merge...).

The TPU build consumes these knobs in ``to_static``/``DistModel``
(dist_model.py): sharding maps to ZeRO levels over the dp axis, amp to the
bf16 train-step path, recompute to jax.checkpoint, pipeline to the SPMD
schedules — all resolved when the step function is built.
"""
from __future__ import annotations

import copy
from typing import Any, Dict, Optional


class _Config:
    _defaults: Dict[str, Any] = {}

    def __init__(self, overrides: Optional[Dict[str, Any]] = None):
        for k, v in self._defaults.items():
            setattr(self, k, copy.deepcopy(v))
        for k, v in (overrides or {}).items():
            setattr(self, k, v)

    def to_dict(self) -> Dict[str, Any]:
        return {k: getattr(self, k) for k in self._defaults}

    def __repr__(self):
        return f"{type(self).__name__}({self.to_dict()})"


class ShardingConfig(_Config):
    _defaults = {"enable": False, "stage": 1, "degree": 8,
                 "offload": False}


class AMPConfig(_Config):
    _defaults = {"enable": False, "dtype": "bfloat16", "level": "O1",
                 "init_loss_scaling": 32768.0, "use_master_weights": True}


class RecomputeConfig(_Config):
    _defaults = {"enable": False, "checkpoints": None,
                 "refined_ops_patterns": None}


class PipelineConfig(_Config):
    _defaults = {"enable": False, "schedule_mode": "1F1B",
                 "micro_batch_size": 1, "accumulate_steps": 1}


class FusedPassesConfig(_Config):
    _defaults = {"enable": False, "fused_passes_list": []}


class GradientMergeConfig(_Config):
    _defaults = {"enable": False, "k_steps": 1, "avg": True}


class MPOptimizationConfig(_Config):
    _defaults = {"enable": False, "replace_with_parallel_cross_entropy":
                 False}


class DPOptimizationConfig(_Config):
    _defaults = {"enable": False, "fuse_all_reduce_ops": True}


class Strategy:
    """reference: auto_parallel/strategy.py:191."""

    _SECTIONS = {
        "sharding": ShardingConfig, "amp": AMPConfig,
        "recompute": RecomputeConfig, "pipeline": PipelineConfig,
        "fused_passes": FusedPassesConfig,
        "gradient_merge": GradientMergeConfig,
        "mp_optimization": MPOptimizationConfig,
        "dp_optimization": DPOptimizationConfig,
    }

    def __init__(self, config: Optional[Dict[str, Any]] = None):
        if config is not None and not isinstance(config, dict):
            raise ValueError(f"Expected a dictionary. But received: {config}")
        cfg = config or {}
        for name, cls in self._SECTIONS.items():
            setattr(self, name, cls(cfg.get(name)))
        self.auto_mode = cfg.get("auto_mode", "semi")
        self.seed = cfg.get("seed", None)

    def __repr__(self):
        parts = ", ".join(f"{n}={getattr(self, n)!r}"
                          for n in self._SECTIONS)
        return f"Strategy({parts})"
