"""Step/comm hang watchdog.

reference: paddle/phi/core/distributed/comm_task_manager.cc:67 — background
threads track per-collective timeouts and dump diagnostics when a rank
hangs. Under XLA there are no per-collective handles to track (collectives
compile into the step program), so the TPU-native unit of watching is the
*step*: if the host loop does not tick within the timeout, the step program
(or a host-side deadlock) is hung.

On timeout the watchdog dumps every Python thread's stack (faulthandler,
like the reference's stack-trace dump) to stderr and the log file, then
either calls the user callback, raises in the main thread, or hard-exits —
turning silent hangs (exit 124 by an outer killer) into diagnosable errors.
"""
from __future__ import annotations

import faulthandler
import os
import sys
import threading
import time
from typing import Callable, Optional

from ..observability import hooks as _obs

_WATCHDOG_ENV = "PADDLE_STEP_TIMEOUT"


class StepWatchdog:
    """Arm with a timeout; call :meth:`tick` every step. If no tick arrives
    within ``timeout`` seconds, dump all thread stacks and act.

    action: "raise" (default; interrupts the main thread — delivered as
    KeyboardInterrupt, the only exception _thread.interrupt_main can
    raise), "exit" (os._exit(124) after the dump — for driver-run
    artifacts where any exit beats a hang), or "callback".
    """

    def __init__(self, timeout: float, action: str = "raise",
                 callback: Optional[Callable] = None,
                 log_path: Optional[str] = None, name: str = "step",
                 start_grace: Optional[float] = None):
        if action not in ("raise", "exit", "callback"):
            raise ValueError(action)
        self.timeout = float(timeout)
        self.action = action
        self.callback = callback
        self.log_path = log_path
        self.name = name
        # the first step includes XLA compilation (minutes on TPU); give it
        # extra slack so a steady-state-sized timeout doesn't kill a
        # healthy compile (reference: comm watchdog's separate init timeout)
        self.start_grace = float(start_grace) if start_grace is not None \
            else max(self.timeout * 9, 600.0)
        self._grace_pending = True
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._fired = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- lifecycle ----
    def start(self):
        if self._thread is not None:
            return self
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=f"watchdog-{self.name}")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # ---- per-step ----
    def tick(self):
        self._grace_pending = False
        self._last = time.monotonic()
        if _obs.enabled:
            _obs.watchdog_tick(self.name)

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    # ---- internals ----
    def _dump_stacks(self):
        # stall telemetry: fired counter + last-stall gauge, plus a span
        # into the profiler collector (when recording) so the stall
        # window shows up in exported chrome traces. Never allowed to
        # break the dump/kill path the watchdog exists for.
        try:
            _obs.watchdog_fired(self.name, time.monotonic() - self._last)
        except Exception:
            pass
        msg = (f"[watchdog] no {self.name} tick for {self.timeout:.0f}s "
               f"(pid {os.getpid()}) — dumping all thread stacks\n")
        sys.stderr.write(msg)
        sys.stderr.flush()
        try:
            faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
        except Exception:
            pass
        if self.log_path:
            try:
                with open(self.log_path, "a") as f:
                    f.write(msg)
                    faulthandler.dump_traceback(file=f, all_threads=True)
            except OSError:
                pass

    def _loop(self):
        while not self._stop.wait(min(1.0, self.timeout / 4)):
            limit = self.timeout + (self.start_grace if self._grace_pending
                                    else 0.0)
            if time.monotonic() - self._last <= limit:
                continue
            self._fired.set()
            self._dump_stacks()
            if self.action == "callback" and self.callback is not None:
                try:
                    self.callback()
                finally:
                    self._last = time.monotonic()
                continue
            if self.action == "exit":
                os._exit(124)
            # "raise": interrupt the main thread (KeyboardInterrupt)
            import _thread
            _thread.interrupt_main()
            self._last = time.monotonic()

    @classmethod
    def from_env(cls, default: Optional[float] = None, **kw
                 ) -> Optional["StepWatchdog"]:
        """Build from PADDLE_STEP_TIMEOUT seconds (unset/0 -> None)."""
        v = os.environ.get(_WATCHDOG_ENV)
        t = float(v) if v else (default or 0)
        return cls(t, **kw).start() if t > 0 else None
