"""Parameter server (reference: paddle/fluid/distributed/ps/ — brpc
PS client/server service/brpc_ps_client.h, table storage table/
(MemoryDenseTable, MemorySparseTable, SSD), python runtime
python/paddle/distributed/ps/the_one_ps.py).

TPU-native interpretation: the PS serves *sparse embedding* workloads
whose tables exceed device HBM. Server processes keep tables in host RAM
(dict-of-rows sparse + ndarray dense) with table-side optimizers (SGD /
Adagrad — the reference's sparse accessor rules); trainers pull rows,
compute the dense part on TPU, and push gradients on backward (PyLayer
hook). Transport is the framework RPC layer — the control-plane analog of
the reference's brpc service.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional

import numpy as np

# module-level table registry: lives in the SERVER process; RPC handlers
# (plain functions, importable at the callee) operate on it
_tables: Dict[str, "Table"] = {}
_lock = threading.Lock()


@dataclasses.dataclass
class TableConfig:
    name: str
    dim: int
    kind: str = "sparse"            # "sparse" | "dense" | "ssd"
    optimizer: str = "adagrad"      # "sgd" | "adagrad"
    lr: float = 0.05
    init_std: float = 0.01
    dense_rows: int = 0             # for dense tables
    # ssd tier (reference: paddle/fluid/distributed/ps/table/
    # ssd_sparse_table.h — RocksDB-backed rows + in-RAM hot cache):
    cache_rows: int = 4096          # hot rows kept in RAM (LRU)
    path: str = ""                  # spill directory ("" -> tempdir)


class Table:
    def __init__(self, cfg: TableConfig):
        self.cfg = cfg
        # the RPC server dispatches handlers on threads (ThreadingTCPServer);
        # concurrent trainers hitting one table must serialize row access or
        # a racing _init_row/+= pair silently drops an update
        self._tlock = threading.RLock()
        if cfg.kind == "dense":
            import zlib
            rng = np.random.default_rng(zlib.crc32(cfg.name.encode()))
            self.dense = (rng.standard_normal(
                (cfg.dense_rows, cfg.dim)) * cfg.init_std).astype(
                np.float32)
            self.dense_g2 = np.zeros_like(self.dense)
        else:
            self.rows: Dict[int, np.ndarray] = {}
            self.g2: Dict[int, np.ndarray] = {}

    def _init_row(self, key: int) -> np.ndarray:
        # zlib.crc32, NOT hash(): str hashing is salted per process, and
        # row init must be identical across server processes/restarts
        import zlib
        seed = ((zlib.crc32(self.cfg.name.encode()) << 20)
                ^ (int(key) & 0xFFFFFFFF))
        rng = np.random.default_rng(seed)
        return (rng.standard_normal(self.cfg.dim) *
                self.cfg.init_std).astype(np.float32)

    # ---- sparse ----
    def pull_sparse(self, keys: np.ndarray) -> np.ndarray:
        out = np.empty((len(keys), self.cfg.dim), np.float32)
        with self._tlock:
            for i, k in enumerate(keys.tolist()):
                row = self.rows.get(k)
                if row is None:
                    row = self.rows[k] = self._init_row(k)
                out[i] = row
        return out

    def push_sparse(self, keys: np.ndarray, grads: np.ndarray):
        lr = self.cfg.lr
        with self._tlock:
            for i, k in enumerate(keys.tolist()):
                row = self.rows.get(k)
                if row is None:
                    row = self.rows[k] = self._init_row(k)
                g = grads[i]
                if self.cfg.optimizer == "adagrad":
                    acc = self.g2.setdefault(
                        k, np.zeros(self.cfg.dim, np.float32))
                    acc += g * g
                    row -= lr * g / (np.sqrt(acc) + 1e-8)
                else:
                    row -= lr * g

    def apply_delta(self, keys: np.ndarray, deltas: np.ndarray):
        """Geo-mode merge: add raw parameter deltas (no optimizer state —
        reference GeoCommunicator sends (param - old)/trainer_num and the
        server adds it; paddle/fluid/distributed/ps/service/communicator/
        communicator.cc SendSparse/RecvSparse)."""
        with self._tlock:
            for i, k in enumerate(keys.tolist()):
                row = self.rows.get(k)
                if row is None:
                    row = self.rows[k] = self._init_row(k)
                row += deltas[i]

    # ---- checkpoint (reference: the PS persists table shards —
    # save_persistables; ssd_sparse_table.h Save/Load) ----
    def save(self, path: str):
        with self._tlock:
            if self.cfg.kind == "dense":
                np.savez(path, kind="dense", dense=self.dense,
                         dense_g2=self.dense_g2)
                return
            n, d = len(self.rows), self.cfg.dim
            keys = np.fromiter(self.rows.keys(), np.int64, n)
            rows = (np.stack([self.rows[k] for k in keys.tolist()])
                    if n else np.zeros((0, d), np.float32))
            zero = np.zeros(d, np.float32)
            g2 = (np.stack([self.g2.get(k, zero) for k in keys.tolist()])
                  if n else np.zeros((0, d), np.float32))
            np.savez(path, kind="sparse", keys=keys, rows=rows, g2=g2)

    def load(self, path: str):
        with np.load(path) as z:
            if str(z["kind"]) == "dense":
                dense = z["dense"]
                g2 = z["dense_g2"]
                with self._tlock:
                    self.dense = np.array(dense, np.float32)
                    self.dense_g2 = np.array(g2, np.float32)
                return
            keys, rows, g2 = z["keys"], z["rows"], z["g2"]
        with self._tlock:
            self.rows.clear()
            self.g2.clear()
            for i, k in enumerate(keys.tolist()):
                self.rows[k] = np.array(rows[i], np.float32)
                self.g2[k] = np.array(g2[i], np.float32)

    # ---- dense ----
    def pull_dense(self) -> np.ndarray:
        with self._tlock:
            return self.dense.copy()

    def push_dense(self, grads: np.ndarray):
        lr = self.cfg.lr
        with self._tlock:
            if self.cfg.optimizer == "adagrad":
                self.dense_g2 += grads * grads
                self.dense -= lr * grads / (np.sqrt(self.dense_g2) + 1e-8)
            else:
                self.dense -= lr * grads


class SSDTable(Table):
    """Disk-backed sparse table (reference: paddle/fluid/distributed/ps/
    table/ssd_sparse_table.h — the "100B features" tier). Re-designed with
    no external KV dependency: fixed-size records (row + adagrad
    accumulator, 2*dim float32) live in one slot file addressed through an
    in-RAM key->slot index; a bounded LRU cache holds hot rows in RAM and
    evicted rows write back to their slot. The key index stays in RAM —
    the same ~O(#keys) RAM the reference pays for its RocksDB index/bloom
    layer — while row payload (the dominant cost) lives on disk.
    """

    _REC_GROW = 65536  # slots per file extension

    def __init__(self, cfg: TableConfig):
        import os
        import tempfile
        self.cfg = cfg
        self._dim = cfg.dim
        self._rec = 2 * cfg.dim * 4  # row + g2, float32
        d = cfg.path or tempfile.mkdtemp(prefix=f"ps_ssd_{cfg.name}_")
        os.makedirs(d, exist_ok=True)
        self._path = os.path.join(d, f"{cfg.name}.slots")
        self._f = open(self._path, "w+b")
        # the RPC server dispatches handlers on threads; seek+read/write on
        # the shared handle (and cache/index mutation) must be serialized
        self._tlock = threading.RLock()
        self._capacity = 0
        self._slots: Dict[int, int] = {}      # key -> slot (RAM index)
        # hot cache: insertion-ordered dict as LRU; values (row, g2)
        self._cache: "Dict[int, tuple]" = {}
        self._evictions = 0

    # --- slot io ---
    def _ensure_capacity(self, slot: int):
        if slot >= self._capacity:
            self._capacity += self._REC_GROW
            self._f.truncate(self._capacity * self._rec)

    def _write_slot(self, slot: int, row: np.ndarray, g2: np.ndarray):
        self._ensure_capacity(slot)
        self._f.seek(slot * self._rec)
        self._f.write(row.tobytes())
        self._f.write(g2.tobytes())

    def _read_slot(self, slot: int):
        self._f.seek(slot * self._rec)
        buf = self._f.read(self._rec)
        arr = np.frombuffer(buf, np.float32).copy()
        return arr[:self._dim], arr[self._dim:]

    # --- LRU cache ---
    def _evict_if_full(self):
        while len(self._cache) > self.cfg.cache_rows:
            k, (row, g2) = next(iter(self._cache.items()))
            del self._cache[k]
            self._write_slot(self._slots[k], row, g2)
            self._evictions += 1

    def _get(self, key: int):
        hit = self._cache.pop(key, None)
        if hit is not None:
            self._cache[key] = hit          # re-insert as most-recent
            return hit
        slot = self._slots.get(key)
        if slot is None:
            self._slots[key] = len(self._slots)
            row = self._init_row(key)
            g2 = np.zeros(self._dim, np.float32)
        else:
            row, g2 = self._read_slot(slot)
        self._cache[key] = (row, g2)
        self._evict_if_full()
        return row, g2

    # --- Table API ---
    def pull_sparse(self, keys: np.ndarray) -> np.ndarray:
        out = np.empty((len(keys), self._dim), np.float32)
        with self._tlock:
            for i, k in enumerate(keys.tolist()):
                out[i] = self._get(k)[0]
        return out

    def push_sparse(self, keys: np.ndarray, grads: np.ndarray):
        lr = self.cfg.lr
        with self._tlock:
            for i, k in enumerate(keys.tolist()):
                row, g2 = self._get(k)
                g = grads[i]
                if self.cfg.optimizer == "adagrad":
                    g2 += g * g
                    row -= lr * g / (np.sqrt(g2) + 1e-8)
                else:
                    row -= lr * g
                self._cache[k] = (row, g2)

    def apply_delta(self, keys: np.ndarray, deltas: np.ndarray):
        with self._tlock:
            for i, k in enumerate(keys.tolist()):
                row, g2 = self._get(k)
                row += deltas[i]
                self._cache[k] = (row, g2)

    def save(self, path: str):
        with self._tlock:
            self.flush()
            n, d = len(self._slots), self._dim
            keys = np.fromiter(self._slots.keys(), np.int64, n)
            rows = np.empty((n, d), np.float32)
            g2 = np.empty((n, d), np.float32)
            for i, k in enumerate(keys.tolist()):
                rows[i], g2[i] = self._read_slot(self._slots[k])
            np.savez(path, kind="sparse", keys=keys, rows=rows, g2=g2)

    def load(self, path: str):
        with np.load(path) as z:
            keys, rows, g2 = z["keys"], z["rows"], z["g2"]
        with self._tlock:
            # checkpoint is authoritative: post-save keys must not
            # survive (parity with Table.load's clear)
            self._cache.clear()
            self._slots.clear()
            for i, k in enumerate(keys.tolist()):
                self._slots[k] = i
                self._write_slot(i, np.ascontiguousarray(rows[i]),
                                 np.ascontiguousarray(g2[i]))
            self._f.flush()

    def flush(self):
        """Write every cached row back to its slot (checkpoint barrier)."""
        with self._tlock:
            for k, (row, g2) in self._cache.items():
                self._write_slot(self._slots[k], row, g2)
            self._f.flush()

    def stats(self) -> dict:
        import os
        with self._tlock:
            self._f.flush()
            return {"keys": len(self._slots),
                    "ram_rows": len(self._cache),
                    "evictions": self._evictions,
                    "disk_bytes": os.path.getsize(self._path)}

    @property
    def rows(self):  # len() parity with the RAM table
        return self._slots



class NativeSSDTable(SSDTable):
    """C++ SSD table (``_native/ssdtable.cpp``) behind the same contract:
    pull/push/flush/stats match SSDTable bit-for-bit (row INIT stays in
    python so the numpy init stream is identical; the native pull reports
    missing keys and the wrapper inserts their initialized rows). Falls
    back to the python table automatically when the toolchain is absent
    (table factory below).

    reference: paddle/fluid/distributed/ps/table/ssd_sparse_table.h — the
    reference's table storage layer is C++; so is this one.
    """

    def __init__(self, cfg: TableConfig):
        import os
        import ctypes
        import tempfile
        from ... import _native
        lib = _native.load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self.cfg = cfg
        self._dim = cfg.dim
        self._lib = lib
        d = cfg.path or tempfile.mkdtemp(prefix=f"ps_ssd_{cfg.name}_")
        os.makedirs(d, exist_ok=True)
        self._path = os.path.join(d, f"{cfg.name}.slots")
        self._h = lib.pt_ssd_open(self._path.encode(), cfg.dim,
                                  cfg.cache_rows)
        if not self._h:
            raise RuntimeError(f"pt_ssd_open failed for {self._path}")
        self._tlock = threading.RLock()
        self._nkeys = 0
        self._c_opt = 1 if cfg.optimizer == "adagrad" else 0

    def _ptr(self, arr, ctype):
        import ctypes
        return arr.ctypes.data_as(ctypes.POINTER(ctype))

    def pull_sparse(self, keys: np.ndarray) -> np.ndarray:
        import ctypes
        keys = np.ascontiguousarray(keys, np.int64)
        n = len(keys)
        out = np.empty((n, self._dim), np.float32)
        missing = np.empty(n, np.int64)
        with self._tlock:
            n_miss = self._lib.pt_ssd_pull(
                self._h, self._ptr(keys, ctypes.c_int64), n,
                self._ptr(out, ctypes.c_float),
                self._ptr(missing, ctypes.c_int64))
            if n_miss < 0:
                raise IOError(f"SSD table I/O failure ({self._path}) — "
                              "refusing to reinitialize trained rows")
            if n_miss:
                idx = missing[:n_miss]
                rows = np.stack([self._init_row(int(keys[i]))
                                 for i in idx])
                mk = np.ascontiguousarray(keys[idx])
                rows = np.ascontiguousarray(rows, np.float32)
                self._lib.pt_ssd_insert(
                    self._h, self._ptr(mk, ctypes.c_int64), n_miss,
                    self._ptr(rows, ctypes.c_float))
                out[idx] = rows
                self._nkeys += n_miss
        return out

    def push_sparse(self, keys: np.ndarray, grads: np.ndarray):
        self._push(keys, grads, self.cfg.lr, self._c_opt)

    def apply_delta(self, keys: np.ndarray, deltas: np.ndarray):
        # row -= 1.0 * (-delta) == row += delta; sgd mode (opt=0) leaves
        # the adagrad accumulator untouched, matching the python tables
        self._push(keys, np.negative(deltas), 1.0, 0)

    def _push(self, keys, grads, lr, opt):
        import ctypes
        keys = np.ascontiguousarray(keys, np.int64)
        grads = np.ascontiguousarray(grads, np.float32)
        skip_idx = np.empty(len(keys), np.int64)
        with self._tlock:
            skipped = self._lib.pt_ssd_push(
                self._h, self._ptr(keys, ctypes.c_int64), len(keys),
                self._ptr(grads, ctypes.c_float),
                float(lr), opt,
                self._ptr(skip_idx, ctypes.c_int64))
            if skipped < 0:
                raise IOError(f"SSD table I/O failure ({self._path})")
            if skipped:
                # push before pull on brand-new keys: init THOSE keys and
                # re-push ONLY them (re-pushing the whole batch would
                # double-apply the grads of keys the first call updated)
                idx = skip_idx[:skipped]
                sub_k = np.ascontiguousarray(keys[idx])
                sub_g = np.ascontiguousarray(grads[idx])
                self.pull_sparse(sub_k)
                rc = self._lib.pt_ssd_push(
                    self._h, self._ptr(sub_k, ctypes.c_int64), len(sub_k),
                    self._ptr(sub_g, ctypes.c_float),
                    float(lr), opt,
                    self._ptr(skip_idx, ctypes.c_int64))
                if rc != 0:
                    raise IOError(
                        f"SSD table push retry failed ({self._path})")

    def flush(self):
        with self._tlock:
            if self._lib.pt_ssd_flush(self._h) != 0:
                raise IOError(f"pt_ssd_flush failed for {self._path}")

    def save(self, path: str):
        import ctypes
        with self._tlock:
            n = self.stats()["keys"]
            keys = np.empty(n, np.int64)
            rows = np.empty((n, self._dim), np.float32)
            g2 = np.empty((n, self._dim), np.float32)
            got = self._lib.pt_ssd_dump(
                self._h, self._ptr(keys, ctypes.c_int64),
                self._ptr(rows, ctypes.c_float),
                self._ptr(g2, ctypes.c_float))
            if got != n:
                raise IOError(f"pt_ssd_dump failed for {self._path}")
        np.savez(path, kind="sparse", keys=keys, rows=rows, g2=g2)

    def load(self, path: str):
        import ctypes
        with np.load(path) as z:
            keys = np.ascontiguousarray(z["keys"], np.int64)
            rows = np.ascontiguousarray(z["rows"], np.float32)
            g2 = np.ascontiguousarray(z["g2"], np.float32)
        with self._tlock:
            rc = self._lib.pt_ssd_restore(
                self._h, self._ptr(keys, ctypes.c_int64), len(keys),
                self._ptr(rows, ctypes.c_float),
                self._ptr(g2, ctypes.c_float))
            if rc != 0:
                raise IOError(f"pt_ssd_restore failed for {self._path}")
            self._nkeys = self.stats()["keys"]

    def stats(self) -> dict:
        import ctypes
        st = np.zeros(4, np.int64)
        with self._tlock:
            self._lib.pt_ssd_stats(self._h, self._ptr(st, ctypes.c_int64))
        return {"keys": int(st[0]), "ram_rows": int(st[1]),
                "evictions": int(st[2]), "disk_bytes": int(st[3])}

    @property
    def rows(self):
        class _Sized:  # len() without materializing an O(#keys) dict
            def __init__(self, n):
                self._n = n

            def __len__(self):
                return self._n
        return _Sized(self.stats()["keys"])

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.pt_ssd_close(self._h)
                self._h = None
        except Exception:
            pass


def _make_ssd_table(cfg: TableConfig):
    """Native C++ table when the toolchain allows, python otherwise."""
    from ... import _native
    if _native.available():
        try:
            return NativeSSDTable(cfg)
        except Exception as e:  # real failure (path perms, open error):
            import warnings     # degrading silently would hide the slow
            warnings.warn(       # python fallback in production
                f"native SSD table unavailable ({type(e).__name__}: {e});"
                " falling back to the python table", RuntimeWarning)
    return SSDTable(cfg)


# ---- RPC-served functions (executed in the server process) ----
def _srv_create_table(cfg_dict: dict):
    with _lock:
        cfg = TableConfig(**cfg_dict)
        if cfg.name not in _tables:
            _tables[cfg.name] = (_make_ssd_table(cfg)
                                 if cfg.kind == "ssd" else Table(cfg))
    return True


def _srv_pull_sparse(name: str, keys: np.ndarray) -> np.ndarray:
    return _tables[name].pull_sparse(np.asarray(keys))


def _srv_push_sparse(name: str, keys, grads) -> bool:
    _tables[name].push_sparse(np.asarray(keys), np.asarray(grads))
    return True


def _srv_apply_delta(name: str, keys, deltas) -> bool:
    _tables[name].apply_delta(np.asarray(keys),
                              np.asarray(deltas, np.float32))
    return True


def _srv_apply_dense_delta(name: str, deltas) -> bool:
    t = _tables[name]
    with t._tlock:
        t.dense += np.asarray(deltas, np.float32)
    return True


def _srv_table_names() -> List[str]:
    with _lock:
        return sorted(_tables.keys())


def _srv_save_table(name: str, path: str) -> bool:
    import os
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    _tables[name].save(path)
    return True


def _srv_load_table(name: str, path: str) -> bool:
    _tables[name].load(path)
    return True


def _srv_pull_dense(name: str) -> np.ndarray:
    return _tables[name].pull_dense()


def _srv_push_dense(name: str, grads) -> bool:
    _tables[name].push_dense(np.asarray(grads))
    return True


def _srv_table_size(name: str) -> int:
    t = _tables[name]
    return t.cfg.dense_rows if t.cfg.kind == "dense" else len(t.rows)


def _srv_table_stats(name: str) -> dict:
    t = _tables[name]
    return t.stats() if isinstance(t, SSDTable) else {
        "keys": _srv_table_size(name), "ram_rows": _srv_table_size(name),
        "evictions": 0, "disk_bytes": 0}


class PsServer:
    """One PS shard (reference: brpc_ps_server.h). Uses the RPC worker
    registry: call after rpc.init_rpc(name=...)."""

    def __init__(self, tables: List[TableConfig]):
        for cfg in tables:
            _srv_create_table(dataclasses.asdict(cfg))


class PsClient:
    """reference: brpc_ps_client.h — pull/push against named servers.
    Sparse keys are mod-hash sharded across servers (key % num_servers)."""

    def __init__(self, server_names: List[str]):
        self.servers = list(server_names)
        self._table_names: List[str] = []

    def _rpc(self):
        from .. import rpc
        return rpc

    def create_table(self, cfg: TableConfig):
        for s in self.servers:
            self._rpc().rpc_sync(s, _srv_create_table,
                                 args=(dataclasses.asdict(cfg),))
        if cfg.name not in self._table_names:
            self._table_names.append(cfg.name)

    def _fanout(self, handler, name: str, keys: np.ndarray,
                vals: Optional[np.ndarray]):
        """Mod-hash shard keys (+row payload) across servers, fire the
        handler per shard, wait all; returns [(shard row indices, reply)]."""
        n = len(self.servers)
        parts = []
        for si in range(n):
            mask = (keys % n) == si
            if mask.any():
                args = ((name, keys[mask]) if vals is None
                        else (name, keys[mask], vals[mask]))
                parts.append((np.nonzero(mask)[0], self._rpc().rpc_async(
                    self.servers[si], handler, args=args)))
        return [(idx, fut.wait()) for idx, fut in parts]

    def pull_sparse(self, name: str, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.int64).ravel()
        if keys.size == 0:
            return np.zeros((0, 0), np.float32)
        rows = [None] * len(keys)
        for idx, vals in self._fanout(_srv_pull_sparse, name, keys, None):
            for j, i in enumerate(idx.tolist()):
                rows[i] = vals[j]
        return np.stack(rows).astype(np.float32)

    def push_sparse(self, name: str, keys: np.ndarray, grads: np.ndarray):
        keys = np.asarray(keys, np.int64).ravel()
        grads = np.asarray(grads, np.float32).reshape(len(keys), -1)
        self._fanout(_srv_push_sparse, name, keys, grads)

    def push_sparse_delta(self, name: str, keys: np.ndarray,
                          deltas: np.ndarray):
        """Geo-mode raw delta merge (no server-side optimizer)."""
        keys = np.asarray(keys, np.int64).ravel()
        deltas = np.asarray(deltas, np.float32).reshape(len(keys), -1)
        self._fanout(_srv_apply_delta, name, keys, deltas)

    def push_dense_delta(self, name: str, deltas: np.ndarray):
        self._rpc().rpc_sync(self.servers[0], _srv_apply_dense_delta,
                             args=(name, np.asarray(deltas)))

    def pull_dense(self, name: str) -> np.ndarray:
        return self._rpc().rpc_sync(self.servers[0], _srv_pull_dense,
                                    args=(name,))

    def push_dense(self, name: str, grads: np.ndarray):
        self._rpc().rpc_sync(self.servers[0], _srv_push_dense,
                             args=(name, np.asarray(grads)))

    def table_size(self, name: str) -> int:
        return sum(self._rpc().rpc_sync(s, _srv_table_size, args=(name,))
                   for s in self.servers)

    # ---- checkpoint (reference: fleet save/load persistables — each
    # server persists its own shard; the key partition is the mod-hash,
    # so shards reload onto the SAME server count) ----
    def _shard_path(self, dirname: str, name: str, si: int) -> str:
        import os
        return os.path.join(dirname, f"{name}.shard{si}.npz")

    def save_table(self, name: str, dirname: str):
        futs = [self._rpc().rpc_async(
            s, _srv_save_table,
            args=(name, self._shard_path(dirname, name, si)))
            for si, s in enumerate(self.servers)]
        for f in futs:
            f.wait()

    def load_table(self, name: str, dirname: str):
        futs = [self._rpc().rpc_async(
            s, _srv_load_table,
            args=(name, self._shard_path(dirname, name, si)))
            for si, s in enumerate(self.servers)]
        for f in futs:
            f.wait()

    def _all_table_names(self) -> List[str]:
        """Server-authoritative name list: tables created by OTHER
        workers or declared in init_server(*tables) must checkpoint too,
        not just the ones this client created."""
        names = list(self._table_names)
        for s in self.servers:
            for n in self._rpc().rpc_sync(s, _srv_table_names):
                if n not in names:
                    names.append(n)
        return names

    def save_persistables(self, dirname: str):
        for name in self._all_table_names():
            self.save_table(name, dirname)

    def load_persistables(self, dirname: str):
        for name in self._all_table_names():
            self.load_table(name, dirname)

    def table_stats(self, name: str) -> List[dict]:
        return [self._rpc().rpc_sync(s, _srv_table_stats, args=(name,))
                for s in self.servers]


def sparse_embedding(client: PsClient, table: str, ids,
                     training: bool = True):
    """Distributed embedding lookup with push-on-backward (reference:
    python/paddle/static/nn/common.py sparse_embedding + the PS pull/push
    pair). Returns a Tensor of shape ids.shape + (dim,)."""
    import jax.numpy as jnp
    from ..._core.tensor import Tensor
    from ...autograd.py_layer import PyLayer

    ids_np = np.asarray(ids.numpy() if isinstance(ids, Tensor) else ids,
                        np.int64)
    flat = ids_np.ravel()
    rows = client.pull_sparse(table, flat)      # (n, dim) host pull

    class _Lookup(PyLayer):
        @staticmethod
        def forward(ctx, rows_t):
            return rows_t

        @staticmethod
        def backward(ctx, grad):
            if training:
                client.push_sparse(table, flat, np.asarray(grad.numpy()))
            return grad

    out = _Lookup.apply(Tensor(jnp.asarray(rows), stop_gradient=False,
                               _internal=True))
    return out.reshape(list(ids_np.shape) + [rows.shape[1]])
