from .the_one_ps import (  # noqa: F401
    PsServer, PsClient, Table, TableConfig, sparse_embedding,
)
from .communicator import (  # noqa: F401
    AsyncCommunicator, GeoCommunicator, create_communicator,
)
