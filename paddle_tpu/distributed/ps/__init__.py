from .the_one_ps import (  # noqa: F401
    PsServer, PsClient, Table, TableConfig, sparse_embedding,
)
