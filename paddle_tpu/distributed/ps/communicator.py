"""PS trainer-side communicators: sync / async / geo.

Re-design of the reference's communicator stack (reference:
paddle/fluid/distributed/ps/service/communicator/communicator.h —
``AsyncCommunicator`` merges queued grads in a background thread and
pushes them to the PS; ``GeoCommunicator`` trains on a LOCAL copy of the
table and periodically merges (param - snapshot)/trainer_num deltas,
selected by ``DistributedStrategy.a_sync`` + ``a_sync_configs['k_steps']``
— python/paddle/distributed/fleet/base/distributed_strategy.py a_sync).

TPU-native interpretation: the dense model lives on-chip inside the jit
train step; the communicator governs only the host-side sparse-table
traffic, which is where the reference's async/geo modes matter (the
"100B features" tier). Mode selection mirrors the reference:

    k_steps == 0  -> async  (merge-and-push grads, background thread)
    k_steps  > 0  -> geo    (local training + delta merge every k steps)
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .the_one_ps import PsClient


class AsyncCommunicator:
    """Background merge-and-push of sparse grads.

    ``push_sparse`` enqueues and returns immediately; a daemon thread
    drains the queue, merges grads per key within a drained batch
    (reference: communicator.cc MergeAdd — duplicate ids sum), and issues
    one RPC push per table. ``flush()`` is the barrier the reference's
    barrier-with-table call provides.
    """

    def __init__(self, client: PsClient, max_merge: int = 64):
        self._client = client
        self._q: "queue.Queue[Optional[Tuple[str, np.ndarray, np.ndarray]]]" = (
            queue.Queue())
        self._max_merge = max_merge
        self._err: Optional[BaseException] = None
        self._stopped = False
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- trainer API --
    def push_sparse(self, name: str, keys: np.ndarray, grads: np.ndarray):
        if self._stopped:
            raise RuntimeError(
                "async communicator is stopped; push_sparse after stop() "
                "would enqueue onto a dead worker thread")
        if self._err is not None:
            raise RuntimeError("async communicator worker died") \
                from self._err
        # copy: push returns immediately, so the caller may legitimately
        # reuse its key/grad buffers for the next microbatch
        self._q.put((name, np.array(keys, np.int64, copy=True).ravel(),
                     np.array(grads, np.float32, copy=True)))

    def pull_sparse(self, name: str, keys: np.ndarray) -> np.ndarray:
        # async mode reads straight through (stale-by-design, like the
        # reference's async tables)
        return self._client.pull_sparse(name, keys)

    def flush(self):
        """Block until every queued push has been applied on the PS."""
        if self._stopped:
            raise RuntimeError(
                "async communicator is stopped; flush() after stop() would "
                "wait on a dead worker thread")
        self._q.join()
        if self._err is not None:
            raise RuntimeError("async communicator worker died") \
                from self._err

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self._q.put(None)
        self._thread.join(timeout=10)
        if self._err is not None:
            raise RuntimeError("async communicator worker died") \
                from self._err

    def __getattr__(self, name):
        # modes must be drop-in substitutable: everything the communicator
        # doesn't intercept (dense ops, create_table, stats) hits the client
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._client, name)

    # -- worker --
    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            batch = [item]
            ndone = 1
            # opportunistically coalesce whatever else is queued
            while len(batch) < self._max_merge:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    self._q.task_done()
                    if self._err is None:
                        self._drain(batch)
                    for _ in range(ndone):
                        self._q.task_done()
                    return
                batch.append(nxt)
                ndone += 1
            # after a failure the communicator is dead: later batches are
            # dropped (not applied out of order around the lost one) and
            # every flush/push raises until the caller rebuilds it
            if self._err is None:
                self._drain(batch)
            for _ in range(ndone):
                self._q.task_done()

    def _drain(self, batch):
        try:
            per_table: Dict[str, Dict[int, np.ndarray]] = {}
            for name, keys, grads in batch:
                acc = per_table.setdefault(name, {})
                grads = grads.reshape(len(keys), -1)
                for i, k in enumerate(keys.tolist()):
                    if k in acc:
                        acc[k] = acc[k] + grads[i]
                    else:
                        acc[k] = grads[i]
            for name, acc in per_table.items():
                ks = np.fromiter(acc.keys(), np.int64, len(acc))
                gs = np.stack(list(acc.values()))
                self._client.push_sparse(name, ks, gs)
        except BaseException as e:  # noqa: BLE001 — surfaced on flush
            self._err = e


class GeoCommunicator:
    """Geo-SGD: local sparse training + periodic delta merge.

    The trainer keeps a local copy of every row it touches and applies
    plain SGD locally; every ``k_steps`` calls to :meth:`step` the
    accumulated movement ``(local - snapshot) / trainer_num`` is merged
    into the PS (server adds raw deltas — no server optimizer state) and
    the fresh server rows replace the local copy, folding in the other
    trainers' movement. Matches the reference's geo protocol
    (communicator.cc GeoCommunicator::SendSparse/RecvSparse).
    """

    def __init__(self, client: PsClient, k_steps: int = 10,
                 trainer_num: int = 1, lr: float = 0.05):
        if k_steps <= 0:
            raise ValueError("geo mode requires k_steps > 0")
        self._client = client
        self._k = k_steps
        self._n = max(1, trainer_num)
        self._lr = lr
        self._step = 0
        # per table: key -> local row / key -> snapshot-at-last-sync
        self._local: Dict[str, Dict[int, np.ndarray]] = {}
        self._snap: Dict[str, Dict[int, np.ndarray]] = {}
        # dense tables: whole-matrix local copy + snapshot
        self._dlocal: Dict[str, np.ndarray] = {}
        self._dsnap: Dict[str, np.ndarray] = {}
        self._table_lr: Dict[str, float] = {}

    def __getattr__(self, name):
        # drop-in substitutable with the bare client (dense ops,
        # stats pass straight through)
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._client, name)

    def create_table(self, cfg):
        # local SGD must step at the table's configured rate, not the
        # communicator default (geo's trainer-side optimizer is plain SGD
        # at the table lr — the reference's geo sparse rule)
        self._table_lr[cfg.name] = float(cfg.lr)
        return self._client.create_table(cfg)

    # -- trainer API --
    def pull_sparse(self, name: str, keys: np.ndarray) -> np.ndarray:
        keys = np.asarray(keys, np.int64).ravel()
        if keys.size == 0:
            return np.zeros((0, 0), np.float32)
        local = self._local.setdefault(name, {})
        snap = self._snap.setdefault(name, {})
        missing = [k for k in dict.fromkeys(keys.tolist())
                   if k not in local]
        if missing:
            mk = np.asarray(missing, np.int64)
            rows = self._client.pull_sparse(name, mk)
            for i, k in enumerate(missing):
                local[k] = rows[i].copy()
                snap[k] = rows[i].copy()
        return np.stack([local[k] for k in keys.tolist()])

    def push_sparse(self, name: str, keys: np.ndarray,
                    grads: np.ndarray):
        """Apply the grad LOCALLY (plain SGD — the reference's geo rule);
        nothing goes on the wire until the k-step sync."""
        keys = np.asarray(keys, np.int64).ravel()
        if keys.size == 0:
            return
        grads = np.asarray(grads, np.float32).reshape(len(keys), -1)
        self.pull_sparse(name, keys)        # materialize missing rows
        local = self._local[name]
        lr = self._table_lr.get(name, self._lr)
        for i, k in enumerate(keys.tolist()):
            local[k] -= lr * grads[i]

    def pull_dense(self, name: str) -> np.ndarray:
        if name not in self._dlocal:
            w = self._client.pull_dense(name)
            self._dlocal[name] = np.array(w, np.float32, copy=True)
            self._dsnap[name] = self._dlocal[name].copy()
        return self._dlocal[name]

    def push_dense(self, name: str, grads: np.ndarray):
        """Local SGD on the dense table; merged at the k-step sync like
        the sparse rows (the reference geo protocol covers dense vars the
        same way — trainer_nums-averaged deltas)."""
        w = self.pull_dense(name)
        w -= (self._table_lr.get(name, self._lr)
              * np.asarray(grads, np.float32))

    def step(self):
        """One trainer step; triggers the geo sync every k steps."""
        self._step += 1
        if self._step % self._k == 0:
            self.sync()

    def invalidate(self):
        """Drop every local copy/snapshot — next pulls refetch from the
        servers (needed after an external table mutation, e.g.
        load_persistables)."""
        self._local.clear()
        self._snap.clear()
        self._dlocal.clear()
        self._dsnap.clear()

    def sync(self):
        """Merge deltas into the PS and refresh EVERY local row — pull-only
        rows too, so reads fold in other trainers' movement instead of
        serving the first-pull value forever (reference RecvSparse delivers
        other trainers' diffs for all held ids)."""
        for name, local in self._local.items():
            snap = self._snap[name]
            allk = list(local.keys())
            if not allk:
                continue
            moved = [k for k in allk
                     if not np.array_equal(local[k], snap[k])]
            if moved:
                ks = np.asarray(moved, np.int64)
                deltas = np.stack([(local[k] - snap[k]) / self._n
                                   for k in moved])
                self._client.push_sparse_delta(name, ks, deltas)
            ak = np.asarray(allk, np.int64)
            fresh = self._client.pull_sparse(name, ak)
            for i, k in enumerate(allk):
                local[k] = fresh[i].copy()
                snap[k] = fresh[i].copy()
        for name, local in self._dlocal.items():
            delta = (local - self._dsnap[name]) / self._n
            if np.any(delta):
                self._client.push_dense_delta(name, delta)
            fresh = np.array(self._client.pull_dense(name), np.float32,
                             copy=True)
            self._dlocal[name] = fresh
            self._dsnap[name] = fresh.copy()


def create_communicator(client: PsClient, strategy=None,
                        trainer_num: int = 1, lr: float = 0.05):
    """Mode selection mirroring the reference's fleet wiring:
    ``a_sync=False`` -> sync (the bare client), ``a_sync=True`` ->
    async, ``a_sync_configs['k_steps'] > 0`` -> geo."""
    if strategy is None or not getattr(strategy, "a_sync", False):
        return client
    k = int(getattr(strategy, "a_sync_configs", {}).get("k_steps", 0))
    if k > 0:
        return GeoCommunicator(client, k_steps=k, trainer_num=trainer_num,
                               lr=lr)
    return AsyncCommunicator(client)
