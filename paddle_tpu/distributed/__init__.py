"""paddle_tpu.distributed: the distributed surface.

Mirrors the reference's ``paddle.distributed`` package
(reference: python/paddle/distributed/__init__.py) re-designed for TPU:
mesh axes instead of ProcessGroups, GSPMD + XLA collectives over ICI/DCN
instead of NCCL, shard_map for manual-control schedules.
"""
from .mesh import (  # noqa: F401
    init_parallel_env as _init_mesh, is_initialized, get_rank,
    get_world_size, new_group, get_group, barrier, destroy_process_group,
    Group, ReduceOp, ParallelEnv, get_mesh, set_mesh, get_world_group,
)
from .parallel import (  # noqa: F401
    DataParallel, init_parallel_env, shard_local_batch,
)
from .collective import (  # noqa: F401
    all_reduce, all_gather, reduce_scatter, all_to_all, alltoall_single,
    broadcast, reduce, scatter, gather, send, recv, isend, irecv, P2POp,
    batch_isend_irecv, ppermute, shift,
)
from .collective import all_to_all as alltoall  # noqa: F401
from .auto_parallel import (  # noqa: F401
    ProcessMesh, Placement, Shard, Replicate, Partial, shard_tensor,
    dtensor_from_local, dtensor_to_local, reshard, shard_layer,
    shard_optimizer, unshard_dtensor, is_dist_tensor, get_placements,
)
from .auto_parallel.api import dtensor_from_local_list  # noqa: F401
from .auto_parallel import (  # noqa: F401
    parallelize, parallelize_model, parallelize_optimizer, ColWiseParallel,
    RowWiseParallel, PrepareLayerInput, PrepareLayerOutput, SplitPoint,
    SequenceParallelBegin, SequenceParallelEnd, SequenceParallelEnable,
    SequenceParallelDisable, Engine,
)
from . import fleet  # noqa: F401
from . import sharding  # noqa: F401
from . import checkpoint  # noqa: F401
from . import launch  # noqa: F401
from .spawn import spawn  # noqa: F401
from .watchdog import StepWatchdog  # noqa: F401
from .store import TCPStore, Store  # noqa: F401
from . import rpc  # noqa: F401
from . import auto_tuner  # noqa: F401
from . import ps  # noqa: F401
from .utils import moe_utils  # noqa: F401
from .fleet.fleet import fleet as _fleet_facade  # noqa: F401
from .checkpoint.api import save_state_dict, load_state_dict  # noqa: F401
from .extras import (  # noqa: F401
    get_backend, is_available, wait, ReduceType, ParallelMode,
    all_gather_object, broadcast_object_list, scatter_object_list,
    dtensor_from_fn, ShardingStage1, ShardingStage2, ShardingStage3,
    DistAttr, shard_dataloader, shard_scaler, split,
    reset_split_layer_cache,
    CountFilterEntry, ProbabilityEntry, ShowClickEntry,
)
from . import io  # noqa: F401
from .auto_parallel.strategy import Strategy  # noqa: F401
from .auto_parallel.dist_model import DistModel, to_static  # noqa: F401
from .fleet.dataset import InMemoryDataset, QueueDataset  # noqa: F401


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """reference: parallel_with_gloo.py — gloo's CPU-collective role is
    played by the XLA CPU backend here; rendezvous is jax.distributed."""
    from .mesh import init_parallel_env as _ipe
    _ipe()


def gloo_barrier():
    from .mesh import barrier as _b
    _b()


def gloo_release():
    pass


def get_mesh_dim_size(axis_name: str) -> int:
    m = get_mesh()
    return m.shape[axis_name] if m is not None and axis_name in m.shape \
        else 1
