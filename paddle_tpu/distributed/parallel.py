"""DataParallel + environment entry points.

Re-design of the reference's DP stack
(reference: python/paddle/distributed/parallel.py:219 DataParallel backed by
the C++ EagerReducer, paddle/fluid/distributed/collective/reducer.h:88 —
gradient bucketing + async allreduce on backward hooks).

TPU-native: under the single-controller SPMD model DP is *batch-axis
sharding* — inputs carry a sharding over the data axis, parameters are
replicated, and XLA emits ONE fused gradient all-reduce over ICI during the
backward of the jit-compiled train step. The EagerReducer's bucketing/overlap
machinery is subsumed by the XLA scheduler, so this wrapper's job is API
parity (scale_loss / no_sync / state passthrough) plus installing the data
sharding on inputs when a mesh is active.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .._core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import mesh as _mesh


class DataParallel(Layer):
    """reference: python/paddle/distributed/parallel.py:219."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters

    @property
    def _data_sharding(self) -> Optional[NamedSharding]:
        g = self._group
        mesh = (g.mesh if g is not None else _mesh.get_mesh())
        if mesh is None:
            return None
        axis = (g.axis_names[0] if g is not None else mesh.axis_names[0])
        return NamedSharding(mesh, PartitionSpec(axis))

    def forward(self, *inputs, **kwargs):
        sharding = self._data_sharding
        if sharding is not None and sharding.mesh.size > 1:
            nproc = jax.process_count()

            def place(x):
                if not (isinstance(x, Tensor) and x.ndim >= 1):
                    return x
                try:
                    if nproc > 1:
                        # multi-host: x is this process's LOCAL batch
                        return shard_local_batch(x, sharding)
                    if x.shape[0] % sharding.mesh.size == 0:
                        return Tensor(jax.device_put(x._value, sharding),
                                      _internal=True)
                except Exception:
                    return x
                return x
            inputs = tuple(place(x) for x in inputs)
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # gradient averaging is part of the compiled psum(mean) — identity
        return loss

    @contextlib.contextmanager
    def no_sync(self):
        # GSPMD has no eager grad sync to suppress; accumulate-then-step
        # naturally defers the all-reduce to the step that runs it.
        yield

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def shard_local_batch(data, sharding):
    """Multi-host data feeding: each process passes its LOCAL batch and gets
    back the GLOBAL batch-sharded array (the reference's per-rank DataLoader
    shard ≙ this process's slice of the dp axis). Single-process: a plain
    dp-sharded device_put.

    reference: python/paddle/io DistributedBatchSampler feeds each rank its
    split; under single-controller-per-host JAX the splits are knitted into
    one global array via make_array_from_process_local_data.
    """
    import numpy as np
    is_tensor = isinstance(data, Tensor)
    val = data._value if is_tensor else data
    if jax.process_count() > 1:
        local = np.asarray(val)
        gshape = (local.shape[0] * jax.process_count(),) + local.shape[1:]
        arr = jax.make_array_from_process_local_data(sharding, local, gshape)
    else:
        arr = jax.device_put(val, sharding)
    return Tensor(arr, _internal=True) if is_tensor else arr


def init_parallel_env(mesh_shape=None, axis_names=None):
    """reference: parallel.py:978 — see mesh.init_parallel_env."""
    return _mesh.init_parallel_env(mesh_shape=mesh_shape,
                                   axis_names=axis_names)


ParallelEnv = _mesh.ParallelEnv
