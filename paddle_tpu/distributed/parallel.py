"""DataParallel + environment entry points.

Re-design of the reference's DP stack
(reference: python/paddle/distributed/parallel.py:219 DataParallel backed by
the C++ EagerReducer, paddle/fluid/distributed/collective/reducer.h:88 —
gradient bucketing + async allreduce on backward hooks).

TPU-native: under the single-controller SPMD model DP is *batch-axis
sharding* — inputs carry a sharding over the data axis, parameters are
replicated, and XLA emits ONE fused gradient all-reduce over ICI during the
backward of the jit-compiled train step. The EagerReducer's bucketing/overlap
machinery is subsumed by the XLA scheduler, so this wrapper's job is API
parity (scale_loss / no_sync / state passthrough) plus installing the data
sharding on inputs when a mesh is active.
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from .._core.tensor import Tensor
from ..nn.layer.layers import Layer
from . import mesh as _mesh


class DataParallel(Layer):
    """reference: python/paddle/distributed/parallel.py:219."""

    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters

    @property
    def _data_sharding(self) -> Optional[NamedSharding]:
        g = self._group
        mesh = (g.mesh if g is not None else _mesh.get_mesh())
        if mesh is None:
            return None
        axis = (g.axis_names[0] if g is not None else mesh.axis_names[0])
        return NamedSharding(mesh, PartitionSpec(axis))

    def forward(self, *inputs, **kwargs):
        sharding = self._data_sharding
        if sharding is not None and sharding.mesh.size > 1:
            def place(x):
                if isinstance(x, Tensor) and x.ndim >= 1 and \
                        x.shape[0] % sharding.mesh.size == 0:
                    try:
                        return Tensor(jax.device_put(x._value, sharding),
                                      _internal=True)
                    except Exception:
                        return x
                return x
            inputs = tuple(place(x) for x in inputs)
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # gradient averaging is part of the compiled psum(mean) — identity
        return loss

    @contextlib.contextmanager
    def no_sync(self):
        # GSPMD has no eager grad sync to suppress; accumulate-then-step
        # naturally defers the all-reduce to the step that runs it.
        yield

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)


def init_parallel_env(mesh_shape=None, axis_names=None):
    """reference: parallel.py:978 — see mesh.init_parallel_env."""
    return _mesh.init_parallel_env(mesh_shape=mesh_shape,
                                   axis_names=axis_names)


ParallelEnv = _mesh.ParallelEnv
