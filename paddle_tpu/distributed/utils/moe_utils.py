"""MoE expert-parallel dispatch utilities.

Re-design of the reference's moe_utils
(reference: python/paddle/distributed/utils/moe_utils.py — global_scatter:20,
global_gather:153; MoE layer python/paddle/incubate/distributed/models/moe/
moe_layer.py:263 MoEScatter:99/MoEGather:149).

The reference routes variable-count token batches between ranks via NCCL
alltoall with per-rank counts. TPU/XLA requires STATIC shapes, so dispatch
is capacity-based (the standard GShard/Switch formulation the reference's
gates also implement): every expert receives a fixed-capacity [E, C, d]
buffer; overflow tokens drop, underflow pads — then ONE static all_to_all
moves expert rows to their owning devices over ICI.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..mesh import Group, in_mapped_context


def dispatch_capacity(num_tokens: int, num_experts: int,
                      capacity_factor: float = 1.25,
                      min_capacity: int = 4) -> int:
    cap = int(num_tokens * capacity_factor / num_experts)
    cap = max(cap, min_capacity)
    return cap


def expert_dispatch(x, gate_idx, gate_weight, num_experts: int,
                    capacity: int):
    """Scatter tokens into per-expert capacity buffers.

    x:           [T, d] tokens
    gate_idx:    [T, k] chosen expert per token (top-k)
    gate_weight: [T, k] combine weights
    returns (buffers [E, C, d], combine_info) where combine_info re-gathers
    expert outputs back to token order with weights (dropped tokens get 0).
    """
    T, d = x.shape
    k = gate_idx.shape[1]
    flat_e = gate_idx.reshape(-1)                       # [T*k]
    flat_w = gate_weight.reshape(-1)
    flat_tok = jnp.repeat(jnp.arange(T), k)
    # position of each (token, expert) pair within its expert's buffer
    onehot = jax.nn.one_hot(flat_e, num_experts, dtype=jnp.int32)  # [T*k, E]
    pos_in_e = (jnp.cumsum(onehot, axis=0) - 1) * onehot           # [T*k, E]
    pos = jnp.sum(pos_in_e, axis=1)                                # [T*k]
    keep = pos < capacity
    flat_w = jnp.where(keep, flat_w, 0.0)
    slot = jnp.where(keep, flat_e * capacity + pos, num_experts * capacity)
    buffers = jnp.zeros((num_experts * capacity + 1, d), x.dtype)
    buffers = buffers.at[slot].add(x[flat_tok])
    buffers = buffers[:-1].reshape(num_experts, capacity, d)
    combine = (flat_tok, slot, flat_w, T)
    return buffers, combine


def expert_combine(expert_out, combine):
    """Gather expert outputs back to [T, d] with combine weights."""
    flat_tok, slot, flat_w, T = combine
    E, C, d = expert_out.shape
    flat = jnp.concatenate(
        [expert_out.reshape(E * C, d), jnp.zeros((1, d), expert_out.dtype)])
    picked = flat[slot] * flat_w[:, None].astype(expert_out.dtype)
    out = jnp.zeros((T, d), expert_out.dtype).at[flat_tok].add(picked)
    return out


def global_scatter(x, local_count=None, global_count=None,
                   group: Optional[Group] = None):
    """reference: moe_utils.py:20 — move per-expert buffers to expert-owning
    devices. Static-shape equivalent: all_to_all on the leading (expert)
    axis inside the mapped regime; identity when ep degree is 1."""
    if group is None or group.nranks == 1 or not in_mapped_context(group):
        return x
    return lax.all_to_all(x, group.axis_names[0], split_axis=0,
                          concat_axis=0, tiled=True)


def global_gather(x, local_count=None, global_count=None,
                  group: Optional[Group] = None):
    """reference: moe_utils.py:153 — inverse of global_scatter (alltoall is
    self-inverse for equal splits)."""
    return global_scatter(x, local_count, global_count, group)


def assign_pos(x, cum_count, eff_num_len=None, name=None):
    """Token-position assignment for MoE all-to-all dispatch: tokens are
    grouped by expert id so that positions ``[cum_count[e-1],
    cum_count[e])`` hold the indices of tokens routed to expert ``e``
    (ids < 0 are dropped). ``eff_num_len`` bounds the output length
    (defaults to ``cum_count[-1]``).

    reference: paddle/phi/kernels/gpu/assign_pos_kernel.cu (AssignPos;
    the CPU kernel raises Unavailable there — this runs everywhere).
    Deviation: within an expert group the reference's atomic fill order
    is nondeterministic; here tokens keep ascending order (stable
    argsort) — MIGRATION.md.
    """
    import numpy as _np
    from ..._core.tensor import Tensor as _T
    from ...ops._registry import as_tensor as _as, raw as _raw
    ids = _np.asarray(_raw(_as(x))).reshape(-1)
    cc = _np.asarray(_raw(_as(cum_count))).reshape(-1)
    n = int(cc[-1]) if eff_num_len is None else \
        int(_np.asarray(_raw(_as(eff_num_len))).reshape(-1)[0])
    keep = _np.flatnonzero(ids >= 0)
    order = keep[_np.argsort(ids[keep], kind="stable")]
    return _T(order[:n].astype(cc.dtype))
