"""Search algorithms over parallel-config candidates.

Reference: python/paddle/distributed/auto_tuner/search.py — SearchAlgo
base with a prune loop, GridSearch (cost-ordered full grid), GBSSearch
(additionally searches the global batch size), CustomizeSearch (explicit
task list / CSV). The dp_estimation mode is subsumed here by the analytic
cost model: candidates are already emitted best-estimate-first, which is
what that mode approximates with a single-dp measurement.
"""
from __future__ import annotations

import csv
import os
from abc import ABC, abstractmethod
from typing import Dict, List, Optional

from .cost_model import estimate_step_time
from .prune import prune_with_history


class SearchAlgo(ABC):
    def __init__(self, tuner):
        self.tuner = tuner
        self._tasks_cache: Optional[List[Dict]] = None
        self._idx = 0

    @abstractmethod
    def _build_tasks(self) -> List[Dict]:
        ...

    def all_tasks(self) -> List[Dict]:
        """Task list, built once (grid generation + cost-model sort are
        not cheap; the search queue serves from the same cache)."""
        if self._tasks_cache is None:
            self._tasks_cache = self._build_tasks()
        return list(self._tasks_cache)

    def search_once(self, history: List[Dict]) -> Optional[Dict]:
        """Next un-pruned task, or None when exhausted (search.py:62)."""
        while True:
            cfg = self._next()
            if cfg is None:
                return None
            if not prune_with_history(self.tuner, cfg, history):
                return cfg

    def _next(self) -> Optional[Dict]:
        if self._tasks_cache is None:
            self._tasks_cache = self._build_tasks()
        if self._idx >= len(self._tasks_cache):
            return None
        cfg = self._tasks_cache[self._idx]
        self._idx += 1
        return dict(cfg)


class GridSearch(SearchAlgo):
    """Full grid, best-estimated-cost first (search.py:48 GridSearch;
    ordering ≙ its need_baseline memory/performance sort, driven here by
    the TPU cost model instead of a first measured run)."""

    def _build_tasks(self) -> List[Dict]:
        cands = self.tuner.generate_candidates()
        cands.sort(key=lambda c: estimate_step_time(
            self.tuner.model, c, chip=self.tuner.chip))
        return cands


class GBSSearch(SearchAlgo):
    """Grid × global-batch-size scan (search.py:120 GBSSearch): for each
    parallel shape, also try scaled global batches; the metric feedback
    decides the winner."""

    def __init__(self, tuner, gbs_candidates: Optional[List[int]] = None):
        super().__init__(tuner)
        base = tuner.model.get("global_batch", 8)
        self.gbs_candidates = gbs_candidates or [
            base, base * 2, base * 4]

    def _build_tasks(self) -> List[Dict]:
        # round-robin across batch sizes, each group best-estimate-first:
        # absolute step time always grows with global batch, so any global
        # sort would group by gbs and a task_limit would starve all but
        # one batch size — interleaving guarantees every gbs gets its best
        # shapes explored
        groups = []
        for gbs in self.gbs_candidates:
            model = dict(self.tuner.model, global_batch=gbs)
            cands = self.tuner.generate_candidates(model)
            cands.sort(key=lambda c: estimate_step_time(
                model, c, chip=self.tuner.chip))
            groups.append([dict(c, global_batch=gbs) for c in cands])
        out = []
        for i in range(max((len(g) for g in groups), default=0)):
            for g in groups:
                if i < len(g):
                    out.append(g[i])
        return out


class CustomizeSearch(SearchAlgo):
    """Explicit task list, in order (search.py:143 CustomizeSearch —
    configs come from the user, only history pruning applies). Accepts a
    list of dicts or a CSV path with axis-name headers."""

    def __init__(self, tuner, configs=None, configs_csv: str = None):
        super().__init__(tuner)
        if configs is None:
            if not (configs_csv and os.path.exists(configs_csv)):
                raise ValueError(
                    "CustomizeSearch needs configs or an existing "
                    "configs_csv")
            with open(configs_csv, newline="") as f:
                rows = list(csv.reader(f))
            if not rows:
                raise ValueError(
                    f"CustomizeSearch: configs_csv {configs_csv!r} is "
                    "empty (need a header row of axis names)")
            head = rows[0]
            configs = [{k: int(v) for k, v in zip(head, row) if v}
                       for row in rows[1:]]
        self.configs = configs

    def _build_tasks(self) -> List[Dict]:
        return [dict(c) for c in self.configs]
