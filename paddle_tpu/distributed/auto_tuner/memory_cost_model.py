"""Per-chip memory model (reference: python/paddle/distributed/auto_tuner/
memory_cost_model.py get_model_memory_usage) for pruning parallel configs.

Accounts: params (model dtype) + fp32 master/m/v (ZeRO-sharded over the
sharding degree), fp32 grads (transient), activations under remat
(per-layer boundary activations / pp / cp), logits chunk.
"""
from __future__ import annotations

from typing import Dict


def estimate_memory_gb(model: Dict, cfg: Dict, *, bytes_per_param: int = 2,
                       seq_chunk: int = 512) -> float:
    """model: {num_params, num_layers, hidden, vocab, seq_len,
    micro_batch}; cfg: {dp, tp, pp, sharding, cp(optional)}."""
    n = model["num_params"]
    tp, pp = cfg.get("tp", 1), cfg.get("pp", 1)
    sh = max(cfg.get("sharding", 1), 1)
    cp = cfg.get("cp", 1)
    mb = model.get("micro_batch", 1)
    S = model["seq_len"]
    H = model["hidden"]
    L = model["num_layers"]
    V = model["vocab"]

    n_local = n / (tp * pp)                      # tensor+pipeline split
    params = n_local * bytes_per_param
    # fp32 master + adam m/v, ZeRO over the sharding axis
    opt = n_local * 12 / sh
    grads = n_local * 4                          # transient fp32
    # remat: keep per-layer boundary activations (L/pp of them)
    act = (L / pp) * mb * (S / cp) * H * bytes_per_param
    # working set of one layer recompute + chunked logits
    work = mb * (S / cp) * max(4 * H, seq_chunk * 0) * 4
    logits = mb * seq_chunk * (V / tp) * 4
    return (params + opt + grads + act + work + logits) / 1e9
