"""Run-history recorder with CSV persistence + breakpoint resume.

Reference: python/paddle/distributed/auto_tuner/recorder.py
(HistoryRecorder: add_cfg / sort_metric / get_best / store_history) and
tuner.py:76 resume_form_history. Stdlib csv only (the reference pulls in
pandas; nothing here needs it).
"""
from __future__ import annotations

import csv
import os
from typing import Dict, List, Optional, Tuple

_AXES = ("dp", "tp", "pp", "cp", "sharding")


def normalize_cfg(cfg: Dict) -> Dict:
    """Canonical config identity: every mesh axis explicit (default 1),
    extra keys (e.g. global_batch) preserved. All history comparisons go
    through this so sparse user configs ({"dp": 4, "tp": 8}) and their
    CSV-round-tripped form compare equal."""
    out = {a: int(cfg.get(a, 1)) for a in _AXES}
    for k, v in cfg.items():
        if k not in _AXES:
            out[k] = v
    return out


class HistoryRecorder:
    def __init__(self, metric_name: str = "tokens_per_sec",
                 direction: str = "Maximize"):
        self.metric_name = metric_name
        self.direction = direction
        self.history: List[Dict] = []

    def add_record(self, cfg: Dict, metric: Optional[float] = None, *,
                   error: Optional[str] = None,
                   memory_gb: Optional[float] = None) -> None:
        self.history.append({"cfg": normalize_cfg(cfg), "metric": metric,
                             "error": error, "memory_gb": memory_gb})

    def sorted_history(self) -> List[Dict]:
        worst = float("-inf") if self.direction == "Maximize" \
            else float("inf")
        return sorted(
            self.history,
            key=lambda r: r["metric"] if r["metric"] is not None else worst,
            reverse=self.direction == "Maximize")

    def get_best(self) -> Tuple[Optional[Dict], bool]:
        """(best record, found) over non-errored runs (recorder.py:58)."""
        ok = [r for r in self.history
              if r["error"] is None and r["metric"] is not None]
        if not ok:
            return None, False
        pick = max if self.direction == "Maximize" else min
        return pick(ok, key=lambda r: r["metric"]), True

    def _extra_cfg_keys(self) -> List[str]:
        """Non-axis cfg keys present anywhere in history (e.g. GBSSearch's
        global_batch) — they are part of the config identity and must
        survive the CSV round trip."""
        keys = []
        for r in self.history:
            for k in r["cfg"]:
                if k not in _AXES and k not in keys:
                    keys.append(k)
        return keys

    # ---- persistence ----------------------------------------------------
    def save_csv(self, path: str) -> None:
        extras = self._extra_cfg_keys()
        with open(path, "w", newline="") as f:
            w = csv.writer(f)
            w.writerow(list(_AXES) + extras
                       + [self.metric_name, "error", "memory_gb"])
            for r in self.history:
                w.writerow([r["cfg"].get(a, 1) for a in _AXES]
                           + [r["cfg"].get(k, "") for k in extras]
                           + [r["metric"] if r["metric"] is not None else "",
                              r["error"] or "",
                              r["memory_gb"]
                              if r["memory_gb"] is not None else ""])

    def load_csv(self, path: str) -> int:
        """Merge records from a history CSV; returns how many were loaded.
        Missing file is a no-op (reference tuner.py:78: resume does not
        start when the csv does not exist). Rows whose cfg is already in
        history are skipped, so repeated resumes don't duplicate records."""
        if not os.path.exists(path):
            return 0
        with open(path, newline="") as f:
            rows = list(csv.reader(f))
        if not rows:
            return 0
        head = rows[0]
        # layout (save_csv): cfg columns, then [<metric>, error, memory_gb]
        # — the metric column is identified positionally, so a recorder
        # configured with a different metric_name still parses the file
        if len(head) < 3 or head[-2:] != ["error", "memory_gb"]:
            raise ValueError(f"unrecognized history CSV header: {head}")
        cfg_cols, metric_col = head[:-3], head[-3]
        n = 0
        for row in rows[1:]:
            d = dict(zip(head, row))
            cfg = {k: int(d[k]) for k in cfg_cols if d.get(k, "") != ""}
            cfg = normalize_cfg(cfg)
            if any(r["cfg"] == cfg for r in self.history):
                continue
            metric = float(d[metric_col]) if d.get(metric_col) else None
            mem = float(d["memory_gb"]) if d.get("memory_gb") else None
            self.add_record(cfg, metric, error=d.get("error") or None,
                            memory_gb=mem)
            n += 1
        return n

    def find(self, cfg: Dict) -> Optional[Dict]:
        """Record whose full normalized identity matches cfg, or None."""
        cfg = normalize_cfg(cfg)
        for r in self.history:
            if r["cfg"] == cfg:
                return r
        return None
