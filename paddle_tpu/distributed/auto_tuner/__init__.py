from .tuner import AutoTuner  # noqa: F401
from .cost_model import estimate_step_time  # noqa: F401
from .memory_cost_model import estimate_memory_gb  # noqa: F401
