from .tuner import AutoTuner  # noqa: F401
from .cost_model import estimate_step_time  # noqa: F401
from .memory_cost_model import estimate_memory_gb  # noqa: F401
from .recorder import HistoryRecorder  # noqa: F401
from .search import (  # noqa: F401
    CustomizeSearch, GBSSearch, GridSearch, SearchAlgo)
from .prune import (  # noqa: F401
    register_prune, register_prune_history)
