"""AutoTuner (reference: python/paddle/distributed/auto_tuner/tuner.py:21
AutoTuner — pluggable search algorithms over dp/mp/pp/sharding/cp
candidates, a prune-rule registry consulted both at generation time and
against run history, a CSV-persisted recorder with breakpoint resume, and
a measurement loop that actually executes candidates).

The reference tuner launches each candidate as a fresh distributed job and
greps its logs for the metric; the TPU-native loop instead builds the
candidate's `jax.sharding.Mesh` in-process and times a jitted hybrid train
step on it (`tune()`), which is both faster and exact — the same XLA
program the real run would compile.

Usage (protocol identical to the reference search_once/add_cfg loop):
    tuner = AutoTuner(model_desc, world_size=64, hbm_gb=16)
    cfg = tuner.search_once()          # best unexplored candidate
    tuner.update(cfg, observed_tps)    # feed measurement back
    tuner.best()

or end-to-end:
    best = tuner.tune(run_fn)          # run_fn(cfg) -> tokens/sec
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

from .cost_model import estimate_step_time
from .memory_cost_model import estimate_memory_gb
from .prune import prune_static
from .recorder import HistoryRecorder
from .search import CustomizeSearch, GBSSearch, GridSearch


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


class AutoTuner:
    def __init__(self, model: Dict, world_size: int, hbm_gb: float = 16.0,
                 chip: str = "v5e", tuner_cfg: Optional[Dict] = None):
        self.model = model
        self.world_size = world_size
        self.hbm_gb = hbm_gb
        self.chip = chip
        self.tuner_cfg = tuner_cfg or {}
        # None = exhaustive (the reference defaults task_limit to 100, but
        # silently truncating the grid loses the optimum; cap only on ask)
        self.task_limit = self.tuner_cfg.get("task_limit")
        self._tasks_issued = 0
        self.recorder = HistoryRecorder(
            metric_name=self.tuner_cfg.get("metric", "tokens_per_sec"),
            direction=self.tuner_cfg.get("direction", "Maximize"))

        algo = self.tuner_cfg.get("search_algo", "grid")
        if algo == "grid":
            self.algo = GridSearch(self)
        elif algo == "gbs":
            self.algo = GBSSearch(
                self, self.tuner_cfg.get("gbs_candidates"))
        elif algo == "customize":
            self.algo = CustomizeSearch(
                self, configs=self.tuner_cfg.get("configs"),
                configs_csv=self.tuner_cfg.get("configs_csv"))
        else:
            raise NotImplementedError(f"search_algo={algo!r}")

    # ---- candidate generation (reference utils.py search_all) ----------
    def generate_candidates(self, model: Optional[Dict] = None) \
            -> List[Dict]:
        """Every (dp, tp, pp, cp, sharding) factorization of world_size
        surviving the static prune rules. model (default self.model) is
        passed through to the rules explicitly — GBS search evaluates
        grids for scaled global batches without touching shared state."""
        model = model if model is not None else self.model
        W = self.world_size
        cands = []
        for tp in _divisors(W):
            for pp in _divisors(W // tp):
                rest = W // tp // pp
                for cp in self.tuner_cfg.get("cp_degree", [1]):
                    if rest % cp:
                        continue
                    dp = rest // cp
                    # interleaved-VPP chunk degrees (reference:
                    # auto_tuner/utils.py "vpp_degree"). The plain
                    # (vpp-absent) config is ALWAYS emitted — vpp>1 is
                    # impossible at pp=1, and a vpp_degree list without
                    # 1 must not delete the non-pipelined baselines —
                    # then each vpp>1 variant joins the grid; validity
                    # (pipeline present, layers split into pp*vpp) is
                    # prune.py's divisibility rule, the shared home of
                    # static config validity
                    vpps = [v for v in
                            self.tuner_cfg.get("vpp_degree", [1])
                            if v > 1]
                    for sh in _divisors(dp):
                        base = {"dp": dp, "tp": tp, "pp": pp,
                                "cp": cp, "sharding": sh}
                        for cfg in ([base] +
                                    [{**base, "vpp": v} for v in vpps]):
                            if not prune_static(self, cfg, model):
                                cands.append(cfg)
        return cands

    @property
    def candidates(self) -> List[Dict]:
        return self.algo.all_tasks()

    # ---- search protocol (reference tuner.py:62 search_once) -----------
    def search_once(self) -> Optional[Dict]:
        if self.task_limit is not None \
                and self._tasks_issued >= self.task_limit:
            return None
        cfg = self.algo.search_once(self.recorder.history)
        if cfg is not None:
            self._tasks_issued += 1
        return cfg

    def update(self, cfg: Dict, metric: Optional[float] = None, *,
               error: Optional[str] = None):
        """Record a run. metric: higher is better (e.g. tokens/sec);
        error: "oom" engages the OOM-history prune rule, any other string
        marks a failed run."""
        self.recorder.add_record(
            cfg, metric, error=error,
            memory_gb=estimate_memory_gb(self.model, cfg))

    def best(self) -> Optional[Dict]:
        rec, ok = self.recorder.get_best()
        return dict(rec["cfg"]) if ok else None

    @property
    def history(self) -> List[Dict]:
        # defensive copy (like .candidates): caller mutation must not
        # corrupt dedup/best/resume state inside the recorder
        return list(self.recorder.history)

    # ---- persistence / resume (reference tuner.py:76) ------------------
    def save_history(self, csv_path: str) -> None:
        self.recorder.save_csv(csv_path)

    def resume_from_history(self, csv_path: str) -> int:
        """Load prior runs; already-run configs are then skipped by the
        duplicate-history prune rule, and resumed runs count toward
        task_limit (a crash/resume cycle must not double the budget)."""
        n = self.recorder.load_csv(csv_path)
        self._tasks_issued += n
        return n

    # ---- end-to-end measurement loop -----------------------------------
    def tune(self, run_fn: Callable[[Dict], float], *,
             max_trials: Optional[int] = None,
             history_csv: Optional[str] = None) -> Optional[Dict]:
        """search → run → record until exhausted (reference launch-side
        loop: launch/main.py auto-tuner branch). run_fn returns the metric;
        raising MemoryError (or any exception whose text smells of OOM)
        records an "oom" run, other exceptions record a failed run.
        """
        trials = 0
        if history_csv:
            self.resume_from_history(history_csv)
        while max_trials is None or trials < max_trials:
            cfg = self.search_once()
            if cfg is None:
                break
            trials += 1
            try:
                metric = run_fn(cfg)
            except Exception as e:  # noqa: BLE001 — classify and record
                s = f"{type(e).__name__}: {e}"
                oom = isinstance(e, MemoryError) or \
                    "RESOURCE_EXHAUSTED" in s or "ut of memory" in s
                self.update(cfg, error="oom" if oom else s[:200])
            else:
                self.update(cfg, metric)
            if history_csv:
                self.save_history(history_csv)
        return self.best()

    # kept for backward compatibility with earlier rounds' callers
    @staticmethod
    def _key(cfg: Dict) -> tuple:
        return tuple(sorted(cfg.items()))

    def estimate(self, cfg: Dict) -> float:
        return estimate_step_time(self.model, cfg, chip=self.chip)
