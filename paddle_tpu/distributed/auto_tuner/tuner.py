"""AutoTuner (reference: python/paddle/distributed/auto_tuner/tuner.py:21
AutoTuner — grid/prune search over dp/mp/pp/sharding candidates, ranked by
cost; utils.py candidate generation + pruning).

Usage:
    tuner = AutoTuner(model_desc, world_size=64, hbm_gb=16)
    cfg = tuner.search_once()          # best unexplored candidate
    tuner.update(cfg, observed_tps)    # feed measurement back
"""
from __future__ import annotations

import itertools
from typing import Dict, List, Optional

from .cost_model import estimate_step_time
from .memory_cost_model import estimate_memory_gb


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


class AutoTuner:
    def __init__(self, model: Dict, world_size: int, hbm_gb: float = 16.0,
                 chip: str = "v5e", tuner_cfg: Optional[Dict] = None):
        self.model = model
        self.world_size = world_size
        self.hbm_gb = hbm_gb
        self.chip = chip
        self.tuner_cfg = tuner_cfg or {}
        self.history: Dict[tuple, float] = {}
        self._candidates = self._generate()
        self._cursor = 0

    # ---- candidate generation + pruning (reference: utils.py
    # generate_combinations + prune functions) ----
    def _generate(self) -> List[Dict]:
        W = self.world_size
        cands = []
        allowed = self.tuner_cfg
        for tp in allowed.get("mp_degree", _divisors(W)):
            if W % tp:
                continue
            for pp in allowed.get("pp_degree", _divisors(W // tp)):
                if (W // tp) % pp:
                    continue
                rest = W // tp // pp
                for cp in allowed.get("cp_degree", [1]):
                    if rest % cp:
                        continue
                    dp = rest // cp
                    for sh in allowed.get("sharding_degree",
                                          _divisors(dp)):
                        if dp % sh:
                            continue
                        cfg = {"dp": dp, "tp": tp, "pp": pp, "cp": cp,
                               "sharding": sh}
                        if self._prune(cfg):
                            continue
                        cands.append(cfg)
        cands.sort(key=lambda c: estimate_step_time(
            self.model, c, chip=self.chip))
        return cands

    def _prune(self, cfg) -> bool:
        # memory prune
        if estimate_memory_gb(self.model, cfg) > self.hbm_gb:
            return True
        # tp must divide heads; pp must divide layers
        heads = self.model.get("num_heads")
        if heads and heads % cfg["tp"]:
            return True
        L = self.model.get("num_layers")
        if L and L % cfg["pp"]:
            return True
        # batch must divide over dp
        B = self.model.get("global_batch")
        if B and B % max(cfg["dp"], 1):
            return True
        return False

    # ---- search protocol (reference: tuner.py search_once) ----
    @property
    def candidates(self) -> List[Dict]:
        return list(self._candidates)

    def search_once(self) -> Optional[Dict]:
        while self._cursor < len(self._candidates):
            cfg = self._candidates[self._cursor]
            self._cursor += 1
            if self._key(cfg) not in self.history:
                return cfg
        return None

    def update(self, cfg: Dict, metric: float):
        """metric: higher is better (e.g. tokens/sec)."""
        self.history[self._key(cfg)] = metric

    def best(self) -> Optional[Dict]:
        if not self.history:
            return None
        key = max(self.history, key=self.history.get)
        return dict(key)

    @staticmethod
    def _key(cfg: Dict) -> tuple:
        return tuple(sorted(cfg.items()))
