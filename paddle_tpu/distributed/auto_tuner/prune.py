"""Prune-rule registry for the auto tuner.

Reference: python/paddle/distributed/auto_tuner/prune.py — two registries
(`_PRUNE_FUNC` static rules at candidate-generation time,
`_PRUNE_HISTORY_FUNC` rules consulted at search time against the run
history). The TPU build keeps the same two-phase contract but the rules
themselves reason over mesh axes (tp/pp/dp/cp/sharding on an ICI mesh) and
the analytic HBM model instead of per-GPU allocator telemetry.

A rule returns True to prune. Static rules see (tuner, cfg, model) —
model is the dict under evaluation, which GBS search varies per candidate
grid; history rules see (tuner, cfg, history) where history is a list of
record dicts ({"cfg", "metric", "error", "memory_gb"}).
"""
from __future__ import annotations

from typing import Callable, Dict, List

from .memory_cost_model import estimate_memory_gb
from .recorder import normalize_cfg

_PRUNE_FUNC: List[Callable] = []
_PRUNE_HISTORY_FUNC: List[Callable] = []


def register_prune(fn):
    """Register a static prune rule (reference prune.py:128 pattern)."""
    _PRUNE_FUNC.append(fn)
    return fn


def register_prune_history(fn):
    """Register a history-aware prune rule (reference prune.py:206)."""
    _PRUNE_HISTORY_FUNC.append(fn)
    return fn


def prune_static(tuner, cfg: Dict, model: Dict = None) -> bool:
    """model overrides tuner.model for rules that read model dims (GBS
    search evaluates candidate grids for scaled global batches without
    mutating shared tuner state)."""
    model = model if model is not None else tuner.model
    return any(fn(tuner, cfg, model) for fn in _PRUNE_FUNC)


def prune_with_history(tuner, cfg: Dict, history: List[Dict]) -> bool:
    return any(fn(tuner, cfg, history) for fn in _PRUNE_HISTORY_FUNC)


def _same_shape(a: Dict, b: Dict, *keys) -> bool:
    return all(a.get(k, 1) == b.get(k, 1) for k in keys)


# ---- static rules -------------------------------------------------------

@register_prune
def prune_by_divisibility(tuner, cfg, model):
    """tp | heads, pp | layers, dp | global batch, product == world.

    Reference prune_by_mp/prune_by_pp (prune.py:129,173): degree must
    divide the model dimension it splits.
    """
    m = model
    heads = m.get("num_heads")
    if heads and heads % cfg.get("tp", 1):
        return True
    layers = m.get("num_layers")
    if layers and layers % cfg.get("pp", 1):
        return True
    # interleaved VPP: vpp>1 needs a pipeline and pp*vpp virtual stages
    # must split the layer stack (reference vpp_degree dim)
    vpp = cfg.get("vpp", 1)
    if vpp > 1 and (cfg.get("pp", 1) < 2 or
                    (layers and layers % (cfg.get("pp", 1) * vpp))):
        return True
    B = m.get("global_batch")
    if B and B % max(cfg.get("dp", 1), 1):
        return True
    world = 1
    for k in ("dp", "tp", "pp", "cp"):
        world *= cfg.get(k, 1)
    return world != tuner.world_size


@register_prune
def prune_by_memory_estimation(tuner, cfg, model):
    """Analytic per-chip HBM estimate over budget (prune.py:605)."""
    return estimate_memory_gb(model, cfg) > tuner.hbm_gb


@register_prune
def prune_by_sharding(tuner, cfg, model):
    """sharding degree must divide the dp degree it lives on
    (prune.py:395 — sharding_degree > degree of its axis is invalid)."""
    sh = cfg.get("sharding", 1)
    dp = max(cfg.get("dp", 1), 1)
    return sh > 1 and dp % sh != 0


@register_prune
def prune_by_allowed_candidates(tuner, cfg, model):
    """User-restricted candidate lists (reference tuner_cfg candidates)."""
    allowed = tuner.tuner_cfg
    for key, axis in (("mp_degree", "tp"), ("pp_degree", "pp"),
                      ("dp_degree", "dp"), ("cp_degree", "cp"),
                      ("sharding_degree", "sharding")):
        lst = allowed.get(key)
        if lst is not None and cfg.get(axis, 1) not in lst:
            return True
    return False


# ---- history rules ------------------------------------------------------
# History records store normalized cfgs (recorder.add_record); incoming
# candidates are normalized here so sparse user configs compare equal to
# their round-tripped form.

@register_prune_history
def prune_duplicate(tuner, cfg, history):
    cfg = normalize_cfg(cfg)
    return any(r["cfg"] == cfg for r in history)


@register_prune_history
def prune_by_oom_history(tuner, cfg, history):
    """Skip configs at least as memory-hungry as one that already OOM'd
    with the same model split AND batch recipe (reference
    prune_by_mbs_history / prune_by_sharding_history prune.py:361,447:
    once a shape dies of OOM, every strictly-heavier sibling dies too).
    global_batch is part of the dominance key — a smaller-batch sibling
    of an OOM'd shape may well fit."""
    cfg = normalize_cfg(cfg)
    mem = estimate_memory_gb(tuner.model, cfg)
    for r in history:
        if r.get("error") != "oom":
            continue
        oom_mem = r.get("memory_gb")
        if oom_mem is None:
            continue  # no estimate recorded — can't establish dominance
        if _same_shape(cfg, r["cfg"], "tp", "pp", "cp", "global_batch") \
                and mem >= oom_mem - 1e-9:
            return True
    return False


@register_prune_history
def prune_by_error_history(tuner, cfg, history):
    """A config that failed for a non-OOM reason is not retried
    (reference search loop records error runs with time=-1)."""
    cfg = normalize_cfg(cfg)
    return any(r["cfg"] == cfg and r.get("error") not in (None, "oom")
               for r in history)
