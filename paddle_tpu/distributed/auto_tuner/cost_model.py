"""Analytic step-time model (reference: python/paddle/distributed/
auto_tuner/cost_model.py) specialized to TPU interconnect characteristics:
tp/sp collectives ride ICI within a slice, dp/sharding gradient
reduce-scatter overlaps the backward, pp adds the GPipe bubble.
"""
from __future__ import annotations

from typing import Dict

# rough per-chip characteristics; tuned for ordering, not absolutes
PEAK_FLOPS = {"v4": 275e12, "v5e": 197e12, "v5p": 459e12, "v6e": 918e12}
ICI_BW = {"v4": 3 * 2 * 100e9, "v5e": 2 * 2 * 50e9, "v5p": 3 * 2 * 100e9,
          "v6e": 2 * 2 * 90e9}  # bytes/s bidirectional per chip


def estimate_step_time(model: Dict, cfg: Dict, *, chip: str = "v5e",
                       mfu: float = 0.4,
                       num_microbatches: int = 8) -> float:
    """Seconds per optimizer step for one data-parallel replica group.

    model: {num_params, num_layers, hidden, seq_len, micro_batch,
    global_batch}; cfg: {dp, tp, pp, sharding, cp}.
    """
    n = model["num_params"]
    tp, pp, dp = cfg.get("tp", 1), cfg.get("pp", 1), cfg.get("dp", 1)
    cp = cfg.get("cp", 1)
    S = model["seq_len"]
    B = model["global_batch"]
    peak = PEAK_FLOPS.get(chip, 275e12)
    bw = ICI_BW.get(chip, 2e11)

    tokens = B * S
    flops = 6.0 * n * tokens          # fwd+bwd matmul flops
    world = tp * pp * dp * cp
    compute = flops / (world * peak * mfu)

    # tp collectives: 2 allreduce-equivalents per layer fwd+bwd over
    # activations of size mb*S*H — ring cost (tp-1)/tp * bytes / bw
    L, H = model["num_layers"], model["hidden"]
    mb_tokens = (B / dp / max(num_microbatches, 1)) * (S / cp)
    if tp > 1:
        per_layer = 4 * 2 * mb_tokens * H * 2  # fwd+bwd, 2 each, bf16
        comm_tp = L * per_layer * (tp - 1) / tp / bw * num_microbatches
    else:
        comm_tp = 0.0
    # dp/sharding grad sync: reduce-scatter+allgather of n/tp/pp bytes
    comm_dp = 0.0
    if dp > 1:
        comm_dp = 2 * (n / (tp * pp)) * 4 * (dp - 1) / dp / bw
    # pp bubble: (pp-1)/(M*vpp+pp-1) of compute — interleaved (VPP)
    # virtual stages lap the ring vpp times, shrinking the bubble
    # (reference: auto_tuner/utils.py vpp_degree search dim;
    # pp_spmd.pipeline_interleave_1f1b)
    vpp = cfg.get("vpp", 1)
    bubble = compute * (pp - 1) / \
        (num_microbatches * max(vpp, 1) + pp - 1) if pp > 1 else 0.0
    # cp ring attention adds kv rotation traffic, minor: fold into tp term
    return compute + bubble + max(comm_tp, comm_dp * 0.3)
