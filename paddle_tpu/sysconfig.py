"""reference: python/paddle/sysconfig.py — get_include/get_lib."""
import os


def get_include() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "_native")


def get_lib() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "_native")
