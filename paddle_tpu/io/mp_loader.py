"""Multiprocess DataLoader workers with shared-memory ndarray transport.

TPU-native re-design of the reference's multiprocess loader tier
(reference: python/paddle/io/dataloader/worker.py:281 ``_worker_loop``,
dataloader_iter.py:459 ``multiprocessing.Process`` spawn + index queues,
worker.py:184 ``_WorkerException``). Python-transform-heavy datasets are
GIL-bound under the thread tier (io/dataloader.py); real processes give
true parallelism for decode/augment pipelines.

Differences from the reference, driven by the TPU runtime:

- **spawn, not fork.** The parent holds a live XLA client (and possibly
  the TPU tunnel); forking a process with XLA/grpc threads deadlocks.
  Workers are spawned fresh and FORCE ``JAX_PLATFORMS=cpu`` before any
  unpickling, so a worker can never claim the single TPU chip out from
  under the trainer.
- **Shared-memory ndarray transport.** Batch arrays travel as
  ``multiprocessing.shared_memory`` segments (name/shape/dtype skeleton
  through the result queue) instead of being pickled through a pipe —
  one memcpy worker-side, one parent-side copy into the device transfer.
  Small leaves (< _SHM_MIN bytes) pickle directly; the segment overhead
  would dominate.
- **Ordered reorder buffer** in the parent restores sampler order, and a
  worker exception is delivered at exactly the batch position it
  happened (the reference's _task_infos/_WorkerException semantics).

The thread tier remains the fallback: unpicklable datasets/collate_fns,
IterableDataset (inherently sequential), or spawn failure fall back with
a one-time warning.
"""
from __future__ import annotations

import os
import pickle
import queue as pyqueue
import threading
import time
import traceback
from typing import Any, List, Optional

import numpy as np

_SHM_MIN = 1 << 16          # below this, pickling through the queue wins
_SPAWN_CTX = None


def _ctx():
    global _SPAWN_CTX
    if _SPAWN_CTX is None:
        import multiprocessing as mp
        _SPAWN_CTX = mp.get_context("spawn")
    return _SPAWN_CTX


class _ShmArray:
    """Skeleton of an ndarray riding a SharedMemory segment."""

    __slots__ = ("name", "shape", "dtype", "was_tensor")

    def __init__(self, name, shape, dtype, was_tensor):
        self.name = name
        self.shape = shape
        self.dtype = dtype
        self.was_tensor = was_tensor


class _NpTensor:
    """A Tensor leaf converted to numpy for transport (small ones)."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr


class _WorkerError:
    """reference: io/dataloader/worker.py:184 _WorkerException — the
    original traceback travels as text; the parent re-raises the same
    exception type with it appended. Only the type NAME is stored (a
    locally-defined exception class would make this object — and with
    it the whole result — unpicklable and silently dropped by the
    queue's feeder thread); builtin exception types are resolved back
    on reraise, others degrade to RuntimeError with the traceback."""

    def __init__(self, exc, tb=None):
        self.exc_type_name = type(exc).__name__
        self.msg = str(exc)
        self.tb = traceback.format_exc() if tb is None else tb

    def reraise(self):
        import builtins
        cls = getattr(builtins, self.exc_type_name, None)
        if not (isinstance(cls, type) and issubclass(cls, BaseException)):
            cls = RuntimeError
        try:
            e = cls(f"{self.msg}\n\n[DataLoader worker traceback]\n"
                    f"{self.tb}")
        except Exception:
            e = RuntimeError(
                f"{self.exc_type_name}: {self.msg}\n{self.tb}")
        raise e


def _encode(obj, created):
    """Replace big ndarray/Tensor leaves with shm skeletons (segments
    appended to ``created``); Tensor leaves become numpy with a marker
    so the parent restores the type."""
    # local import: the worker has forced the cpu platform by now
    from .._core.tensor import Tensor
    was_tensor = isinstance(obj, Tensor)
    if was_tensor:
        obj = np.asarray(obj.numpy())
    if isinstance(obj, np.ndarray):
        if obj.nbytes >= _SHM_MIN:
            from multiprocessing import shared_memory
            obj = np.ascontiguousarray(obj)
            shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
            np.ndarray(obj.shape, obj.dtype, buffer=shm.buf)[...] = obj
            created.append(shm)
            return _ShmArray(shm.name, obj.shape, str(obj.dtype),
                             was_tensor)
        return _NpTensor(obj) if was_tensor else obj
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        return type(obj)(*(_encode(x, created) for x in obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_encode(x, created) for x in obj)
    if isinstance(obj, dict):
        return {k: _encode(v, created) for k, v in obj.items()}
    return obj


def _decode(obj):
    """Parent-side: materialize shm skeletons (copy out + unlink) and
    restore Tensor leaves."""
    from multiprocessing import shared_memory
    from .._core.tensor import Tensor
    if isinstance(obj, _ShmArray):
        shm = shared_memory.SharedMemory(name=obj.name)
        try:
            arr = np.array(np.ndarray(obj.shape, np.dtype(obj.dtype),
                                      buffer=shm.buf))
        finally:
            shm.close()
            shm.unlink()
        return Tensor(arr) if obj.was_tensor else arr
    if isinstance(obj, _NpTensor):
        return Tensor(obj.arr)
    if isinstance(obj, tuple) and hasattr(obj, "_fields"):
        return type(obj)(*(_decode(x) for x in obj))
    if isinstance(obj, (list, tuple)):
        return type(obj)(_decode(x) for x in obj)
    if isinstance(obj, dict):
        return {k: _decode(v) for k, v in obj.items()}
    return obj


def _release(obj):
    """Unlink shm segments of an undelivered payload (early close)."""
    from multiprocessing import shared_memory
    if isinstance(obj, _ShmArray):
        try:
            shm = shared_memory.SharedMemory(name=obj.name)
            shm.close()
            shm.unlink()
        except Exception:
            pass
    elif isinstance(obj, (list, tuple)):
        for x in obj:
            _release(x)
    elif isinstance(obj, dict):
        for x in obj.values():
            _release(x)


def _np_collate(batch):
    """Pure-numpy default collate for the worker side (no jax, no device
    — the parent wraps the stacked arrays into Tensors). Mirrors
    default_collate_fn's structure handling."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, float, np.number)):
        return np.asarray(batch)
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: _np_collate([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [_np_collate(list(items)) for items in zip(*batch)]
    # Tensor or unknown: defer to the full collate (cpu jax in worker)
    from .dataloader import default_collate_fn
    return default_collate_fn(batch)


def _worker_main(wid, num_workers, ds_bytes, collate_bytes, init_bytes,
                 seed, task_q, result_q):
    """Worker process entry (reference: worker.py:281 _worker_loop).
    The FIRST action pins jax to cpu — before unpickling the dataset,
    whose module imports may pull in paddle_tpu/jax."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PADDLE_TPU_DEVICE", None)
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        dataset = pickle.loads(ds_bytes)
        collate_fn = pickle.loads(collate_bytes) if collate_bytes else None
        init_fn = pickle.loads(init_bytes) if init_bytes else None
        import random as pyrandom
        np.random.seed((seed + wid) % (2 ** 32))
        pyrandom.seed(seed + wid)
        from . import dataloader as dl
        dl._worker_info_tls.info = dl.WorkerInfo(
            id=wid, num_workers=num_workers, dataset=dataset)
        if init_fn is not None:
            init_fn(wid)
    except Exception as e:  # startup failure: surface on the first batch
        result_q.put(pickle.dumps((-1, _WorkerError(e))))
        return
    while True:
        task = task_q.get()
        if task is None:
            return
        seq, indices = task
        created: List[Any] = []
        try:
            samples = [dataset[i] for i in indices]
            batch = (collate_fn(samples) if collate_fn is not None
                     else _np_collate(samples))
            payload = _encode(batch, created)
            # pickle HERE: mp.Queue serializes in a background feeder
            # thread that silently DROPS unpicklable items (the parent
            # would wait on this seq forever). Self-pickling turns that
            # into a deliverable error; re-pickling the bytes in the
            # feeder is a cheap memcpy.
            blob = pickle.dumps((seq, payload))
        except Exception as e:  # noqa: BLE001
            for shm in created:
                try:
                    shm.close()
                    shm.unlink()
                except Exception:
                    pass
            blob = pickle.dumps((seq, _WorkerError(e)))
            result_q.put(blob)
            continue
        result_q.put(blob)
        for shm in created:
            shm.close()
            # the parent owns the segment now; drop this process's
            # resource-tracker claim so its exit doesn't unlink/warn
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:
                pass


class _MPPool:
    """The spawned worker pool: processes + queues + a monotonically
    increasing task-sequence counter. With ``persistent_workers=True``
    (reference: reader.py DataLoader arg) one pool serves every epoch —
    the spawn+import cost (seconds) is paid once, not per epoch. Seqs
    never reset, so results of an abandoned epoch are recognized (and
    their shm released) by the next epoch's ``seq < base`` filter."""

    def __init__(self, loader, num_workers):
        ctx = _ctx()
        self.procs: list = []
        self.closed = False
        # pickled HERE (not via Process args) so failures raise in the
        # parent synchronously -> thread-tier fallback
        ds_bytes = pickle.dumps(loader.dataset)
        collate_bytes = (pickle.dumps(loader.collate_fn)
                         if loader.collate_fn is not None else b"")
        init_fn = getattr(loader, "worker_init_fn", None)
        init_bytes = pickle.dumps(init_fn) if init_fn is not None else b""
        self.num_workers = max(1, num_workers)
        self.task_q = ctx.Queue()
        self.result_q = ctx.Queue()
        self.next_seq = 0
        seed = int(np.random.randint(0, 2 ** 31 - 1))
        for wid in range(self.num_workers):
            p = ctx.Process(
                target=_worker_main,
                args=(wid, self.num_workers, ds_bytes, collate_bytes,
                      init_bytes, seed, self.task_q, self.result_q),
                daemon=True)
            p.start()
            self.procs.append(p)

    def _drain_release(self):
        try:
            while True:
                _, payload = pickle.loads(self.result_q.get_nowait())
                if not isinstance(payload, _WorkerError):
                    _release(payload)
        except pyqueue.Empty:
            pass
        except Exception:
            pass

    def close(self):
        if self.closed:
            return
        self.closed = True
        # sentinels FIRST, then join, then release: a worker mid-batch
        # finishes, puts its payload, and only then takes the sentinel —
        # draining before the join would miss (and leak) that segment
        for _ in self.procs:
            try:
                self.task_q.put_nowait(None)
            except Exception:
                pass
        for p in self.procs:
            p.join(timeout=2.0)
        self._drain_release()
        for p in self.procs:
            if p.is_alive():
                p.terminate()
                p.join(timeout=0.5)
        self._drain_release()
        for q in (self.task_q, self.result_q):
            try:
                q.cancel_join_thread()
                q.close()
            except Exception:
                pass

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class MPLoaderIter:
    """Process-pool prefetching iterator for map-style datasets.

    Parent keeps ``cap = num_workers * prefetch_factor`` tasks in
    flight through the pool's task queue; results return out of order
    and a reorder buffer restores sampler order. Construction raises
    (pickle/spawn errors) so DataLoader can fall back to the thread
    tier."""

    def __init__(self, loader, num_workers, prefetch_factor, pool=None):
        self._own_pool = pool is None
        self._pool = pool if pool is not None else _MPPool(loader,
                                                           num_workers)
        prev = getattr(self._pool, "live_iter", None)
        prev = prev() if prev is not None else None
        if prev is not None and not prev._closed:
            # one live iterator per pool: two concurrent consumers would
            # steal each other's results off the shared queue
            prev.close()
        import weakref
        self._pool.live_iter = weakref.ref(self)
        self._procs = self._pool.procs            # liveness checks/tests
        self._closed = False
        self.dataset = loader.dataset
        self._wrap_default = loader.collate_fn is None
        self._sampler_it = iter(loader.batch_sampler)
        self._cap = max(2, self._pool.num_workers * prefetch_factor)
        self._base = self._pool.next_seq          # this epoch's first seq
        self._next_task = self._base
        self._next_out = self._base
        self._buf: dict = {}
        self._errs: dict = {}
        self._exhausted = False
        self._timeout = getattr(loader, "timeout", 0) or 0
        self._fill()

    def _fill(self):
        while not self._exhausted and \
                self._next_task - self._next_out < self._cap:
            try:
                indices = next(self._sampler_it)
            except StopIteration:
                self._exhausted = True
                self._pool.next_seq = self._next_task
                return
            self._pool.task_q.put((self._next_task, list(indices)))
            self._next_task += 1
        self._pool.next_seq = max(self._pool.next_seq, self._next_task)

    def __iter__(self):
        return self

    def __next__(self):
        if self._next_out >= self._next_task and self._exhausted:
            self.close()
            raise StopIteration
        deadline = (time.monotonic() + self._timeout) if self._timeout \
            else None
        while self._next_out not in self._buf and \
                self._next_out not in self._errs:
            try:
                seq, payload = pickle.loads(
                    self._pool.result_q.get(timeout=1.0))
            except pyqueue.Empty:
                dead = [p for p in self._procs if not p.is_alive()]
                if dead and self._next_out < self._next_task:
                    self.close()
                    raise RuntimeError(
                        f"DataLoader worker (pid {dead[0].pid}) exited "
                        f"unexpectedly (exitcode={dead[0].exitcode})")
                if deadline is not None and time.monotonic() > deadline:
                    self.close()
                    raise RuntimeError(
                        f"DataLoader timed out after {self._timeout}s "
                        "waiting for a worker batch")
                continue
            if 0 <= seq < self._base:
                # stragglers of an abandoned earlier epoch (persistent
                # pool): release and drop
                if not isinstance(payload, _WorkerError):
                    _release(payload)
                continue
            if isinstance(payload, _WorkerError):
                # startup failures (seq==-1) surface at the next batch
                self._errs[self._next_out if seq < 0 else seq] = payload
            else:
                self._buf[seq] = payload
        if self._next_out in self._errs:
            err = self._errs.pop(self._next_out)
            self.close()
            err.reraise()
        payload = self._buf.pop(self._next_out)
        self._next_out += 1
        self._fill()
        batch = _decode(payload)
        if self._wrap_default:
            batch = _tensorize(batch)
        return batch

    def close(self):
        if self._closed:
            return
        self._closed = True
        for p in self._buf.values():
            _release(p)
        self._buf.clear()
        # in-flight seqs of this epoch stay owned by the pool; the next
        # epoch's base filter releases any stragglers
        self._pool.next_seq = max(self._pool.next_seq, self._next_task)
        if self._own_pool:
            self._pool.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def _tensorize(batch):
    """Wrap the worker's numpy default-collate output into Tensors
    (structure mirror of default_collate_fn's output types)."""
    from .._core.tensor import Tensor
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, dict):
        return {k: _tensorize(v) for k, v in batch.items()}
    if isinstance(batch, list):
        return [_tensorize(b) for b in batch]
    return batch
