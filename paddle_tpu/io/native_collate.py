"""Native host data path wrappers (reference:
paddle/fluid/framework/data_feed.cc — C++ feed/collate without the GIL).

numpy-facing helpers over _native/datapath.cpp; fall back to numpy when
the native lib is unavailable."""
from __future__ import annotations

import ctypes
import os
from typing import List, Sequence

import numpy as np

from .. import _native


def collate_stack(samples: Sequence[np.ndarray],
                  num_threads: int = 0) -> np.ndarray:
    """np.stack(samples) through the native multi-threaded memcpy path."""
    lib = _native.load()
    arrs = [np.ascontiguousarray(s) for s in samples]
    if lib is None or not arrs:
        return np.stack(arrs)
    first = arrs[0]
    if any(a.shape != first.shape or a.dtype != first.dtype
           for a in arrs[1:]):
        return np.stack(arrs)
    n = len(arrs)
    out = np.empty((n,) + first.shape, first.dtype)
    ptrs = (ctypes.c_void_p * n)(
        *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs])
    nt = num_threads or min(8, os.cpu_count() or 1)
    lib.pt_collate(ptrs, n, first.nbytes, out.ctypes.data_as(
        ctypes.c_void_p), nt)
    return out


def shuffle_indices(n: int, seed: int) -> np.ndarray:
    lib = _native.load()
    if lib is None:
        rng = np.random.default_rng(seed)
        return rng.permutation(n).astype(np.int64)
    out = np.empty(n, np.int64)
    lib.pt_shuffle_indices(n, seed,
                           out.ctypes.data_as(
                               ctypes.POINTER(ctypes.c_int64)))
    return out


def normalize_images(batch_u8_nhwc: np.ndarray, mean: Sequence[float],
                     std: Sequence[float],
                     num_threads: int = 0) -> np.ndarray:
    """uint8 NHWC -> float32 NCHW with (x/255 - mean)/std, native loop."""
    lib = _native.load()
    x = np.ascontiguousarray(batch_u8_nhwc, np.uint8)
    n, h, w, c = x.shape
    m = np.asarray(mean, np.float32)
    s = np.asarray(std, np.float32)
    if lib is None:
        f = x.astype(np.float32) / 255.0
        f = (f - m) / s
        return np.ascontiguousarray(f.transpose(0, 3, 1, 2))
    out = np.empty((n, c, h, w), np.float32)
    nt = num_threads or min(8, os.cpu_count() or 1)
    lib.pt_normalize_nhwc_to_nchw(
        x.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n, h, w, c,
        m.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        s.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), nt)
    return out
