"""DataLoader (reference: python/paddle/io/reader.py:262 DataLoader;
multiprocess workers python/paddle/io/dataloader/worker.py).

TPU-native host data path: multiprocess workers feed a prefetch queue
(double-buffering host→device transfer against compute). The C++ fast
collate path lives in native/ (paddle_tpu.lib.fast_collate) and is used
automatically for numeric batches when built.
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, List, Optional

import numpy as np

from .dataset import (BatchSampler, Dataset, IterableDataset,
                      SequenceSampler, RandomSampler)
from .._core.tensor import Tensor


def default_collate_fn(batch):
    """reference: io/dataloader/collate.py default_collate_fn."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        from .native_collate import collate_stack
        return Tensor(collate_stack(batch))
    if isinstance(sample, Tensor):
        from ..ops.manipulation import stack
        return stack(batch, axis=0)
    if isinstance(sample, (int, float, np.number)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate batch of {type(sample)}")


def default_convert_fn(batch):
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (tuple, list)):
        return [default_convert_fn(b) for b in batch]
    return batch


class _SingleProcessLoaderIter:
    def __init__(self, loader):
        self.loader = loader
        self.dataset = loader.dataset
        self.collate_fn = loader.collate_fn or default_collate_fn
        if loader._is_iterable:
            self._it = iter(self.dataset)
            self._drained = False
        else:
            self._sampler_it = iter(loader.batch_sampler)

    def __iter__(self):
        return self

    def __next__(self):
        if self.loader._is_iterable:
            batch = list(itertools.islice(self._it,
                                          self.loader.batch_size or 1))
            if not batch:
                raise StopIteration
            if self.loader.batch_size is None:
                return default_convert_fn(batch[0])
            if len(batch) < (self.loader.batch_size or 1) and \
                    self.loader.drop_last:
                raise StopIteration
            return self.collate_fn(batch)
        indices = next(self._sampler_it)
        batch = [self.dataset[i] for i in indices]
        return self.collate_fn(batch)


class _PrefetchLoaderIter:
    """Thread-prefetching iterator: overlaps host batch assembly with device
    compute (the reference overlaps via multiprocess workers + pinned
    memory; on TPU a thread pool suffices because collate is numpy-bound
    and jax transfers release the GIL)."""

    def __init__(self, loader, num_workers, prefetch_factor):
        self.inner = _SingleProcessLoaderIter(loader)
        self.q: "queue.Queue" = queue.Queue(maxsize=max(
            2, num_workers * prefetch_factor))
        self._done = object()
        self._err = None

        def worker():
            try:
                for item in self.inner:
                    self.q.put(item)
            except Exception as e:  # propagate to consumer
                self._err = e
            finally:
                self.q.put(self._done)
        self.t = threading.Thread(target=worker, daemon=True)
        self.t.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self.q.get()
        if item is self._done:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


class DataLoader:
    """reference: python/paddle/io/reader.py:262."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        if num_workers == 0:
            # incubate.autotune dataloader tuning (reference: the tuner
            # rewrites num_workers after measuring)
            from ..incubate.autotune import tuned_num_workers
            tuned = tuned_num_workers()
            if tuned:
                num_workers = tuned
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self._is_iterable = isinstance(dataset, IterableDataset)
        if not self._is_iterable:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                if batch_size is None:
                    raise ValueError("batch_size=None requires batch_sampler")
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
        else:
            self.batch_sampler = None

    def __iter__(self):
        if self.num_workers > 0:
            return _PrefetchLoaderIter(self, self.num_workers,
                                       self.prefetch_factor)
        return _SingleProcessLoaderIter(self)

    def __len__(self):
        if self._is_iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)


def get_worker_info():
    return None
