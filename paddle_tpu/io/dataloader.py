"""DataLoader (reference: python/paddle/io/reader.py:262 DataLoader;
multiprocess workers python/paddle/io/dataloader/worker.py).

TPU-native host data path: multiprocess workers feed a prefetch queue
(double-buffering host→device transfer against compute). The C++ fast
collate path lives in native/ (paddle_tpu.lib.fast_collate) and is used
automatically for numeric batches when built.
"""
from __future__ import annotations

import itertools
import os
import queue
import threading
import time
from typing import Any, Callable, List, Optional

import numpy as np

from .dataset import (BatchSampler, Dataset, IterableDataset,
                      SequenceSampler, RandomSampler)
from .._core.tensor import Tensor
from ..observability import hooks as _obs


def default_collate_fn(batch):
    """reference: io/dataloader/collate.py default_collate_fn."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        from .native_collate import collate_stack
        return Tensor(collate_stack(batch))
    if isinstance(sample, Tensor):
        from ..ops.manipulation import stack
        return stack(batch, axis=0)
    if isinstance(sample, (int, float, np.number)):
        return Tensor(np.asarray(batch))
    if isinstance(sample, (str, bytes)):
        return batch
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    raise TypeError(f"cannot collate batch of {type(sample)}")


def default_convert_fn(batch):
    if isinstance(batch, np.ndarray):
        return Tensor(batch)
    if isinstance(batch, (tuple, list)):
        return [default_convert_fn(b) for b in batch]
    return batch


class _SingleProcessLoaderIter:
    def __init__(self, loader):
        self.loader = loader
        self.dataset = loader.dataset
        self.collate_fn = loader.collate_fn or default_collate_fn
        if loader._is_iterable:
            self._it = iter(self.dataset)
            self._drained = False
        else:
            self._sampler_it = iter(loader.batch_sampler)

    def __iter__(self):
        return self

    def __next__(self):
        # reader-wait telemetry (observability.hooks): time blocked in
        # the loader vs the consumer's compute gap — zero-cost when off
        if not _obs.active():
            return self._next_impl()
        t0 = time.perf_counter_ns()
        batch = self._next_impl()
        _obs.dataloader_next(self, t0)
        return batch

    def _next_impl(self):
        if self.loader._is_iterable:
            batch = list(itertools.islice(self._it,
                                          self.loader.batch_size or 1))
            if not batch:
                raise StopIteration
            if self.loader.batch_size is None:
                return default_convert_fn(batch[0])
            if len(batch) < (self.loader.batch_size or 1) and \
                    self.loader.drop_last:
                raise StopIteration
            return self.collate_fn(batch)
        indices = next(self._sampler_it)
        batch = [self.dataset[i] for i in indices]
        return self.collate_fn(batch)


def _pool_worker_main(ref, wake):
    """Worker thread entry: holds NO strong reference to the iterator
    while idle (backpressure waits happen HERE, on the shared ``wake``
    event, after dropping the ref), so a consumer that abandons
    iteration lets the iterator be garbage-collected and the pool wind
    down within one wait timeout."""
    while True:
        it = ref()
        if it is None:
            return
        try:
            status = it._worker_step_nowait()
        finally:
            del it
        if status == "exit":
            return
        if status == "idle":
            wake.wait(timeout=0.2)
            wake.clear()


class _PrefetchLoaderIter:
    """Worker-pool prefetching iterator: ``num_workers`` threads assemble
    whole batches in parallel and a reorder buffer restores sampler order
    (reference: io/dataloader/dataloader_iter.py _DataLoaderIterMultiProcess
    — multiprocess workers + an _order-preserving _task_infos buffer; on
    TPU threads suffice because sample decode/collate are numpy/IO-bound
    and release the GIL, while jax transfers also release it).

    IterableDataset keeps a single assembly thread (its iterator protocol
    is inherently sequential) but still overlaps with device compute."""

    def __init__(self, loader, num_workers, prefetch_factor):
        self._err = None
        self._lock = threading.Lock()
        if loader._is_iterable:
            # sequential source: one producer thread, bounded queue
            self.q: "queue.Queue" = queue.Queue(
                maxsize=max(2, num_workers * prefetch_factor))
            self._done = object()

            def worker():
                # reference get_worker_info() contract: inside a loader
                # worker, the dataset can ask who it is to self-shard.
                # The iterable path has ONE sequential producer, so it is
                # worker 0 of 1 (each reference worker would otherwise
                # re-iterate the whole dataset). The TLS is set BEFORE
                # iter(dataset) runs, so non-generator __iter__ bodies
                # also see it.
                _worker_info_tls.info = WorkerInfo(
                    id=0, num_workers=1, dataset=loader.dataset)
                try:
                    self.inner = _SingleProcessLoaderIter(loader)
                    for item in self.inner:
                        self.q.put(item)
                except Exception as e:  # propagate to consumer
                    self._err = e
                finally:
                    _worker_info_tls.info = None
                    self.q.put(self._done)
            self.t = threading.Thread(target=worker, daemon=True)
            self.t.start()
            self._mode = "stream"
            return

        self._mode = "pool"
        self.dataset = loader.dataset
        self.collate_fn = loader.collate_fn or default_collate_fn
        # The reference's multiprocess workers each own a dataset COPY;
        # threads share ONE object, so stateful __getitem__ (shared file
        # handle seek+read, decode buffers) would corrupt silently under
        # concurrent fetch.  Default: per-sample fetch is serialized (the
        # parallel win is collate + overlap with device compute); a
        # dataset declaring ``thread_safe = True`` unlocks fully parallel
        # fetch (the built-in array-backed datasets set it).
        self._fetch_lock = (
            None if getattr(loader.dataset, "thread_safe", False)
            else threading.Lock())
        # sampler consumed LAZILY under the lock: infinite/streaming batch
        # samplers keep working, and no O(num_batches) index list is held
        self._sampler_it = iter(loader.batch_sampler)
        self._exhausted = False
        self._ntasks = None           # known once the sampler raises Stop
        self._next_task = 0
        self._next_out = 0
        self._buf: dict = {}
        self._err_seq = None          # batch index the error belongs to
        self._stop = False
        self._cap = max(2, num_workers * prefetch_factor)
        self._cv = threading.Condition(self._lock)
        # workers hold only a WEAKREF to the iterator and re-check it
        # between steps (bounded waits): abandoning the iterator (break,
        # early return) lets it be collected, upon which every worker
        # exits — no thread/batch leak per epoch
        import weakref
        ref = weakref.ref(self)
        self._wake = threading.Event()
        self._threads = [
            threading.Thread(target=_pool_worker_main,
                             args=(ref, self._wake), daemon=True)
            for _ in range(max(1, num_workers))]
        for t in self._threads:
            t.start()

    def close(self):
        """Stop the worker pool (idempotent; called on exhaustion/error
        delivery and usable explicitly after early loop exit)."""
        if self._mode != "pool":
            return
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._wake.set()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _worker_step_nowait(self):
        """One NON-BLOCKING worker iteration: "work" (did a batch),
        "idle" (backpressure — caller waits WITHOUT holding us), or
        "exit"."""
        with self._cv:
            if self._stop or self._exhausted or self._err_seq is not None:
                return "exit"
            # backpressure: don't run more than cap batches ahead
            if self._next_task - self._next_out >= self._cap:
                return "idle"
            seq = self._next_task
            try:
                indices = next(self._sampler_it)
            except StopIteration:
                self._exhausted = True
                self._ntasks = self._next_task
                self._cv.notify_all()
                return "exit"
            except Exception as e:   # buggy sampler: surface, don't hang
                self._err, self._err_seq = e, self._next_task
                self._cv.notify_all()
                return "exit"
            self._next_task += 1
        try:
            if self._fetch_lock is not None:
                with self._fetch_lock:
                    samples = [self.dataset[i] for i in indices]
            else:
                samples = [self.dataset[i] for i in indices]
            batch = self.collate_fn(samples)
        except Exception as e:
            with self._cv:
                # deliver every earlier batch first: the error is
                # raised only when the consumer reaches THIS position
                # (matches the old sequential path's determinism)
                if self._err_seq is None or seq < self._err_seq:
                    self._err, self._err_seq = e, seq
                self._cv.notify_all()
            return "exit"
        with self._cv:
            self._buf[seq] = batch
            self._cv.notify_all()
        return "work"

    def __iter__(self):
        return self

    def __next__(self):
        if not _obs.active():
            return self._next_impl()
        t0 = time.perf_counter_ns()
        batch = self._next_impl()
        _obs.dataloader_next(self, t0)
        return batch

    def _next_impl(self):
        if self._mode == "stream":
            item = self.q.get()
            if item is self._done:
                if self._err is not None:
                    raise self._err
                raise StopIteration
            return item
        with self._cv:
            while True:
                if self._err_seq is not None and \
                        self._next_out == self._err_seq:
                    self._stop = True
                    self._cv.notify_all()
                    raise self._err
                if self._next_out in self._buf:
                    batch = self._buf.pop(self._next_out)
                    self._next_out += 1
                    self._cv.notify_all()
                    self._wake.set()   # capacity freed: rouse idle workers
                    return batch
                if self._ntasks is not None and \
                        self._next_out >= self._ntasks:
                    self._stop = True
                    self._cv.notify_all()
                    raise StopIteration
                self._cv.wait()


class DataLoader:
    """reference: python/paddle/io/reader.py:262."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        if num_workers == 0:
            # incubate.autotune dataloader tuning (reference: the tuner
            # rewrites num_workers after measuring)
            from ..incubate.autotune import tuned_num_workers
            tuned = tuned_num_workers()
            if tuned:
                num_workers = tuned
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.use_shared_memory = use_shared_memory
        self.persistent_workers = persistent_workers
        self._mp_pool = None
        self._mp_failed = False
        self._is_iterable = isinstance(dataset, IterableDataset)
        if not self._is_iterable:
            if batch_sampler is not None:
                self.batch_sampler = batch_sampler
            else:
                if batch_size is None:
                    raise ValueError("batch_size=None requires batch_sampler")
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)
        else:
            self.batch_sampler = None

    def __iter__(self):
        if self.num_workers > 0:
            # process workers (reference: multiprocessing.Process,
            # dataloader_iter.py:459) for map-style datasets — true
            # parallelism for GIL-bound Python transforms. Thread tier
            # stays the fallback: IterableDataset (sequential iterator
            # protocol), use_shared_memory=False, or unpicklable
            # dataset/collate/worker_init_fn (warned once).
            if not self._is_iterable and self.use_shared_memory and \
                    not self._mp_failed and \
                    os.environ.get("PADDLE_TPU_LOADER_THREADS") != "1":
                from .mp_loader import MPLoaderIter, _MPPool
                try:
                    if self.persistent_workers:
                        # one pool serves every epoch (spawn cost paid
                        # once; reference: reader.py persistent_workers).
                        # A pool with ANY dead worker is recreated: a
                        # startup error re-raises at root cause on the
                        # fresh pool, and a partially-dead pool (one
                        # OOM-killed worker) would otherwise trip the
                        # dead-worker check spuriously in later epochs
                        pool = self._mp_pool
                        if pool is not None and not pool.closed and \
                                any(not p.is_alive() for p in pool.procs):
                            pool.close()
                            pool = None
                        if pool is None or pool.closed:
                            self._mp_pool = _MPPool(self, self.num_workers)
                        return MPLoaderIter(self, self.num_workers,
                                            self.prefetch_factor,
                                            pool=self._mp_pool)
                    return MPLoaderIter(self, self.num_workers,
                                        self.prefetch_factor)
                except Exception as e:  # pickle/spawn failure
                    self._mp_failed = True
                    import warnings
                    warnings.warn(
                        f"DataLoader: multiprocess workers unavailable "
                        f"({type(e).__name__}: {e}); falling back to "
                        f"thread workers", stacklevel=2)
            return _PrefetchLoaderIter(self, self.num_workers,
                                       self.prefetch_factor)
        return _SingleProcessLoaderIter(self)

    def __len__(self):
        if self._is_iterable:
            raise TypeError("IterableDataset has no len()")
        return len(self.batch_sampler)

    def __del__(self):
        pool = getattr(self, "_mp_pool", None)
        if pool is not None:
            try:
                pool.close()
            except Exception:
                pass


class WorkerInfo:
    """reference: io/dataloader/worker.py WorkerInfo — (id, num_workers,
    dataset) visible to IterableDataset.__iter__ for self-sharding."""

    def __init__(self, id, num_workers, dataset):
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset


_worker_info_tls = threading.local()


def get_worker_info():
    """reference: io/reader.py get_worker_info — None outside a loader
    worker; inside, the worker's identity."""
    return getattr(_worker_info_tls, "info", None)


def prefetch_to_device(loader, size: int = 2, sharding=None):
    """Wrap an iterable of (pytrees of) host batches into an iterator
    that keeps ``size`` batches already transferred to the accelerator —
    the H2D copy of batch i+1 overlaps the step computing batch i.

    TPU-native analog of the reference DataLoader's buffered reader tier
    (reference: use_buffer_reader/prefetch_factor — there a host-side
    double buffer; here the buffer lives in HBM). ``sharding``: optional
    ``jax.sharding.Sharding`` (e.g. a dp NamedSharding) applied in the
    transfer, so batches land already-sharded for the jit step.
    """
    import collections
    import jax

    from .._core.tensor import Tensor

    def _put(batch):
        def leaf(x):
            if isinstance(x, Tensor):
                return Tensor(jax.device_put(x._value, sharding),
                              _internal=True)
            return jax.device_put(x, sharding)

        return jax.tree.map(leaf, batch,
                            is_leaf=lambda x: isinstance(x, Tensor))

    queue = collections.deque()
    it = iter(loader)

    def gen():
        while True:
            while len(queue) < max(1, size):
                try:
                    queue.append(_put(next(it)))
                except StopIteration:
                    while queue:
                        yield queue.popleft()
                    return
            yield queue.popleft()

    return gen()
