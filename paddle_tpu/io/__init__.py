"""paddle_tpu.io (reference: python/paddle/io/)."""
from .dataset import (  # noqa: F401
    Dataset, IterableDataset, TensorDataset, ComposeDataset, ChainDataset,
    ConcatDataset, Subset, random_split, Sampler, SequenceSampler,
    RandomSampler, WeightedRandomSampler, SubsetRandomSampler, BatchSampler,
    DistributedBatchSampler,
)
from .dataloader import (  # noqa: F401
    DataLoader, default_collate_fn, get_worker_info, prefetch_to_device,
)
