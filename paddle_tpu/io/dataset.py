"""Datasets + samplers (reference: python/paddle/io/dataloader/dataset.py,
sampler.py, batch_sampler.py)."""
from __future__ import annotations

import bisect
import math
from typing import Iterable, List, Sequence

import numpy as np


class Dataset:
    """reference: io/dataloader/dataset.py Dataset.

    Set ``thread_safe = True`` on a subclass whose ``__getitem__`` is safe
    to call from several threads at once (pure indexing, no shared
    seek/read state): the DataLoader worker pool then fetches samples
    fully in parallel instead of serializing the per-sample fetch."""

    thread_safe = False

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    thread_safe = True   # pure array indexing

    def __init__(self, tensors):
        from .._core.tensor import Tensor
        assert all(t.shape[0] == tensors[0].shape[0] for t in tensors)
        self.tensors = tensors

    def __getitem__(self, index):
        return tuple(t[index] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert all(len(d) == len(self.datasets[0]) for d in self.datasets)

    def __len__(self):
        return len(self.datasets[0])

    def __getitem__(self, index):
        sample = []
        for d in self.datasets:
            item = d[index]
            sample.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(sample)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = np.cumsum([len(d) for d in
                                           self.datasets]).tolist()

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        off = idx - (self.cumulative_sizes[di - 1] if di > 0 else 0)
        return self.datasets[di][off]


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    """reference: io/dataloader/dataset.py random_split."""
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths) and \
            abs(sum(lengths) - 1.0) < 1e-6:
        sizes = [int(math.floor(total * l)) for l in lengths]
        for i in range(total - sum(sizes)):
            sizes[i % len(sizes)] += 1
        lengths = sizes
    assert sum(lengths) == total
    perm = np.random.permutation(total)
    out, off = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[off:off + l].tolist()))
        off += l
    return out


class Sampler:
    """reference: io/dataloader/sampler.py Sampler."""

    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            yield from np.random.randint(0, n, self.num_samples).tolist()
        else:
            yield from np.random.permutation(n)[:self.num_samples].tolist()

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        yield from idx.tolist()

    def __len__(self):
        return self.num_samples


class SubsetRandomSampler(Sampler):
    def __init__(self, indices, generator=None):
        self.indices = list(indices)

    def __iter__(self):
        perm = np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in perm])

    def __len__(self):
        return len(self.indices)


class BatchSampler(Sampler):
    """reference: io/dataloader/batch_sampler.py BatchSampler."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        if sampler is None:
            sampler = RandomSampler(dataset) if shuffle else \
                SequenceSampler(dataset)
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """reference: io/dataloader/batch_sampler.py DistributedBatchSampler —
    shards indices across data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        if num_replicas is None or rank is None:
            from ..distributed import env as dist_env
            num_replicas = num_replicas if num_replicas is not None else \
                dist_env.get_world_size()
            rank = rank if rank is not None else dist_env.get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
