"""Runtime telemetry: metrics registry + hot-path spans + step timeline.

The reference stack pairs its HostTracer/CUPTI profiler with
instrumentation woven through the runtime
(paddle/fluid/platform/profiler/); this package is that layer for the
TPU build:

- :mod:`metrics` — process-global, thread-safe Counters / Gauges /
  Histograms with labels, exportable as Prometheus text
  (``REGISTRY.to_prometheus()``) and JSON (``REGISTRY.to_json()``).
- :mod:`hooks` — the emitters the hot paths call (pipeline engine,
  predictor, generate, dataloader, collectives, watchdog). Near-zero
  cost when disabled: one module-flag read per call site, no
  allocation (``hooks.span`` hands back a shared nullcontext).
- :mod:`timeline` — merges profiler spans + metrics into one per-phase
  summary dict (``Profiler.phase_summary()``; ``bench.py`` attaches it
  under each round's ``phases`` key) + the shared sort-stable Chrome
  trace exporter (``chrome_trace``).
- :mod:`tracing` — request-scoped distributed tracing for the serving
  plane: a trace minted at submission rides the request handle through
  queue/prefill/handoff/swap/decode/recovery, stitching cross-replica
  hops into one trace; per-request TTFT breakdowns; Chrome export.
  Independent switch (``tracing.enable(clock_ns=...)``), zero-cost
  when off.
- :mod:`flight` — the crash flight recorder: per-supervisor ring of
  scheduler ticks + request-trace tails, dumped as a CRC-framed
  ``flight-<ts>.json`` black box on EngineDead / step exceptions / on
  demand.

Usage::

    import paddle_tpu.observability as obs
    obs.enable()                       # or PADDLE_TPU_METRICS=1
    ... run training / serving ...
    print(obs.REGISTRY.to_prometheus())   # scrape payload
    obs.disable()
"""
from . import metrics  # noqa: F401
from . import hooks  # noqa: F401
from . import timeline  # noqa: F401
from . import tracing  # noqa: F401
from . import flight  # noqa: F401
from .metrics import (  # noqa: F401
    REGISTRY, Counter, Gauge, Histogram, MetricsRegistry,
    counter, gauge, histogram,
)
from .hooks import enable, disable, metrics_enabled, span  # noqa: F401
from .timeline import (  # noqa: F401
    StepTimeline, chrome_trace, phase_summary,
)
from .tracing import RequestTrace, Tracer  # noqa: F401
from .flight import FlightRecorder  # noqa: F401
