"""Crash flight recorder for the serving plane (ISSUE 16).

A fixed-size ring buffer per :class:`~paddle_tpu.serving.EngineSupervisor`
recording the last N scheduler ticks (plan summary, budget use,
degraded rung, consecutive-failure count, WAL lsn) plus the last M
request-trace tails, dumped as a CRC-framed ``flight-<ts>.json`` into
the supervisor's WAL/journal directory on EngineDead, on any exception
escaping ``step()``, and on demand (``EngineSupervisor.dump_flight()``)
— every simulated kill -9 leaves a readable black box next to the log
it replays.

Framing mirrors the WAL's integrity discipline
(:mod:`paddle_tpu.serving.wal`: magic + length + crc32 per frame) but
stays a PLAIN json file so the dump is greppable on a dead box with no
tooling: the envelope is ``{"magic": "PTFR", "version": 1, "crc32":
<crc of the canonical payload encoding>, "payload": {...}}`` and
:func:`load` re-encodes the parsed payload canonically to verify the
checksum — a torn or bit-flipped dump fails loudly, same as a torn WAL
frame.  Writes are atomic (tmp + fsync + rename) for the same reason
WAL checkpoints are.
"""
from __future__ import annotations

import json
import os
import time
import zlib
from collections import deque
from typing import Optional

MAGIC = "PTFR"
VERSION = 1
PREFIX = "flight-"


def _canonical(payload) -> bytes:
    """The byte encoding the CRC covers. ``default=_jsonable`` maps
    numpy scalars (tick fields come straight off scheduler state) to
    native ints/floats, so the parsed payload re-encodes to the SAME
    bytes — the property :func:`load`'s verification rests on."""
    return json.dumps(payload, sort_keys=True, separators=(",", ":"),
                      default=_jsonable).encode("utf-8")


def _jsonable(x):
    item = getattr(x, "item", None)     # numpy scalar -> native
    if item is not None:
        try:
            return item()
        except Exception:
            pass
    tolist = getattr(x, "tolist", None)  # small numpy array -> list
    if tolist is not None:
        try:
            return tolist()
        except Exception:
            pass
    return str(x)


class FlightRecorder:
    """The per-supervisor ring. Always-on and allocation-light: one
    small dict append per scheduler tick (the supervisor already pays
    a WAL append per tick; this is noise next to it)."""

    def __init__(self, max_ticks: int = 256, max_traces: int = 8,
                 max_trace_spans: int = 32, meta: Optional[dict] = None):
        self.ticks = deque(maxlen=max(1, int(max_ticks)))
        self.max_traces = int(max_traces)
        self.max_trace_spans = int(max_trace_spans)
        self.meta = dict(meta or {})
        self.ticks_total = 0
        self.dumps = []          # paths this recorder wrote

    def record_tick(self, **fields) -> None:
        self.ticks_total += 1
        self.ticks.append(fields)

    def last_ticks(self) -> list:
        return list(self.ticks)

    def dump(self, dir_path: str, reason: str,
             extra: Optional[dict] = None) -> str:
        """Write the black box: ring + request-trace tails (when
        tracing is on) + supervisor-supplied extras. Returns the
        path. Never called from a context that can tolerate a second
        failure — callers wrap it best-effort."""
        from . import tracing
        os.makedirs(dir_path, exist_ok=True)
        payload = {
            "reason": str(reason),
            "wall_time": time.time(),
            "meta": self.meta,
            "ticks_total": self.ticks_total,
            "ticks": list(self.ticks),
            "traces": (tracing.TRACER.tails(self.max_traces,
                                            self.max_trace_spans)
                       if tracing.enabled else []),
            "extra": extra or {},
        }
        body = _canonical(payload)
        doc = (b'{"magic":"%s","version":%d,"crc32":%d,"payload":'
               % (MAGIC.encode(), VERSION, zlib.crc32(body))
               ) + body + b"}"
        path = os.path.join(dir_path, f"{PREFIX}{time.time_ns()}.json")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(doc)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        self.dumps.append(path)
        return path


def load(path: str) -> dict:
    """Parse + integrity-check a flight dump; returns the payload.
    Raises ValueError on a bad magic, version, or CRC mismatch (a torn
    or corrupted dump must fail loudly, like a torn WAL frame)."""
    with open(path, "rb") as f:
        doc = json.load(f)
    if doc.get("magic") != MAGIC:
        raise ValueError(f"{path}: not a flight dump (magic "
                         f"{doc.get('magic')!r})")
    if doc.get("version") != VERSION:
        raise ValueError(f"{path}: unsupported flight dump version "
                         f"{doc.get('version')!r}")
    payload = doc.get("payload")
    crc = zlib.crc32(_canonical(payload))
    if crc != doc.get("crc32"):
        raise ValueError(f"{path}: flight dump CRC mismatch "
                         f"(stored {doc.get('crc32')}, computed {crc})")
    return payload


def find_dumps(dir_path: str) -> list:
    """All flight dumps under ``dir_path``, oldest first (the
    timestamped names sort chronologically)."""
    try:
        names = sorted(n for n in os.listdir(dir_path)
                       if n.startswith(PREFIX) and n.endswith(".json"))
    except OSError:
        return []
    return [os.path.join(dir_path, n) for n in names]
