"""Process-global metrics registry.

Re-design of the reference's runtime stat surface (reference:
paddle/fluid/platform/profiler + the serving stack's exported counters)
as a Prometheus-style registry: Counters, Gauges and Histograms with
label support, exportable as Prometheus text exposition format and as a
JSON snapshot. Everything is thread-safe — hot-path emitters run from
dataloader worker threads and the watchdog thread concurrently with a
scrape.

The registry itself is always live; whether the hot paths FEED it is
gated by :mod:`paddle_tpu.observability.hooks` (one module-global flag),
so a disabled process pays one boolean read per instrumented call site
and allocates nothing.
"""
from __future__ import annotations

import json
import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# latency-oriented default buckets (seconds): 100us .. 60s covers a
# dataloader wait as well as a cold XLA compile
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _fmt_labels(names: Sequence[str], values: Sequence[str],
                extra: Tuple[str, str] = None) -> str:
    pairs = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    if extra is not None:
        pairs.append(f'{extra[0]}="{extra[1]}"')
    return "{" + ",".join(pairs) + "}" if pairs else ""


class _CounterValue:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += amount

    def get(self) -> float:
        return self._value


class _GaugeValue:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float):
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0):
        self.inc(-amount)

    def get(self) -> float:
        return self._value


class _HistogramValue:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float]):
        self._lock = threading.Lock()
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +1: +Inf
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            i = len(self.buckets)
            for j, b in enumerate(self.buckets):
                if v <= b:
                    i = j
                    break
            self.counts[i] += 1
            self.sum += v
            self.count += 1

    def get(self) -> dict:
        with self._lock:
            cum, out = 0, {}
            for b, c in zip(self.buckets, self.counts):
                cum += c
                out[b] = cum
            return {"buckets": out, "sum": self.sum, "count": self.count}


class Metric:
    """One named metric; label combinations materialize child values."""

    kind = "untyped"
    _child_cls = _CounterValue

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not name or not name.replace("_", "").replace(":", "").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    def _new_child(self):
        return self._child_cls()

    def labels(self, *values, **kwvalues):
        if kwvalues:
            if values:
                raise ValueError("pass labels positionally OR by name")
            try:
                values = tuple(kwvalues[n] for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e.args[0]!r} "
                    f"(labelnames={self.labelnames})") from None
            if len(kwvalues) != len(self.labelnames):
                extra = set(kwvalues) - set(self.labelnames)
                raise ValueError(f"{self.name}: unknown labels {extra}")
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected {len(self.labelnames)} label "
                f"value(s) {self.labelnames}, got {len(values)}")
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.get(values)
                if child is None:
                    child = self._children[values] = self._new_child()
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call "
                f".labels(...) first")
        return self.labels()

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return list(self._children.items())


class Counter(Metric):
    kind = "counter"
    _child_cls = _CounterValue

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def get(self) -> float:
        return self._default().get()


class Gauge(Metric):
    kind = "gauge"
    _child_cls = _GaugeValue

    def set(self, value: float):
        self._default().set(value)

    def inc(self, amount: float = 1.0):
        self._default().inc(amount)

    def dec(self, amount: float = 1.0):
        self._default().dec(amount)

    def get(self) -> float:
        return self._default().get()


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, help="", labelnames=(),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, labelnames)
        bs = tuple(sorted(buckets if buckets is not None
                          else DEFAULT_BUCKETS))
        if not bs or any(not math.isfinite(b) for b in bs):
            raise ValueError("histogram buckets must be finite and "
                             "non-empty (+Inf is implicit)")
        self.buckets = bs

    def _new_child(self):
        return _HistogramValue(self.buckets)

    def observe(self, value: float):
        self._default().observe(value)

    def get(self) -> dict:
        return self._default().get()


class MetricsRegistry:
    """Get-or-create registry; name collisions across kinds, labels, or
    explicitly differing histogram buckets raise."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, Metric] = {}

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                if type(m) is not cls or \
                        m.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind} with labels {m.labelnames}")
                # explicit differing buckets must not silently reuse the
                # first registration's boundaries (None = don't care)
                buckets = kw.get("buckets")
                if buckets is not None and \
                        tuple(sorted(buckets)) != m.buckets:
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {m.buckets}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return list(self._metrics)

    def collect(self) -> List[Metric]:
        with self._lock:
            return list(self._metrics.values())

    def clear(self):
        """Drop every metric (tests / fresh rounds)."""
        with self._lock:
            self._metrics.clear()

    # ---- exporters ----
    def to_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4)."""
        lines: List[str] = []
        for m in self.collect():
            lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for lv, child in m.children():
                if isinstance(child, _HistogramValue):
                    snap = child.get()
                    for b, cum in snap["buckets"].items():
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_fmt_labels(m.labelnames, lv, ('le', repr(float(b)))) }"
                            f" {cum}")
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(m.labelnames, lv, ('le', '+Inf'))}"
                        f" {snap['count']}")
                    lab = _fmt_labels(m.labelnames, lv)
                    lines.append(f"{m.name}_sum{lab} {snap['sum']}")
                    lines.append(f"{m.name}_count{lab} {snap['count']}")
                else:
                    lab = _fmt_labels(m.labelnames, lv)
                    lines.append(f"{m.name}{lab} {child.get()}")
        return "\n".join(lines) + ("\n" if lines else "")

    def to_json(self) -> dict:
        """Structured snapshot: {name: {kind, help, values}}; histogram
        values carry bucket counts + sum/count."""
        out = {}
        for m in self.collect():
            values = {}
            for lv, child in m.children():
                key = ",".join(f"{n}={v}" for n, v in
                               zip(m.labelnames, lv)) or ""
                v = child.get()
                if isinstance(child, _HistogramValue):
                    v = {"buckets": {repr(b): c for b, c in
                                     v["buckets"].items()},
                         "sum": v["sum"], "count": v["count"]}
                values[key] = v
            out[m.name] = {"kind": m.kind, "help": m.help,
                           "labels": list(m.labelnames), "values": values}
        return out

    def dumps(self) -> str:
        return json.dumps(self.to_json())


#: the process-global registry every hook feeds
REGISTRY = MetricsRegistry()


def counter(name, help="", labelnames=()):
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help="", labelnames=()):
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help="", labelnames=(), buckets=None):
    return REGISTRY.histogram(name, help, labelnames, buckets)
