"""Step-timeline aggregation: profiler spans -> per-phase summary dict.

Merges the host spans collected by the profiler's ``_Collector`` (the
HostTracer analog) with the metrics registry snapshot into ONE
structured dict, so a single ``Profiler`` run yields a chrome trace AND
a machine-readable per-phase breakdown — the piece BENCH_r*.json rounds
were missing (totals with no attribution). ``bench.py`` attaches this
dict under each round's ``phases`` key.

Phase mapping: the reference Model-Summary event types (Forward /
Backward / Optimization / DataLoader) plus the serving phases carried in
span names (``Generate.prefill`` / ``Generate.decode`` /
``Predictor.run``), the pipeline engine's spans (``PP.*``) and watchdog
firings. (Collectives contribute counters/bytes to the ``metrics``
snapshot, not spans — they execute inside compiled programs.)
"""
from __future__ import annotations

from typing import Dict, List, Optional

# event_type -> phase bucket (the reference Model Summary split)
_TYPE_PHASE = {
    "Forward": "forward",
    "Backward": "backward",
    "Optimization": "optimizer",
    "DataLoader": "dataloader",
    "Watchdog": "watchdog",
}

# name prefix -> phase bucket; FIRST match wins, checked before the
# event-type mapping so serving/pipeline spans land in their own buckets
_NAME_PHASE = (
    ("Generate.prefill", "prefill"),
    ("Generate.decode", "decode"),
    ("Predictor.run", "inference"),
    ("PP.forward", "forward"),
    ("PP.backward", "backward"),
    ("PP.spmd", "pp_spmd"),
    ("PP.", "pipeline"),
    ("Optimizer.step", "optimizer"),
    ("DataLoader.", "dataloader"),
    ("Train.step", "train_step"),
    ("Watchdog.", "watchdog"),
)


def phase_of(name: str, event_type: str) -> str:
    for prefix, phase in _NAME_PHASE:
        if name.startswith(prefix):
            return phase
    return _TYPE_PHASE.get(event_type, "other")


def phase_summary(events, step_times: Optional[List[float]] = None,
                  include_metrics: bool = True) -> dict:
    """Aggregate spans into ``{"phases": {...}, "window_ms": ...}``.

    Each phase bucket: calls, total_ms, avg_ms, max_ms and share (of the
    step window when step times exist, else of the summed span time).
    ``metrics`` carries the registry JSON snapshot so counters (tokens,
    collective bytes, watchdog firings) ride along with the timings.
    """
    phases: Dict[str, dict] = {}
    total_span_ns = 0.0
    for e in events:
        ph = phase_of(e.name, e.event_type)
        d = phases.setdefault(ph, {"calls": 0, "total_ms": 0.0,
                                   "max_ms": 0.0})
        dur = e.end - e.start
        d["calls"] += 1
        d["total_ms"] += dur / 1e6
        d["max_ms"] = max(d["max_ms"], dur / 1e6)
        total_span_ns += dur
    window_ms = (sum(step_times) * 1e3 if step_times
                 else total_span_ns / 1e6)
    for d in phases.values():
        d["avg_ms"] = round(d["total_ms"] / d["calls"], 6)
        d["share"] = round(d["total_ms"] / window_ms, 6) if window_ms \
            else 0.0
        d["total_ms"] = round(d["total_ms"], 6)
        d["max_ms"] = round(d["max_ms"], 6)
    out = {
        "phases": phases,
        "window_ms": round(window_ms, 6),
        "steps": len(step_times or ()),
    }
    if include_metrics:
        from . import metrics as _m
        snap = _m.REGISTRY.to_json()
        if snap:
            out["metrics"] = snap
    return out


def chrome_trace(rows, pid_names: Optional[Dict[int, str]] = None,
                 tid_names: Optional[Dict[int, str]] = None) -> dict:
    """Rows -> a Chrome trace-event dict (``chrome://tracing`` /
    Perfetto's legacy JSON format). Each row: {name, cat, start_ns,
    dur_ns, pid, tid, args?}.

    Two properties every exporter in the tree routes through here for
    (ISSUE 16 bugfix — the old ``Profiler._export_chrome`` emitted one
    ``os.getpid()`` row, so cluster traces interleaved into a single
    unreadable lane):

    - DISTINCT pid/tid rows: callers map replica -> pid and slot ->
      tid (``pid_names``/``tid_names`` become process_name /
      thread_name metadata events), so a 2-replica handoff renders as
      two labeled process groups instead of one shredded row.
    - SORT-STABLE output: events are ordered by (pid, tid, ts, dur,
      name) and metadata precedes them, so two exports of the same
      spans serialize byte-identically — golden tests diff the bytes.
    """
    meta = []
    for pid, label in sorted((pid_names or {}).items()):
        meta.append({"ph": "M", "name": "process_name", "pid": pid,
                     "tid": 0, "args": {"name": label}})
    for tid, label in sorted((tid_names or {}).items()):
        meta.append({"ph": "M", "name": "thread_name", "pid": 0,
                     "tid": tid, "args": {"name": label}})
    events = []
    for r in rows:
        ev = {"ph": "X", "name": r["name"], "cat": r.get("cat", ""),
              "pid": int(r.get("pid", 0)), "tid": int(r.get("tid", 0)),
              "ts": r["start_ns"] / 1e3,        # chrome wants microsecs
              "dur": r.get("dur_ns", 0) / 1e3}
        if r.get("args"):
            ev["args"] = r["args"]
        events.append(ev)
    events.sort(key=lambda e: (e["pid"], e["tid"], e["ts"], e["dur"],
                               e["name"]))
    return {"traceEvents": meta + events}


class StepTimeline:
    """Incremental aggregator over a live profiler run.

    ``merge(profiler)`` folds the profiler's collected spans (draining
    the native ring through ``_Collector.drain``) and its step times
    into this timeline; ``summary()`` emits the combined per-phase
    dict. Lets a long job merge several RECORD windows into one
    breakdown."""

    def __init__(self):
        self._events = []
        self._step_times: List[float] = []

    def merge(self, prof) -> "StepTimeline":
        self._events.extend(prof.events())
        self._step_times.extend(getattr(prof, "_step_times", ()))
        return self

    def add_events(self, events) -> "StepTimeline":
        self._events.extend(events)
        return self

    def summary(self, include_metrics: bool = True) -> dict:
        return phase_summary(self._events, self._step_times,
                             include_metrics=include_metrics)
