"""Step-timeline aggregation: profiler spans -> per-phase summary dict.

Merges the host spans collected by the profiler's ``_Collector`` (the
HostTracer analog) with the metrics registry snapshot into ONE
structured dict, so a single ``Profiler`` run yields a chrome trace AND
a machine-readable per-phase breakdown — the piece BENCH_r*.json rounds
were missing (totals with no attribution). ``bench.py`` attaches this
dict under each round's ``phases`` key.

Phase mapping: the reference Model-Summary event types (Forward /
Backward / Optimization / DataLoader) plus the serving phases carried in
span names (``Generate.prefill`` / ``Generate.decode`` /
``Predictor.run``), the pipeline engine's spans (``PP.*``) and watchdog
firings. (Collectives contribute counters/bytes to the ``metrics``
snapshot, not spans — they execute inside compiled programs.)
"""
from __future__ import annotations

from typing import Dict, List, Optional

# event_type -> phase bucket (the reference Model Summary split)
_TYPE_PHASE = {
    "Forward": "forward",
    "Backward": "backward",
    "Optimization": "optimizer",
    "DataLoader": "dataloader",
    "Watchdog": "watchdog",
}

# name prefix -> phase bucket; FIRST match wins, checked before the
# event-type mapping so serving/pipeline spans land in their own buckets
_NAME_PHASE = (
    ("Generate.prefill", "prefill"),
    ("Generate.decode", "decode"),
    ("Predictor.run", "inference"),
    ("PP.forward", "forward"),
    ("PP.backward", "backward"),
    ("PP.spmd", "pp_spmd"),
    ("PP.", "pipeline"),
    ("Optimizer.step", "optimizer"),
    ("DataLoader.", "dataloader"),
    ("Train.step", "train_step"),
    ("Watchdog.", "watchdog"),
)


def phase_of(name: str, event_type: str) -> str:
    for prefix, phase in _NAME_PHASE:
        if name.startswith(prefix):
            return phase
    return _TYPE_PHASE.get(event_type, "other")


def phase_summary(events, step_times: Optional[List[float]] = None,
                  include_metrics: bool = True) -> dict:
    """Aggregate spans into ``{"phases": {...}, "window_ms": ...}``.

    Each phase bucket: calls, total_ms, avg_ms, max_ms and share (of the
    step window when step times exist, else of the summed span time).
    ``metrics`` carries the registry JSON snapshot so counters (tokens,
    collective bytes, watchdog firings) ride along with the timings.
    """
    phases: Dict[str, dict] = {}
    total_span_ns = 0.0
    for e in events:
        ph = phase_of(e.name, e.event_type)
        d = phases.setdefault(ph, {"calls": 0, "total_ms": 0.0,
                                   "max_ms": 0.0})
        dur = e.end - e.start
        d["calls"] += 1
        d["total_ms"] += dur / 1e6
        d["max_ms"] = max(d["max_ms"], dur / 1e6)
        total_span_ns += dur
    window_ms = (sum(step_times) * 1e3 if step_times
                 else total_span_ns / 1e6)
    for d in phases.values():
        d["avg_ms"] = round(d["total_ms"] / d["calls"], 6)
        d["share"] = round(d["total_ms"] / window_ms, 6) if window_ms \
            else 0.0
        d["total_ms"] = round(d["total_ms"], 6)
        d["max_ms"] = round(d["max_ms"], 6)
    out = {
        "phases": phases,
        "window_ms": round(window_ms, 6),
        "steps": len(step_times or ()),
    }
    if include_metrics:
        from . import metrics as _m
        snap = _m.REGISTRY.to_json()
        if snap:
            out["metrics"] = snap
    return out


class StepTimeline:
    """Incremental aggregator over a live profiler run.

    ``merge(profiler)`` folds the profiler's collected spans (draining
    the native ring through ``_Collector.drain``) and its step times
    into this timeline; ``summary()`` emits the combined per-phase
    dict. Lets a long job merge several RECORD windows into one
    breakdown."""

    def __init__(self):
        self._events = []
        self._step_times: List[float] = []

    def merge(self, prof) -> "StepTimeline":
        self._events.extend(prof.events())
        self._step_times.extend(getattr(prof, "_step_times", ()))
        return self

    def add_events(self, events) -> "StepTimeline":
        self._events.extend(events)
        return self

    def summary(self, include_metrics: bool = True) -> dict:
        return phase_summary(self._events, self._step_times,
                             include_metrics=include_metrics)
