"""Request-scoped distributed tracing for the serving plane (ISSUE 16).

The PR 1 metrics plane is aggregate-only; this module is the
PER-REQUEST lifecycle view: a trace minted at submission rides the
:class:`~paddle_tpu.inference.predictor.GenerationRequest` handle
through every edge the serving tower moves it across — queue wait,
admission, each prefill chunk, each decode/verify commit it
participated in, preempt -> swap-out -> swap-in, prefill->decode
handoff across replicas, WAL recovery replay, finish — as HOST-side
spans.  Spans carry replica id + slot + a per-request step seq, so a
request that crosses replicas (cluster handoff, failover rehome)
stitches into ONE trace: the handle carries its ``RequestTrace`` and
``Tracer.attach`` is a no-op on an already-traced request.

Contracts (the same discipline the rest of the tower lives by):

- ZERO cost when disabled: every hook in :mod:`.hooks` that feeds this
  module starts with one module-attribute read (``tracing.enabled``) —
  no allocation, no clock read (``serving_trace_now`` returns 0 and
  call sites skip the close entirely, the PR 1 pattern).
- NO device syncs: span timestamps come from the tracer's host clock;
  call sites close spans only at existing commit fences or on pure
  host paths.  ``tools/check_instrumentation.py`` lints this file for
  device-fetch/fence idioms like the dispatch paths.
- BOUNDED memory: each trace keeps at most ``max_spans`` spans (a ring
  — the tail survives, the drop count is kept), and the tracer holds
  at most ``max_traces`` traces (LRU by insertion; evictions counted).
- DETERMINISTIC under virtual time: the clock is injectable
  (``enable(clock_ns=...)``), so FakeClock traffic runs produce
  byte-identical Chrome exports run-to-run.

Exports: ``Tracer.chrome()`` (Chrome trace JSON via
:func:`paddle_tpu.observability.timeline.chrome_trace` — one pid row
per replica, one tid row per slot) and per-request
``RequestTrace.ttft_breakdown()`` — {queue_ms, prefill_ms, handoff_ms,
swap_ms, sched_overhead_ms} — which ``serving.traffic.SLOReport``
aggregates into p50/p99 breakdown columns.
"""
from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Optional

#: module-global fast-path flag — hooks read this directly (one
#: attribute read per disabled call, the PR 1 contract)
enabled = False

_DEF_MAX_TRACES = 1024
_DEF_MAX_SPANS = 512

#: span name -> TTFT phase bucket. Anything unlisted is host-plane
#: bookkeeping and lands in sched_overhead_ms by subtraction.
PHASE_OF = {
    "queue_wait": "queue",
    "prefill_chunk": "prefill",
    "resume_replay": "prefill",
    "decode_step": "decode",
    "spec_verify": "decode",
    "handoff_export": "handoff",
    "handoff_import": "handoff",
    "swap_out": "swap",
    "swap_in": "swap",
    "wal_replay": "recovery",
}

#: phases whose span close can mint the first token (the TTFT stamp)
_FIRST_TOKEN_PHASES = ("prefill", "decode")


class Span:
    """One closed host-side span. Plain slots, no behavior — traces
    hold thousands of these."""

    __slots__ = ("name", "start_ns", "end_ns", "replica", "slot",
                 "seq", "meta")

    def __init__(self, name, start_ns, end_ns, replica=-1, slot=-1,
                 seq=-1, meta=None):
        self.name = name
        self.start_ns = int(start_ns)
        self.end_ns = int(end_ns)
        self.replica = int(replica)
        self.slot = int(slot)
        self.seq = int(seq)
        self.meta = meta

    @property
    def phase(self) -> str:
        return PHASE_OF.get(self.name, "sched")

    def to_dict(self) -> dict:
        d = {"name": self.name, "start_ns": self.start_ns,
             "end_ns": self.end_ns, "replica": self.replica,
             "slot": self.slot, "seq": self.seq}
        if self.meta:
            d["meta"] = self.meta
        return d


class RequestTrace:
    """The per-request span ring + the incrementally-maintained TTFT
    phase accumulator (kept OUTSIDE the ring so a long decode that
    evicts the early spans cannot lose the breakdown)."""

    __slots__ = ("trace_id", "rid", "submit_ns", "enqueued_ns",
                 "first_token_ns", "end_ns", "spans", "recorded",
                 "dropped", "phase_ns", "replicas", "done", "reason")

    def __init__(self, trace_id: int, rid: int, now_ns: int,
                 max_spans: int = _DEF_MAX_SPANS):
        self.trace_id = trace_id
        self.rid = rid
        self.submit_ns = now_ns
        self.enqueued_ns = now_ns     # re-stamped on every requeue
        self.first_token_ns = 0
        self.end_ns = 0
        self.spans = deque(maxlen=max(1, int(max_spans)))
        self.recorded = 0
        self.dropped = 0
        self.phase_ns = {}            # TTFT window only (pre first token)
        self.replicas = []            # insertion-ordered, deduped
        self.done = False
        self.reason = None

    def add(self, span: Span, tokens_seen: bool = False) -> None:
        self.recorded += 1
        if len(self.spans) == self.spans.maxlen:
            self.dropped += 1
        self.spans.append(span)
        if span.replica >= 0 and span.replica not in self.replicas:
            self.replicas.append(span.replica)
        if not self.first_token_ns:
            ph = span.phase
            self.phase_ns[ph] = (self.phase_ns.get(ph, 0)
                                 + max(0, span.end_ns - span.start_ns))
            if tokens_seen and ph in _FIRST_TOKEN_PHASES:
                self.first_token_ns = span.end_ns

    def ttft_breakdown(self) -> Optional[dict]:
        """Where this request's time-to-first-token went, in ms:
        {queue_ms, prefill_ms, handoff_ms, swap_ms, sched_overhead_ms,
        ttft_ms}. Pre-first-token decode/verify work counts as
        prefill_ms (it is compute toward the first token); the
        unattributed remainder — planning, dispatch bookkeeping,
        waiting for a slot in a full plan — is sched_overhead_ms.
        None until a first token exists."""
        if not self.first_token_ns:
            return None
        total = max(0, self.first_token_ns - self.submit_ns)
        q = self.phase_ns.get("queue", 0)
        p = (self.phase_ns.get("prefill", 0)
             + self.phase_ns.get("decode", 0))
        h = self.phase_ns.get("handoff", 0)
        s = self.phase_ns.get("swap", 0)
        return {
            "queue_ms": q / 1e6,
            "prefill_ms": p / 1e6,
            "handoff_ms": h / 1e6,
            "swap_ms": s / 1e6,
            "sched_overhead_ms": max(0, total - q - p - h - s) / 1e6,
            "ttft_ms": total / 1e6,
        }

    def to_dict(self, tail: Optional[int] = None) -> dict:
        spans = list(self.spans)
        if tail is not None:
            spans = spans[-tail:]
        d = {"trace_id": self.trace_id, "rid": self.rid,
             "submit_ns": self.submit_ns,
             "first_token_ns": self.first_token_ns,
             "end_ns": self.end_ns, "replicas": list(self.replicas),
             "recorded": self.recorded, "dropped": self.dropped,
             "done": self.done, "reason": self.reason,
             "spans": [s.to_dict() for s in spans]}
        bd = self.ttft_breakdown()
        if bd is not None:
            d["ttft_breakdown"] = bd
        return d


class Tracer:
    """The process trace registry: trace_id -> RequestTrace, LRU-capped
    at ``max_traces`` (insertion order; finished and live traces age
    out alike — the flight recorder snapshots tails before they do)."""

    def __init__(self, max_traces: int = _DEF_MAX_TRACES,
                 max_spans: int = _DEF_MAX_SPANS, clock_ns=None):
        self.max_traces = max(1, int(max_traces))
        self.max_spans = max(1, int(max_spans))
        self.clock_ns = clock_ns or time.monotonic_ns
        self.traces: "OrderedDict[int, RequestTrace]" = OrderedDict()
        self.evicted = 0
        self.spans_total = 0
        self._next_id = 1
        self._lock = threading.Lock()

    # ---- clock ----
    def now(self) -> int:
        return int(self.clock_ns())

    # ---- lifecycle ----
    def attach(self, req, replica: int = -1) -> RequestTrace:
        """Mint a trace onto ``req`` (idempotent: a request that
        already carries one — a handoff import, a failover rehome, a
        cluster request reaching a replica scheduler — keeps it, which
        is exactly what stitches cross-replica hops into one trace)."""
        tr = getattr(req, "trace", None)
        if tr is not None:
            return tr
        now = self.now()
        with self._lock:
            tr = RequestTrace(self._next_id, int(req.rid), now,
                              self.max_spans)
            self._next_id += 1
            self.traces[tr.trace_id] = tr
            while len(self.traces) > self.max_traces:
                self.traces.popitem(last=False)
                self.evicted += 1
        req.trace = tr
        tr.add(Span("submit", now, now, replica=replica))
        return tr

    def record(self, req, name: str, t0_ns: int, t1_ns: int = 0,
               replica: int = -1, slot: int = -1, seq: int = -1,
               meta=None) -> None:
        """Close a span opened at ``t0_ns`` (a ``now()`` anchor) onto
        ``req``'s trace; ``t1_ns=0`` closes at now. No-op for
        untraced requests (minted before enable, or evicted)."""
        tr = getattr(req, "trace", None)
        if tr is None or not t0_ns:
            return
        end = t1_ns or self.now()
        self.spans_total += 1
        tr.add(Span(name, t0_ns, end, replica=replica, slot=slot,
                    seq=seq, meta=meta),
               tokens_seen=bool(getattr(req, "tokens", None)))

    def mark(self, req, name: str, replica: int = -1, slot: int = -1,
             seq: int = -1, meta=None) -> None:
        """Zero-duration point event (preempt, dispatch, rehome, ...)."""
        now = self.now()
        self.record(req, name, now, now, replica=replica, slot=slot,
                    seq=seq, meta=meta)

    def enqueued(self, req) -> None:
        """Re-stamp the queue-wait anchor (submit and every requeue:
        preemption, recovery resume, shed-retry re-dispatch)."""
        tr = getattr(req, "trace", None)
        if tr is not None:
            tr.enqueued_ns = self.now()

    def admitted(self, req, replica: int = -1, slot: int = -1,
                 meta=None, t_ns: int = 0) -> None:
        """Close the queue_wait span opened at the last enqueue and
        mark the admission edge. ``t_ns``: the admission instant when
        the caller anchored it earlier (an admit path that swaps KV in
        first passes its entry time so queue and swap stay disjoint)."""
        tr = getattr(req, "trace", None)
        if tr is None:
            return
        now = t_ns or self.now()
        self.spans_total += 1
        tr.add(Span("queue_wait", tr.enqueued_ns, now, replica=replica,
                    slot=slot, meta=meta))
        tr.add(Span("admit", now, now, replica=replica, slot=slot))

    def first_token(self, req) -> None:
        """Stamp TTFT explicitly — the decode commit calls this for the
        rows whose first token just landed, so the stamp never depends
        on span ordering inside the commit."""
        tr = getattr(req, "trace", None)
        if tr is not None and not tr.first_token_ns:
            tr.first_token_ns = self.now()

    def finish(self, req, reason: str, replica: int = -1) -> None:
        tr = getattr(req, "trace", None)
        if tr is None or tr.done:
            return
        now = self.now()
        tr.done = True
        tr.reason = reason
        tr.end_ns = now
        tr.add(Span("finish", now, now, replica=replica,
                    meta={"reason": reason}))

    # ---- queries / exports ----
    def get(self, trace_id: int) -> Optional[RequestTrace]:
        return self.traces.get(trace_id)

    def trace_of(self, req) -> Optional[RequestTrace]:
        return getattr(req, "trace", None)

    def breakdowns(self) -> list:
        """Every trace's TTFT breakdown (finished-first-token only) —
        the raw rows ``traffic.SLOReport`` percentiles."""
        out = []
        for tr in self.traces.values():
            bd = tr.ttft_breakdown()
            if bd is not None:
                out.append(bd)
        return out

    def tails(self, max_traces: int = 8, max_spans: int = 32) -> list:
        """The newest ``max_traces`` traces, each clipped to its last
        ``max_spans`` spans — the request-side half of a flight dump."""
        trs = list(self.traces.values())[-max(0, int(max_traces)):]
        return [tr.to_dict(tail=max_spans) for tr in trs]

    def chrome(self) -> dict:
        """Chrome trace-event JSON dict: one pid row per replica (the
        un-placed replica -1 renders as pid 0 "router"), one tid row
        per slot (slotless marks on tid 0). Sort-stable — see
        :func:`paddle_tpu.observability.timeline.chrome_trace`."""
        from . import timeline
        rows = []
        for tr in self.traces.values():
            for s in tr.spans:
                args = {"trace_id": tr.trace_id, "rid": tr.rid,
                        "seq": s.seq}
                if s.meta:
                    args.update(s.meta)
                rows.append({
                    "name": s.name, "cat": s.phase,
                    "start_ns": s.start_ns,
                    "dur_ns": max(0, s.end_ns - s.start_ns),
                    "pid": s.replica + 1, "tid": max(0, s.slot) + 1,
                    "args": args})
        pids = sorted({r["pid"] for r in rows})
        labels = {p: ("router" if p == 0 else f"replica {p - 1}")
                  for p in pids}
        return timeline.chrome_trace(rows, pid_names=labels)

    def export_chrome(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump(self.chrome(), f, sort_keys=True,
                      separators=(",", ":"))
        return path

    def stats(self) -> dict:
        return {"traces": len(self.traces), "evicted": self.evicted,
                "spans_total": self.spans_total,
                "max_traces": self.max_traces,
                "max_spans": self.max_spans}


#: the process tracer — replaced wholesale by :func:`enable`
TRACER = Tracer()


def enable(clock_ns=None, max_traces: int = _DEF_MAX_TRACES,
           max_spans: int = _DEF_MAX_SPANS) -> Tracer:
    """Turn request tracing on with a FRESH registry (deterministic
    trace ids). ``clock_ns``: injectable monotonic-ns callable —
    FakeClock traffic passes a virtual clock so exports are
    byte-identical run-to-run."""
    global enabled, TRACER
    TRACER = Tracer(max_traces=max_traces, max_spans=max_spans,
                    clock_ns=clock_ns)
    enabled = True
    return TRACER


def disable() -> None:
    global enabled
    enabled = False


def tracing_enabled() -> bool:
    return enabled
