"""Hot-path instrumentation hooks.

Every training/serving hot path (pipeline engine, predictor, generate,
dataloader, collectives, watchdog) calls into THIS module instead of
touching the registry or the profiler collector directly, so the
disabled-path cost is one module-attribute read (``hooks.enabled``) per
call site — no allocation, no string formatting, no lock (the contract
ISSUE telemetry demands and ``tools/check_instrumentation.py`` lints).

Two independent switches feed two sinks:

- ``enabled`` (set via :func:`enable`/:func:`disable`, or the
  ``PADDLE_TPU_METRICS=1`` env at import): metric emission into
  :data:`paddle_tpu.observability.metrics.REGISTRY`.
- the profiler collector's RECORD state: span emission. :func:`span`
  returns a shared ``nullcontext`` singleton when neither is active, so
  an un-profiled step allocates nothing.

Spans emitted inside a ``jax.jit`` trace measure TRACE time (they fire
once per compile, not per execution) — device time lives in the
jax.profiler xplane tier. Host-loop spans (eager pipeline fallback,
generate called eagerly, dataloader) measure real wall time.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time

from ..profiler.profiler import RecordEvent, _Event, _collector
from . import metrics as _m
from . import tracing as _tr

#: module-global fast-path flag — call sites read this directly
enabled = os.environ.get("PADDLE_TPU_METRICS", "").lower() in (
    "1", "true", "yes", "on")

_NULL = contextlib.nullcontext()  # shared: the disabled span() result


def enable():
    """Turn metric emission on (idempotent)."""
    global enabled
    enabled = True


def disable():
    global enabled
    enabled = False


def metrics_enabled() -> bool:
    return enabled


def active() -> bool:
    """True when ANY sink wants events (metrics on, or profiler
    RECORDing) — the guard for instrumentation that must time work."""
    return enabled or _collector.enabled


def span(name: str, event_type: str = "UserDefined"):
    """Context manager for a host span; a shared no-op unless the
    profiler collector is recording (spans feed ONLY the collector —
    metrics-enabled alone must not pay the RecordEvent allocation)."""
    if not _collector.enabled:
        return _NULL
    return RecordEvent(name, event_type)


def _record(name: str, start_ns: int, end_ns: int, event_type: str):
    """Append a closed span to the profiler collector (if recording)."""
    if _collector.enabled:
        _collector.add(_Event(name, start_ns, end_ns,
                              threading.get_ident(), event_type))


def _block(x):
    """Fence on device values so a span measures compute, not dispatch.
    No-op for tracers (instrumented code running under jit)."""
    try:
        import jax
        jax.block_until_ready(x)
    except Exception:
        pass


# ---------------- pipeline engine ----------------

def pp_step(schedule: str, pp: int, micro: int, num_chunks: int = 1):
    """One pipeline step: bubble-ratio gauge + step/microbatch counters.

    Bubble ratio is the schedule's theoretical fill fraction lost to
    pipeline bubbles: (pp-1)/(M*chunks + pp - 1) for the wavefront
    family (GPipe/1F1B; interleave divides by the chunk count), ~0 for
    zero-bubble, and (pp-1)/pp for the de-pipelined accumulation
    fallback (no overlap at all).
    """
    if not enabled:
        return
    if schedule == "accum":
        bubble = (pp - 1) / pp if pp > 1 else 0.0
    elif schedule == "zero_bubble":
        bubble = 0.0
    else:
        denom = micro * max(1, num_chunks) + pp - 1
        bubble = (pp - 1) / denom if denom > 0 else 0.0
    _m.gauge("pp_bubble_ratio",
             "theoretical pipeline bubble fraction of the last step",
             ("schedule",)).labels(schedule).set(bubble)
    _m.counter("pp_steps_total", "pipeline forward_backward steps",
               ("schedule",)).labels(schedule).inc()
    _m.counter("pp_microbatches_total",
               "microbatches consumed by the pipeline engine").inc(micro)


# ---------------- serving ----------------

def generate_begin() -> int:
    """Phase-timing anchor; 0 when no sink is active (callers skip)."""
    if not (enabled or _collector.enabled):
        return 0
    return time.perf_counter_ns()


def generate_phase(phase: str, t0_ns: int, out, tokens: int) -> int:
    """Close a generate() phase opened at ``t0_ns``: fence ``out``,
    record the span, feed the phase histogram + token counter. Returns a
    fresh anchor for the next phase."""
    if not t0_ns:
        return 0
    _block(out)
    now = time.perf_counter_ns()
    _record(f"Generate.{phase}", t0_ns, now, "Forward")
    if enabled:
        secs = (now - t0_ns) / 1e9
        _m.histogram(f"generate_{phase}_seconds",
                     f"wall seconds per generate() {phase} phase"
                     ).observe(secs)
        _m.counter("generate_tokens_total",
                   "tokens processed by generate()",
                   ("phase",)).labels(phase).inc(tokens)
        if phase == "decode" and secs > 0:
            _m.gauge("generate_decode_tokens_per_sec",
                     "decode throughput of the last generate() call"
                     ).set(tokens / secs)
    return time.perf_counter_ns()


def predictor_run(t0_ns: int, batch: int):
    """Close a Predictor.run span: latency histogram + request counter."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Predictor.run", t0_ns, now, "Forward")
    if enabled:
        _m.histogram("inference_run_seconds",
                     "Predictor.run wall seconds").observe(
            (now - t0_ns) / 1e9)
        _m.counter("inference_requests_total",
                   "Predictor.run calls").inc()
        if batch:
            _m.counter("inference_samples_total",
                       "samples served by Predictor.run").inc(batch)


# ---------------- continuous-batching serving ----------------

def serving_admitted(n: int, prompt_tokens: int):
    """A FRESH request entered a decode slot (admission counter +
    prefill token counter). Preemption resumes re-enter through
    ``serving_resumed`` instead, so drained occupancy satisfies
    ``admissions - evictions == 0`` (resumes == preemptions cancel
    out)."""
    if not enabled:
        return
    _m.counter("serving_admissions_total",
               "requests admitted into decode slots").inc(n)
    _m.counter("serving_prefill_tokens_total",
               "prompt tokens prefilled into the paged cache"
               ).inc(prompt_tokens)


def serving_prefix(hit_tokens: int, miss_tokens: int):
    """One admission's prefix-cache outcome: ``hit`` tokens were mapped
    from already-prefilled shared pages (zero prefill FLOPs, zero fresh
    KV HBM), ``miss`` tokens go through chunked prefill. The ratio is
    the live prefix-cache hit rate — the multiplier on the
    shared-system-prompt serving win."""
    if not enabled:
        return
    _m.counter("serving_prefix_hit_tokens_total",
               "prompt tokens served from shared prefix pages"
               ).inc(hit_tokens)
    _m.counter("serving_prefix_miss_tokens_total",
               "prompt tokens that required fresh prefill"
               ).inc(miss_tokens)


def serving_prefill_chunk(t0_ns: int, out, tokens: int):
    """Close one chunked-prefill step opened at ``t0_ns`` (a
    :func:`generate_begin` anchor): fence ``out``, feed the per-chunk
    latency histogram — the engine's per-step latency bound — plus the
    chunk-size counter."""
    if not t0_ns:
        return
    _block(out)
    now = time.perf_counter_ns()
    _record("Serving.prefill_chunk", t0_ns, now, "Forward")
    if enabled:
        _m.histogram("serving_prefill_chunk_ms",
                     "wall milliseconds per chunked-prefill step",
                     buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250,
                              500, 1000, 2500)).observe(
            (now - t0_ns) / 1e6)
        _m.counter("serving_prefill_chunk_tokens_total",
                   "prompt tokens prefilled via chunked prefill"
                   ).inc(tokens)


def serving_cancelled(n: int, reason: str):
    """A request was cancelled while QUEUED — it never held a slot or
    pages (e.g. the scheduler's ``deadline_exceeded``), so it must not
    count as an eviction: admissions - evictions is an occupancy
    derivation and would go negative."""
    if not enabled:
        return
    _m.counter("serving_cancellations_total",
               "queued requests cancelled before admission (never held "
               "a slot)", ("reason",)).labels(reason).inc(n)


def serving_retired(n: int, reason: str):
    """A request left its slot and recycled its pages; ``reason`` is a
    structured finish reason (``eos`` / ``max_len`` /
    ``deadline_exceeded`` / other cancellations of RUNNING requests —
    queued-request cancellations count in
    ``serving_cancellations_total`` instead)."""
    if not enabled:
        return
    _m.counter("serving_evictions_total",
               "requests retired from decode slots",
               ("reason",)).labels(reason).inc(n)


def serving_preempted(n: int, pages_freed: int):
    """A running request's pages were evicted back to the pool to make
    room for a higher-priority admission (it will resume token-
    identically later). ``pages_freed`` counts pages that actually
    reached the free list — trie-shared pages survive elsewhere."""
    if not enabled:
        return
    _m.counter("serving_preemptions_total",
               "requests preempted (pages evicted for higher-priority "
               "admissions)").inc(n)
    _m.counter("serving_preempt_pages_freed_total",
               "pages returned to the pool by preemption evictions"
               ).inc(pages_freed)


def serving_resumed(n: int, replay_tokens: int):
    """A preempted request re-entered a slot; ``replay_tokens`` is the
    continuation-prefill work its eviction cost (tokens re-forwarded —
    prefix-trie survivors subtract from it)."""
    if not enabled:
        return
    _m.counter("serving_resumes_total",
               "preempted requests resumed into decode slots").inc(n)
    _m.counter("serving_resume_replay_tokens_total",
               "tokens re-prefilled by preemption resumes"
               ).inc(replay_tokens)


def serving_spec_verify(t0_ns: int, out, rows: int, drafted: int,
                        accepted: int, t1_ns: int = 0):
    """Close one speculative-decode verify step opened at ``t0_ns`` (a
    :func:`generate_begin` anchor): fence the verify output, record the
    span, and feed the speculation counters — drafted/accepted token
    totals, the rejected-tail rollback counter, and the per-step
    acceptance-rate histogram (the quantity the adaptive per-row k is
    driven by; its EMA is observable as accepted/drafted over any
    scrape window). ``rows`` is the number of slots the verify
    advanced. ``t1_ns``: the caller's own device-fence timestamp —
    the engine materializes the verify output (a host np.asarray sync)
    and only then runs its per-slot commit loop before reaching this
    hook, so the span must close at that fence, not at call time, or
    the histogram would charge the host loop to the device."""
    if not t0_ns:
        return
    _block(out)
    now = t1_ns or time.perf_counter_ns()
    _record("Serving.spec_verify", t0_ns, now, "Forward")
    if not enabled:
        return
    _m.histogram("serving_spec_verify_ms",
                 "wall milliseconds per speculative verify step",
                 buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000, 2500)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_spec_steps_total",
               "speculative verify steps executed").inc()
    _m.counter("serving_spec_rows_total",
               "slots advanced through the verify program").inc(rows)
    _m.counter("serving_spec_drafted_tokens_total",
               "draft tokens proposed to the verify program"
               ).inc(drafted)
    _m.counter("serving_spec_accepted_tokens_total",
               "draft tokens accepted by greedy verification"
               ).inc(accepted)
    _m.counter("serving_spec_rollback_tokens_total",
               "rejected draft tokens whose KV rows were rolled back "
               "(length bookkeeping, no copy)").inc(drafted - accepted)
    if drafted:
        _m.histogram("serving_spec_acceptance_rate",
                     "accepted/drafted ratio per verify step",
                     buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                              0.875, 1.0)).observe(accepted / drafted)


def serving_tp_allgather(nbytes: int):
    """One tensor-parallel serving all-gather in a TRACED program
    (models/generate._tp_allgather). Like :func:`collective`, this
    fires at TRACE time — the counters report the number of collectives
    (and per-shard payload bytes) in each COMPILED serving program, once
    per compile, which is exactly the per-step collective bill of the
    tp decode/prefill/verify path."""
    if not enabled:
        return
    _m.counter("serving_tp_allgather_calls_total",
               "all-gather collectives traced into tp serving programs"
               ).inc()
    _m.counter("serving_tp_allgather_bytes_total",
               "per-shard payload bytes of traced tp serving all-gathers"
               ).inc(nbytes)


def serving_tp_step(tp: int, pages_used: int, pages_total: int):
    """One tp-sharded engine step: per-shard pool-utilization gauge.
    Block tables and the allocator are REPLICATED across the mesh (same
    page ids everywhere), so every shard's utilization is identical by
    construction — the per-shard labels make that invariant observable
    (a divergence would be a sharding bug) and give dashboards the
    per-shard HBM view (each shard holds 1/tp of the pool bytes)."""
    if not enabled:
        return
    g = _m.gauge("serving_tp_pool_utilization",
                 "paged-pool utilization per tp shard (replicated "
                 "tables: all shards identical by construction)",
                 ("shard",))
    util = pages_used / max(pages_total, 1)
    for s in range(tp):
        g.labels(str(s)).set(util)
    _m.gauge("serving_tp_shards",
             "tp mesh size of the serving engine").set(tp)


def serving_tp_logits_gather(t0_ns: int, out):
    """Close one timed logits-collective probe (a dedicated jitted
    all-gather of a logits-shard-sized array over the serving mesh,
    run periodically by the engine): the latency histogram of the ONE
    cross-shard collective the tp decode step ends with. Probed in
    isolation because the fused step program cannot attribute its own
    collective time from the host."""
    if not t0_ns:
        return
    _block(out)
    now = time.perf_counter_ns()
    _record("Serving.tp_logits_gather", t0_ns, now, "Communication")
    if enabled:
        _m.histogram("serving_tp_logits_gather_ms",
                     "wall milliseconds per probed logits all-gather "
                     "over the serving tp mesh",
                     buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25,
                              50, 100)).observe((now - t0_ns) / 1e6)


def serving_dp_step(dp: int, batches):
    """One 2-D-mesh engine step (ISSUE 17): per-dp-shard batch gauge.
    ``batches`` maps dp shard index -> decode rows the scheduler
    assigned that shard this step (the planner balances within each
    priority class, so a persistent skew here is a planning bug made
    observable, the serving_tp_step idiom applied to the second
    axis)."""
    if not enabled:
        return
    g = _m.gauge("serving_dp_batch_rows",
                 "decode rows per dp shard in the last 2-D-mesh step",
                 ("shard",))
    for s in range(dp):
        g.labels(str(s)).set(batches.get(s, 0) if hasattr(batches, "get")
                             else batches[s])
    _m.gauge("serving_dp_shards",
             "dp mesh size of the serving engine").set(dp)


def serving_moe_dispatch(nbytes: int, routed: int):
    """One expert-parallel MoE dispatch traced into a serving program
    (models/generate._moe_ffn): the all-to-all pair that ships routed
    token copies to their experts' owner shards and the outputs back.
    Fires at TRACE time (the :func:`serving_tp_allgather` contract) —
    once per compile per layer, reporting the compiled program's
    per-step collective bill; the routed-tokens histogram records the
    static item count (tokens x top_k) each dispatch carries."""
    if not enabled:
        return
    _m.counter("serving_moe_dispatch_calls_total",
               "expert-parallel all-to-all dispatches traced into "
               "serving programs").inc()
    _m.counter("serving_moe_dispatch_bytes_total",
               "per-shard payload bytes of traced MoE all-to-all "
               "dispatches (tokens there + outputs back)").inc(nbytes)
    _m.histogram("serving_moe_routed_tokens",
                 "routed token copies (tokens x top_k) per traced MoE "
                 "dispatch",
                 buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)
                 ).observe(routed)


def serving_queue_wait(seconds: float, priority: int):
    """One admission's time-in-queue (scheduler submit -> slot), by
    priority class — the SLO the scheduler exists to bound."""
    if not enabled:
        return
    _m.histogram("serving_time_in_queue_seconds",
                 "seconds from scheduler submit to slot admission",
                 ("priority",),
                 buckets=(0.001, 0.005, 0.025, 0.1, 0.5, 1, 2.5, 5, 10,
                          30, 60, 120)).labels(str(int(priority))
                                               ).observe(seconds)


def serving_sched_step(queue_depths, scheduled_tokens: int, budget):
    """One scheduler step: per-class queue-depth gauges + the
    budget-utilization gauge (skipped when no budget is configured).
    ``queue_depths`` maps priority class -> queued requests; classes
    that have EVER queued keep reporting (a depth that drops to zero
    must overwrite the stale gauge, not vanish)."""
    if not enabled:
        return
    g = _m.gauge("serving_queue_depth",
                 "queued requests awaiting admission, by priority class",
                 ("priority",))
    for prio, depth in queue_depths.items():
        g.labels(str(int(prio))).set(depth)
    _m.counter("serving_sched_steps_total",
               "SLO-scheduler steps planned").inc()
    _m.counter("serving_sched_tokens_total",
               "tokens scheduled by the step planner (decode slots + "
               "prefill-chunk widths)").inc(scheduled_tokens)
    if budget:
        _m.gauge("serving_step_budget_utilization",
                 "fraction of the per-step token budget the planner "
                 "scheduled").set(scheduled_tokens / budget)


def serving_overlap_step(exposed_ns: int, wall_ns: int, committed: int,
                         overlap: bool):
    """One scheduler step's host-plane attribution (ISSUE 12 — the
    async overlapped runtime's scoreboard). ``exposed_ns`` is the host
    bookkeeping time NOT hidden under an in-flight device program
    (wall minus commit-fence device waits minus the planning phase
    when it ran under an in-flight step); the ratio against the step's
    wall time is the ``serving_host_overhead_fraction`` gauge —
    measurably lower with ``overlap=True``, because expire/admit/plan
    then runs while the device executes. ``serving_sched_step_ms``
    (per-step wall latency, the p99 source) and the per-mode step
    counter ride alongside so sync-vs-overlap comparisons need no
    external clock."""
    if not enabled:
        return
    _m.gauge("serving_host_overhead_fraction",
             "fraction of the last scheduler step's wall time spent "
             "on exposed host-plane work (not hidden under an "
             "in-flight device program)").set(
        min(1.0, exposed_ns / max(1, wall_ns)))
    _m.histogram("serving_sched_step_ms",
                 "wall milliseconds per scheduler step (plan + "
                 "dispatch + commit)",
                 ("mode",),
                 buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                          250, 1000)).labels(
        "overlap" if overlap else "sync").observe(wall_ns / 1e6)
    _m.counter("serving_overlap_steps_total",
               "scheduler steps by execution mode",
               ("mode",)).labels(
        "overlap" if overlap else "sync").inc()
    if committed:
        _m.counter("serving_overlap_committed_total",
                   "units (tokens/slots/chunks) committed at step "
                   "commit fences").inc(committed)


def serving_sched_idle(fenced: bool):
    """A scheduler step planned zero tokens and committed nothing —
    all remaining work waits on device or swap completion. The run
    loop FENCED in-flight work (or yielded when there was nothing to
    fence) instead of busy-spinning through another empty
    expire/admit/plan pass (ISSUE 12 bugfix)."""
    if not enabled:
        return
    _m.counter("serving_sched_idle_steps_total",
               "zero-work scheduler steps resolved by fence or yield "
               "instead of re-planning",
               ("action",)).labels("fence" if fenced else "yield").inc()


def serving_fault(site: str, kind: str, injected: bool):
    """One serving fault, classified by hot-path site
    (:data:`paddle_tpu.serving.resilience.SITES`) and kind (the
    injector's mode, or the caught exception's class name). Injected
    faults (the deterministic :class:`FaultInjector`) and real ones
    keep SEPARATE counters — a chaos soak must be able to prove its
    faults were all its own."""
    if not enabled:
        return
    if injected:
        _m.counter("serving_fault_injected_total",
                   "faults fired by the deterministic fault injector",
                   ("site", "kind")).labels(site, kind).inc()
    else:
        _m.counter("serving_fault_failures_total",
                   "real (non-injected) serving step failures the "
                   "supervisor caught", ("site", "kind")
                   ).labels(site, kind).inc()


def serving_fault_recovery(t0_ns: int, sessions: int,
                           replay_tokens: int):
    """Close one supervisor recovery opened at ``t0_ns`` (a
    :func:`generate_begin` anchor): teardown + pool rebuild + journal
    restore. ``replay_tokens`` is the continuation-prefill bill the
    restored sessions will pay (prompt + committed tokens minus one,
    per admitted session) — the recovery-cost model's x-axis
    (PERF_NOTES: recovery time ∝ resident tokens)."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.fault_recovery", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_fault_recovery_ms",
                 "wall milliseconds per engine teardown+rebuild+restore",
                 buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                          2500, 5000)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_fault_recoveries_total",
               "engine teardown+rebuild recoveries").inc()
    _m.counter("serving_fault_restored_sessions_total",
               "in-flight sessions restored through the resume replay"
               ).inc(sessions)
    _m.counter("serving_fault_replay_tokens_total",
               "tokens scheduled for re-prefill by crash recoveries"
               ).inc(replay_tokens)


def serving_degraded(level: int):
    """The supervisor's degraded-mode rung (0 = healthy, 1 = spec
    decode off, 2 = one-page prefill chunks, 3 = LOW admissions shed;
    one past the ladder = circuit open / dead) — the replica-health
    gauge a multi-engine router steers by."""
    if not enabled:
        return
    _m.gauge("serving_degraded_mode",
             "degraded-mode ladder rung of the engine supervisor "
             "(0 healthy .. 3 shed_low; 4 = circuit open)"
             ).set(level)


def serving_journal(entries: int, tokens: int):
    """Write-ahead request-journal size after a committed step: live
    entries and their resident tokens (prompt + committed) — the
    recovery bill if the engine died right now."""
    if not enabled:
        return
    _m.gauge("serving_fault_journal_entries",
             "live requests in the supervisor's write-ahead journal"
             ).set(entries)
    _m.gauge("serving_fault_journal_tokens",
             "resident tokens (prompt + committed) the journal would "
             "replay on a crash").set(tokens)


def serving_drain_checkpoint(t0_ns: int, nbytes: int, sessions: int,
                             trie_pages: int):
    """Close one engine drain opened at ``t0_ns``: checkpoint latency
    histogram + size gauges (bytes on disk, sessions checkpointed,
    prefix-trie pages persisted)."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.drain_checkpoint", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_drain_checkpoint_ms",
                 "wall milliseconds per drain checkpoint write",
                 buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                          2500, 5000)).observe((now - t0_ns) / 1e6)
    _m.gauge("serving_drain_checkpoint_bytes",
             "size of the last drain checkpoint on disk").set(nbytes)
    _m.counter("serving_drain_sessions_total",
               "in-flight sessions checkpointed by drains"
               ).inc(sessions)
    _m.counter("serving_drain_trie_pages_total",
               "prefix-trie pages persisted by drains").inc(trie_pages)


def serving_drain_restore(t0_ns: int, nbytes: int, sessions: int,
                          trie_pages: int):
    """Close one drain-checkpoint restore opened at ``t0_ns``: restore
    latency histogram + size gauges (the other half of the
    ``serving_drain_*`` pair — restarts are observable end to end)."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.drain_restore", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_drain_restore_ms",
                 "wall milliseconds per drain-checkpoint restore",
                 buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                          2500, 5000)).observe((now - t0_ns) / 1e6)
    _m.gauge("serving_drain_restore_bytes",
             "size of the last restored drain checkpoint").set(nbytes)
    _m.counter("serving_drain_restored_sessions_total",
               "sessions restored from drain checkpoints").inc(sessions)
    _m.counter("serving_drain_restored_trie_pages_total",
               "prefix-trie pages restored from drain checkpoints"
               ).inc(trie_pages)


# ---------------- durable journal plane (ISSUE 15) ----------------

def serving_wal_append(t0_ns: int, nbytes: int):
    """One CRC-framed record appended to the on-disk write-ahead
    journal: append counter + bytes counter + latency histogram — the
    per-record half of the fsync-ladder overhead model (PERF_NOTES
    'Durability')."""
    if not enabled:
        return
    _m.counter("serving_wal_appends_total",
               "records appended to the durable request journal").inc()
    _m.counter("serving_wal_bytes_total",
               "bytes appended to the durable request journal"
               ).inc(nbytes)
    _m.histogram("serving_wal_append_ms",
                 "wall milliseconds per WAL record append",
                 buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5,
                          5, 10, 25)).observe(
        (time.perf_counter_ns() - t0_ns) / 1e6)


def serving_wal_fsync(t0_ns: int):
    """One WAL fsync (per-commit policy: every append; group policy:
    amortized over the group-commit window): counter + latency
    histogram — the dominant term of the durability tax."""
    if not enabled:
        return
    _m.counter("serving_wal_fsyncs_total",
               "fsyncs issued by the durable request journal").inc()
    _m.histogram("serving_wal_fsync_ms",
                 "wall milliseconds per WAL fsync",
                 buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50,
                          100)).observe(
        (time.perf_counter_ns() - t0_ns) / 1e6)


def serving_wal_checkpoint(t0_ns: int, nbytes: int, sessions: int,
                           segments_pruned: int):
    """One incremental WAL checkpoint (snapshot written atomically,
    covered log segments pruned — admissions never stopped): latency
    histogram + size gauge + sessions/pruned-segment counters."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.wal_checkpoint", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_wal_checkpoint_ms",
                 "wall milliseconds per incremental WAL checkpoint",
                 buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000)).observe((now - t0_ns) / 1e6)
    _m.gauge("serving_wal_checkpoint_bytes",
             "size of the last incremental WAL checkpoint").set(nbytes)
    _m.counter("serving_wal_checkpoints_total",
               "incremental WAL checkpoints written").inc()
    _m.counter("serving_wal_checkpoint_sessions_total",
               "live sessions snapshotted by WAL checkpoints"
               ).inc(sessions)
    _m.counter("serving_wal_segments_pruned_total",
               "log segments compacted away by WAL checkpoints"
               ).inc(segments_pruned)


def serving_wal_recovery(t0_ns: int, sessions: int, records: int,
                         torn_frames: int, quarantined: int):
    """One cold-restart recovery from the durable journal
    (:meth:`~paddle_tpu.serving.EngineSupervisor.recover_from_disk`):
    recovery latency histogram, the recovery-replay gauge (sessions a
    dead process's journal brought back) and the media-fault counters
    — a torn tail truncated or a corrupt segment/checkpoint
    quarantined is an absorbed fault, and absorbed faults must be
    countable."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.wal_recovery", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_wal_recovery_ms",
                 "wall milliseconds per cold-restart WAL recovery",
                 buckets=(1, 2.5, 5, 10, 25, 50, 100, 250, 500, 1000,
                          2500, 5000)).observe((now - t0_ns) / 1e6)
    _m.gauge("serving_wal_recovered_sessions",
             "live sessions replayed by the last cold-restart "
             "recovery").set(sessions)
    _m.counter("serving_wal_replayed_records_total",
               "WAL records folded by cold-restart recoveries"
               ).inc(records)
    _m.counter("serving_wal_torn_frames_total",
               "torn WAL tails truncated at the last valid frame"
               ).inc(torn_frames)
    _m.counter("serving_wal_quarantined_total",
               "corrupt WAL segments/checkpoints quarantined during "
               "recovery").inc(quarantined)


# ---------------- hierarchical KV tier (ISSUE 10) ----------------

def serving_swap_out(t0_ns: int, nbytes: int, pages: int):
    """Close one preemption SWAP-OUT opened at ``t0_ns``: the victim's
    live KV pages gathered device→host before its device pages freed.
    Latency histogram + bytes/pages counters — the 'bytes moved' half
    of the swap-vs-replay crossover model (PERF_NOTES)."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.swap_out", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_swap_out_ms",
                 "wall milliseconds per preemption swap-out gather",
                 buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_swap_outs_total",
               "preemption victims swapped out to the host tier").inc()
    _m.counter("serving_swap_out_bytes_total",
               "KV bytes moved device→host by swap-outs").inc(nbytes)
    _m.counter("serving_swap_pages_total",
               "KV pages moved through the host tier",
               ("direction",)).labels("out").inc(pages)


def serving_swap_in(t0_ns: int, nbytes: int, pages: int):
    """Close one resume SWAP-IN opened at ``t0_ns``: fresh pages
    allocated and the host payload scattered back (the shared donated
    ``_pool_scatter``) — the resume that replaces the ``O(resident
    tokens)`` replay prefill. Latency histogram + bytes/pages
    counters."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.swap_in", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_swap_in_ms",
                 "wall milliseconds per resume swap-in scatter",
                 buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_swap_ins_total",
               "preempted requests resumed by host-tier swap-in").inc()
    _m.counter("serving_swap_in_bytes_total",
               "KV bytes moved host→device by swap-ins").inc(nbytes)
    _m.counter("serving_swap_pages_total",
               "KV pages moved through the host tier",
               ("direction",)).labels("in").inc(pages)


def serving_swap_fallback():
    """A resume found no (valid) host payload — LRU capacity drop or a
    stale length — and fell back to the replay-prefill path. The
    fallback rate is the honest cost of bounding host-tier RAM."""
    if not enabled:
        return
    _m.counter("serving_swap_replay_fallbacks_total",
               "swap-in resumes that fell back to replay prefill "
               "(payload dropped or stale)").inc()


def serving_host_pool(pages: int, nbytes: int, capacity):
    """Host-tier residency gauges after a store mutation: pages/bytes
    resident in host RAM, plus occupancy against the configured page
    capacity (skipped when unbounded)."""
    if not enabled:
        return
    _m.gauge("serving_host_pool_pages",
             "KV pages resident in the host-RAM tier").set(pages)
    _m.gauge("serving_host_pool_bytes",
             "KV bytes resident in the host-RAM tier").set(nbytes)
    if capacity:
        _m.gauge("serving_host_pool_utilization",
                 "host-tier page residency over its configured "
                 "capacity").set(pages / capacity)


def serving_host_disk_pruned(files: int, bytes_total: int):
    """Standing-store files removed by the ``max_disk_bytes`` bound
    (ISSUE 15 satellite — LRU-by-mtime pruning so long-running engines
    don't grow ``artifacts/`` without limit): pruned-file counter +
    lifetime pruned-bytes gauge, next to the corrupt-unlink counter so
    capacity pruning and quarantine stay distinguishable."""
    if not enabled:
        return
    _m.counter("serving_host_disk_pruned_total",
               "standing-store files pruned by the disk byte bound"
               ).inc(files)
    _m.gauge("serving_host_disk_pruned_bytes",
             "lifetime bytes pruned from the standing disk store"
             ).set(bytes_total)


def serving_prefix_demoted(pages: int):
    """Prefix-trie pages DEMOTED to the host tier under pool pressure
    (instead of dying with their eviction) — each is a candidate for a
    later promote hit."""
    if not enabled:
        return
    _m.counter("serving_prefix_demoted_pages_total",
               "prefix-trie pages demoted to the host tier on "
               "eviction").inc(pages)


def serving_prefix_promoted(t0_ns: int, pages: int):
    """Close one prefix PROMOTION opened at ``t0_ns``: demoted (or
    standing-store-persisted) chain pages scattered back into the pool
    and re-registered, converting what would have been a prefill miss
    into a prefix HIT — the demoted-trie promote hit counter."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.prefix_promote", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_prefix_promote_ms",
                 "wall milliseconds per host→pool prefix promotion",
                 buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_prefix_promoted_pages_total",
               "prefix pages promoted back from the host tier "
               "(demote/persist hits)").inc(pages)


# ---------------- multi-tenant adapter plane (ISSUE 14) ----------------

def serving_adapter_slots(used: int, capacity: int, pinned: int):
    """Adapter-pool residency gauges after a slot mutation: slots
    holding a loaded adapter, the configured slot capacity, and how
    many resident adapters are currently pinned by running rows — the
    occupancy picture the multi-LoRA admission path breathes by."""
    if not enabled:
        return
    _m.gauge("serving_adapter_slots_used",
             "adapter-pool slots holding a loaded adapter").set(used)
    _m.gauge("serving_adapter_slots_capacity",
             "configured adapter-pool slot capacity").set(capacity)
    _m.gauge("serving_adapter_slots_pinned",
             "resident adapters pinned by running requests").set(pinned)


def serving_adapter_load(t0_ns: int, nbytes: int, promoted: bool):
    """Close one adapter slot install opened at ``t0_ns``: packed
    factors written into a pool slot (one donated device program).
    ``promoted`` splits host-store promotions (the demoted/persisted
    copy came back) from fresh registry loads — the hit economy of the
    adapter tier, same shape as the prefix demote/promote pair."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.adapter_load", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_adapter_load_ms",
                 "wall milliseconds per adapter slot install",
                 buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_adapter_loads_total",
               "adapter slot installs, by source",
               ("source",)).labels(
        "promote" if promoted else "load").inc()
    _m.counter("serving_adapter_load_bytes_total",
               "packed factor bytes installed into adapter slots"
               ).inc(nbytes)


def serving_adapter_demoted(nbytes: int):
    """One cold adapter DEMOTED to the host tier on LRU slot reclaim
    (CRC-stamped packed bytes; a later admission promotes it back
    instead of re-reading the registry)."""
    if not enabled:
        return
    _m.counter("serving_adapter_demotions_total",
               "adapters demoted to the host tier on slot reclaim"
               ).inc()
    _m.counter("serving_adapter_demote_bytes_total",
               "packed factor bytes demoted to the host tier"
               ).inc(nbytes)


def serving_adapter_fallback(site: str):
    """A corrupt/torn demoted adapter payload failed its CRC before
    install: the entry quarantined and the admission fell back to a
    FRESH registry load — counted, never silent (the PR 13 integrity
    discipline on adapter bytes)."""
    if not enabled:
        return
    _m.counter("serving_adapter_fallbacks_total",
               "adapter promotions that fell back to a fresh load "
               "(corrupt/torn payload quarantined)",
               ("site",)).labels(site).inc()


def serving_adapter_gather(nbytes: int):
    """One adapter-augmented serving forward TRACED: the per-step
    factor bytes the compiled program gathers out of the adapter pool
    (per-row A/B slices, all layers). Fires at TRACE time like
    :func:`serving_tp_allgather` — once per compile, which is exactly
    the per-step adapter-bandwidth bill of the multi-LoRA path (the
    PERF_NOTES rank-r bytes/token model reads this)."""
    if not enabled:
        return
    _m.counter("serving_adapter_gather_calls_total",
               "adapter factor gathers traced into serving programs"
               ).inc()
    _m.counter("serving_adapter_gather_bytes_total",
               "per-step adapter factor bytes gathered by traced "
               "serving programs").inc(int(nbytes))


# ---------------- sampled speculation (ISSUE 14) ----------------

def serving_sample_accept(drafted: int, accepted: int):
    """One REJECTION-SAMPLED verify commit: drafted/accepted token
    counters plus the per-step accept-rate histogram — the sampled
    sibling of ``serving_spec_acceptance_rate`` (temperature>0 rows
    accept with probability p(draft), so this rate IS the realized
    1+k·rate speedup multiplier of sampled speculative decode)."""
    if not enabled:
        return
    _m.counter("serving_sample_drafted_total",
               "draft tokens offered to rejection-sampled acceptance"
               ).inc(drafted)
    _m.counter("serving_sample_accepted_total",
               "draft tokens accepted by rejection sampling"
               ).inc(accepted)
    if drafted:
        _m.histogram("serving_sample_accept_rate",
                     "accepted/drafted ratio per rejection-sampled "
                     "verify step",
                     buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                              0.875, 1.0)).observe(accepted / drafted)


# ------- model-based draft + tree speculation (ISSUE 20) -------

def serving_draft_propose(rows: int, tokens: int, catchup: int):
    """One draft-model propose pass: ``rows`` slots drafted ``tokens``
    proposal tokens (linear chain tokens, or tree NODES under tree
    speculation) after ``catchup`` catch-up tokens re-fed through the
    draft forward (zero in steady state; prompt-sized on a cold slot —
    first propose, post-preemption resume, crash recovery, so this
    counter IS the disposable-draft-pool rebuild bill)."""
    if not enabled:
        return
    _m.counter("serving_draft_propose_total",
               "draft-model propose passes").inc()
    _m.counter("serving_draft_rows_total",
               "slots that received draft-model proposals").inc(rows)
    _m.counter("serving_draft_proposed_tokens_total",
               "draft-model proposal tokens (tree nodes under tree "
               "speculation)").inc(tokens)
    _m.counter("serving_draft_catchup_tokens_total",
               "committed-context tokens re-fed through the draft "
               "model to rebuild its disposable pool").inc(catchup)


def serving_draft_pool(pages_used: int, pages_usable: int):
    """Draft paged-pool occupancy after a propose pass — the second
    (small) pool's utilization gauge pair; balanced against its
    allocator after every rejection cascade by construction (proposal
    feeds never allocate; pages move only at admit/release)."""
    if not enabled:
        return
    _m.gauge("serving_draft_pool_pages_used",
             "draft-pool pages currently referenced").set(pages_used)
    _m.gauge("serving_draft_pool_pages_usable",
             "draft-pool pages usable (total minus reserved)"
             ).set(pages_usable)


def serving_tree_verify(t0_ns: int, out, rows: int, nodes: int,
                        accepted: int, paths, t1_ns: int = 0):
    """Close one TREE-speculation verify step opened at ``t0_ns``: the
    whole token tree scored in ONE forward. ``nodes``/``accepted``
    count tree nodes offered vs accepted along the committed root
    paths; ``paths`` is the per-row committed path length (accepted +
    1 — the path-length histogram is the quantity the (width, depth)
    expected-gain model in PERF_NOTES is fit against). Same
    device-fence contract as :func:`serving_spec_verify`."""
    if not t0_ns:
        return
    _block(out)
    now = t1_ns or time.perf_counter_ns()
    _record("Serving.tree_verify", t0_ns, now, "Forward")
    if not enabled:
        return
    _m.histogram("serving_tree_verify_ms",
                 "wall milliseconds per tree-speculation verify step",
                 buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000, 2500)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_tree_steps_total",
               "tree-speculation verify steps executed").inc()
    _m.counter("serving_tree_rows_total",
               "slots advanced through the tree verify program"
               ).inc(rows)
    _m.counter("serving_tree_nodes_total",
               "tree nodes proposed to the verify program").inc(nodes)
    _m.counter("serving_tree_accepted_nodes_total",
               "tree nodes accepted on committed root paths"
               ).inc(accepted)
    h = _m.histogram("serving_tree_path_len",
                     "committed root-path length per row (accepted "
                     "nodes + 1)",
                     buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32))
    for p in paths:
        h.observe(p)
    if nodes:
        _m.histogram("serving_tree_acceptance_rate",
                     "accepted/proposed node ratio per tree verify "
                     "step",
                     buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75,
                              0.875, 1.0)).observe(accepted / nodes)


# ---------------- constrained decoding (ISSUE 14) ----------------

def serving_constrain(mask_ns: int, violations: int, rows: int):
    """One constrained decode commit: the host-side mask
    build/advance latency, the violation-avoided counter (steps where
    the UNCONSTRAINED argmax was grammar-invalid — each one is an
    output the mask saved from a parse failure), and the constrained
    row count."""
    if not enabled:
        return
    _m.histogram("serving_constrain_mask_ms",
                 "wall milliseconds per step of constraint mask "
                 "build + DFA advance",
                 buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5,
                          5, 10, 25)).observe(mask_ns / 1e6)
    _m.counter("serving_constrain_violations_avoided_total",
               "steps whose unconstrained argmax would have violated "
               "the grammar").inc(violations)
    _m.counter("serving_constrain_rows_total",
               "constrained rows advanced through masked sampling"
               ).inc(rows)


# ---------------- fused serving kernels (ISSUE 11) ----------------

def serving_fused_dispatch(kernel: str, bytes_saved: int):
    """One fused-kernel dispatch TRACED into a serving program
    (models/generate's fused decode/chunk/verify branches and the
    paged-cache fused page move). Like :func:`serving_tp_allgather`
    this fires at TRACE time — the counters report the fused launches
    (and the HBM bytes each fusion removes from the hot loop: the
    rotated-q round-trip, the materialized f32 score/prob tensors, the
    host-staged page payload) in each COMPILED program, once per
    compile — exactly the per-step fusion bill. ``bytes_saved`` also
    feeds the per-kernel bytes-saved gauge the PERF_NOTES roofline
    model reads."""
    if not enabled:
        return
    _m.counter("serving_fused_dispatch_total",
               "fused-kernel launches traced into serving programs",
               ("kernel",)).labels(kernel).inc()
    _m.counter("serving_fused_bytes_saved_total",
               "estimated HBM bytes the fused kernels keep out of the "
               "decode hot loop (per traced launch)",
               ("kernel",)).labels(kernel).inc(int(bytes_saved))
    _m.gauge("serving_fused_bytes_saved",
             "estimated HBM bytes saved per launch by each fused "
             "serving kernel", ("kernel",)).labels(kernel).set(
        int(bytes_saved))


def serving_fused_latency(kernel: str, t0_ns: int, out):
    """Close one HOST-timed fused-path step opened at ``t0_ns`` (the
    engine's decode/prefill/verify step with fusion on, or one fused
    page move): blocks on ``out`` so the histogram holds real device
    wall time per kernel — the ``decode_fused_speedup`` bench rider's
    per-kernel breakdown."""
    if not t0_ns:
        return
    _block(out)
    now = time.perf_counter_ns()
    _record(f"Serving.fused.{kernel}", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_fused_step_ms",
                 "wall milliseconds per fused-path serving step",
                 ("kernel",),
                 buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                          250, 1000)).labels(kernel).observe(
        (now - t0_ns) / 1e6)


# ---------------- disaggregated cluster serving (ISSUE 9) ----------------

def serving_router_dispatch(replica: int, affinity_hit: bool):
    """One router dispatch decision: per-replica dispatch counter plus
    the affinity hit/miss split — the live prefix-affinity hit rate
    (hits mean the tenant's system prompt lands on a replica whose trie
    already holds it; misses fall back to least-loaded placement)."""
    if not enabled:
        return
    _m.counter("serving_router_dispatch_total",
               "requests dispatched to engine replicas by the cluster "
               "router", ("replica",)).labels(str(replica)).inc()
    _m.counter("serving_router_affinity_total",
               "prefix-affinity routing outcomes",
               ("outcome",)).labels(
        "hit" if affinity_hit else "miss").inc()


def serving_router_retry(n: int = 1):
    """A request a degraded replica shed (``rejected_overload``) was
    re-dispatched to the healthiest replica before surfacing the
    rejection to the caller — the router-level retry of shed work."""
    if not enabled:
        return
    _m.counter("serving_router_retries_total",
               "shed requests re-dispatched to a healthier replica"
               ).inc(n)


def serving_router_ratelimited(tenant: str):
    """A submission exceeded its tenant's token quota and finished
    ``rejected_ratelimit`` without touching any replica."""
    if not enabled:
        return
    _m.counter("serving_router_ratelimited_total",
               "submissions rejected by per-tenant rate limits",
               ("tenant",)).labels(tenant).inc()


def serving_router_failover(sessions: int):
    """A replica left service (circuit open, or a rolling-upgrade
    drain) and the router rehomed its live sessions onto surviving
    replicas — counted per event, with the rehomed-session total
    alongside (zero lost requests is the gate)."""
    if not enabled:
        return
    _m.counter("serving_router_failovers_total",
               "replica exits (death or retirement) the router "
               "rehomed sessions from").inc()
    _m.counter("serving_router_rehomed_sessions_total",
               "live sessions re-dispatched off dead or retiring "
               "replicas").inc(sessions)


def serving_router_replica(replica: int, queued: int, occupancy: float,
                           degraded_level: int):
    """One replica's published load signals, refreshed each cluster
    step: queue depth, paged-pool occupancy and the degraded-mode rung
    — the registry-side mirror of ``ServingScheduler.load_stats()``
    (the router reads the structured API; dashboards read these)."""
    if not enabled:
        return
    _m.gauge("serving_replica_queue_depth",
             "queued requests per engine replica",
             ("replica",)).labels(str(replica)).set(queued)
    _m.gauge("serving_replica_pool_occupancy",
             "paged-pool occupancy per engine replica",
             ("replica",)).labels(str(replica)).set(occupancy)
    _m.gauge("serving_replica_degraded_mode",
             "degraded-mode ladder rung per engine replica",
             ("replica",)).labels(str(replica)).set(degraded_level)


def serving_handoff_export(t0_ns: int, nbytes: int, pages: int):
    """Close one prefill→decode KV export opened at ``t0_ns`` (a
    :func:`generate_begin` anchor): latency histogram + bytes/pages
    counters — the numerator of the handoff cost model (page bytes
    moved vs the replay-prefill FLOPs they replace; PERF_NOTES)."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.handoff_export", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_handoff_export_ms",
                 "wall milliseconds per prefill-side KV export",
                 buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_handoff_exports_total",
               "prefill→decode KV handoffs exported").inc()
    _m.counter("serving_handoff_bytes_total",
               "KV bytes moved by prefill→decode handoffs").inc(nbytes)
    _m.counter("serving_handoff_pages_total",
               "KV pages moved by prefill→decode handoffs").inc(pages)


def serving_handoff_import(t0_ns: int):
    """Close one decode-side KV import (allocate + donated scatter)
    opened at ``t0_ns`` — the latency half of the other side of the
    ``serving_handoff_*`` pair. Bytes/pages are counted ONCE, at
    export (:func:`serving_handoff_export`): a successful handoff
    moves each byte exactly once, so a second counter here would
    double the cost model's numerator."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.handoff_import", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.histogram("serving_handoff_import_ms",
                 "wall milliseconds per decode-side KV import scatter",
                 buckets=(0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
                          1000)).observe((now - t0_ns) / 1e6)
    _m.counter("serving_handoff_imports_total",
               "prefill→decode KV handoffs imported").inc()


def serving_router_retry_exhausted():
    """A shed request exhausted its per-request retry budget (or its
    tenant's retry-rate cap) and the rejection surfaced to the caller —
    counted SEPARATELY from first-try rejection so overload dashboards
    can tell 'the cluster is full' from 'one replica is degraded and
    retries are amplifying' (ISSUE 13 satellite)."""
    if not enabled:
        return
    _m.counter("serving_router_retry_exhausted_total",
               "shed requests whose retry budget or tenant retry-rate "
               "cap ran out before a replica accepted them").inc()


# ---------------- overload & SLO (ISSUE 13) ----------------

def serving_slo_rejected(tenant: str):
    """The admission controller rejected a submission at the cluster
    door because its deadline was infeasible against current backlog
    (``rejected_infeasible``) — shed BEFORE any replica pays queueing
    or prefill for a request that could never meet its SLO."""
    if not enabled:
        return
    _m.counter("serving_slo_rejected_infeasible_total",
               "submissions rejected at admission as deadline-"
               "infeasible", ("tenant",)).labels(tenant).inc()


def serving_slo_ttft(ttft_s: float, met: bool, priority: int):
    """One request's time-to-first-token under the trace-driven
    harness (virtual-clock seconds from arrival to first committed
    token), with its deadline outcome — the p99 TTFT and
    deadline-met-fraction sources of the goodput-under-SLO tier."""
    if not enabled:
        return
    _m.histogram("serving_slo_ttft_ms",
                 "milliseconds from arrival to first token under the "
                 "traffic harness", ("priority",),
                 buckets=(1, 5, 10, 25, 50, 100, 250, 500, 1000, 2500,
                          5000, 10000)).labels(
        str(int(priority))).observe(ttft_s * 1e3)
    _m.counter("serving_slo_deadline_total",
               "requests by deadline outcome under the traffic harness",
               ("outcome",)).labels("met" if met else "missed").inc()


def serving_slo_tokens(n: int, met: bool):
    """Tokens produced by a finished request, split by whether the
    request met its SLO: the ``met`` stream is GOODPUT, the rest is
    work the cluster did for requests that missed anyway — the split
    the admission controller exists to improve."""
    if not enabled:
        return
    _m.counter("serving_slo_tokens_total",
               "tokens produced under the traffic harness, by SLO "
               "outcome", ("outcome",)).labels(
        "goodput" if met else "badput").inc(n)


def serving_slo_report(goodput_tps: float, met_frac: float,
                       p99_ttft_ms):
    """End-of-trace summary gauges: goodput (tokens/s of SLO-met
    requests over the run's wall time), deadline-met fraction, and
    p99 TTFT — the three headline numbers of the
    ``decode_slo_goodput`` bench tier."""
    if not enabled:
        return
    _m.gauge("serving_slo_goodput_tokens_per_sec",
             "goodput of the last traffic-harness run (tokens of "
             "deadline-met requests per wall second)").set(goodput_tps)
    _m.gauge("serving_slo_deadline_met_fraction",
             "deadline-met fraction of the last traffic-harness run"
             ).set(met_frac)
    if p99_ttft_ms is not None:
        _m.gauge("serving_slo_p99_ttft_ms",
                 "p99 time-to-first-token of the last traffic-harness "
                 "run").set(p99_ttft_ms)


def serving_autoscale(direction: str, replicas: int,
                      backlog_per_replica: float):
    """One autoscaler decision that actually scaled (``direction`` in
    ``up``/``down``): event counter + the serviceable-replica-count
    and backlog gauges — the closed loop's observable trajectory
    (tools/chaos_soak.py --traffic asserts both directions fired)."""
    if not enabled:
        return
    _m.counter("serving_autoscale_events_total",
               "autoscaler scale events", ("direction",)).labels(
        direction).inc()
    _m.gauge("serving_autoscale_replicas",
             "serviceable replicas after the last autoscaler decision"
             ).set(replicas)
    _m.gauge("serving_autoscale_backlog_per_replica",
             "backlog per serviceable replica at the last autoscaler "
             "decision").set(backlog_per_replica)


# ---------------- payload integrity (ISSUE 13) ----------------

def serving_integrity(site: str, action: str):
    """One payload-integrity event at a byte-moving site (``handoff``,
    ``swap_in``, ``prefix_promote``, ``disk_store``): ``detected`` — a
    checksum caught a corrupt/torn payload before install;
    ``quarantined`` — the entry was removed so it can never be
    re-served; ``replayed`` — the request recovered through the gated
    replay path. detected == quarantined (+ the replay where one
    applies) is the integrity gate's arithmetic."""
    if not enabled:
        return
    _m.counter("serving_integrity_events_total",
               "payload-integrity events at byte-moving sites",
               ("site", "action")).labels(site, action).inc()


def serving_integrity_retry(site: str):
    """One bounded-backoff retry of a byte-moving operation
    (``handoff_import`` / ``swap_in``) after a transient fault — the
    retry is idempotent (a failed attempt frees everything it
    allocated before re-raising), so the counter measures transient
    flakiness absorbed without a full engine recovery."""
    if not enabled:
        return
    _m.counter("serving_integrity_retries_total",
               "bounded retries of byte-moving operations after "
               "transient faults", ("site",)).labels(site).inc()


def serving_step(active: int, max_slots: int, pages_used: int,
                 pages_total: int):
    """One continuous-batching decode step: batch-occupancy histogram +
    block-pool utilization gauge."""
    if not enabled:
        return
    _m.histogram("serving_batch_occupancy",
                 "active decode slots per step, as a fraction of "
                 "max_batch",
                 buckets=(0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875,
                          1.0)).observe(active / max(max_slots, 1))
    _m.gauge("serving_block_pool_utilization",
             "fraction of the paged KV block pool in use"
             ).set(pages_used / max(pages_total, 1))
    _m.counter("serving_decode_steps_total",
               "continuous-batching decode steps").inc()
    _m.counter("serving_decode_tokens_total",
               "tokens decoded by the continuous-batching engine"
               ).inc(active)


# ---------------- data path ----------------

def dataloader_next(it, t0_ns: int):
    """One ``__next__`` return: ``wait`` is the time blocked inside the
    loader, ``compute`` the gap since the previous batch was handed out
    (the consumer's step time) — the reader-wait vs compute split."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("DataLoader.next", t0_ns, now, "DataLoader")
    if enabled:
        _m.histogram("dataloader_wait_seconds",
                     "seconds the consumer blocked waiting for a batch"
                     ).observe((now - t0_ns) / 1e9)
        prev = getattr(it, "_obs_last_ret_ns", None)
        if prev is not None:
            _m.histogram("dataloader_compute_seconds",
                         "seconds between batches (consumer compute)"
                         ).observe(max(0, t0_ns - prev) / 1e9)
    it._obs_last_ret_ns = now


# ---------------- collectives ----------------

def _nbytes(x) -> int:
    total = 0
    for t in (x if isinstance(x, (list, tuple)) else (x,)):
        v = getattr(t, "_value", t)  # unwrap framework Tensor
        try:
            import numpy as np
            total += int(v.size) * int(np.dtype(v.dtype).itemsize)
        except Exception:
            pass
    return total


def collective(op: str, x):
    """Count one collective call + its payload bytes. Inside jit this
    counts TRACE-time calls (once per compile), which is exactly the
    number of collectives in the compiled program."""
    # callers pre-check ``hooks.enabled``; re-check for direct users
    if not enabled:
        return
    _m.counter("collective_calls_total",
               "collective API calls", ("op",)).labels(op).inc()
    _m.counter("collective_bytes_total",
               "payload bytes through collective calls",
               ("op",)).labels(op).inc(_nbytes(x))


# ------- request tracing + flight recorder (ISSUE 16) -------
#
# A THIRD switch, independent of metrics and the profiler collector:
# ``tracing.enabled`` (set via ``tracing.enable()``). Every hook below
# starts with that one module-attribute read — the PR 1 zero-cost
# contract — and none of them touches device values: span timestamps
# come from the tracer's injectable host clock, and call sites close
# spans only at existing commit fences or on pure host paths
# (check_sync_points lints tracing.py alongside the dispatch paths).

def serving_trace_now() -> int:
    """Span anchor from the tracer's (injectable) clock; 0 when
    tracing is off, so call sites skip the close entirely — the same
    skip-on-zero convention as :func:`generate_begin`."""
    if not _tr.enabled:
        return 0
    return _tr.TRACER.now()


def serving_trace_submit(req, replica: int = -1):
    """Mint a trace onto a freshly-submitted request handle
    (idempotent — a handle that already rides a trace keeps it, which
    is what stitches cross-replica handoff/rehome hops into ONE
    trace)."""
    if not _tr.enabled:
        return
    _tr.TRACER.attach(req, replica=replica)
    if enabled:
        _m.counter("serving_trace_requests_total",
                   "request traces minted at submission").inc()


def serving_trace_enqueued(req):
    """Re-stamp the queue-wait anchor: submission and every requeue
    (preemption, recovery resume, shed-retry re-dispatch) restart the
    queue_wait span the next admission closes."""
    if not _tr.enabled:
        return
    _tr.TRACER.enqueued(req)


def serving_trace_admitted(req, replica: int = -1, slot: int = -1,
                           meta=None, t_ns: int = 0):
    """Close the queue_wait span opened at the last enqueue and mark
    the admission edge (slot assignment). ``t_ns``: admission instant
    anchored earlier by the caller (keeps queue and swap disjoint on
    the swap-in admit path)."""
    if not _tr.enabled:
        return
    _tr.TRACER.admitted(req, replica=replica, slot=slot, meta=meta,
                        t_ns=t_ns)


def serving_trace_first_token(req):
    """Explicit TTFT stamp for the row whose first token just
    committed — called from the commit fence, never from dispatch."""
    if not _tr.enabled:
        return
    _tr.TRACER.first_token(req)


def serving_trace_span(req, name: str, t0_ns: int, t1_ns: int = 0,
                       replica: int = -1, slot: int = -1,
                       seq: int = -1, meta=None):
    """Close a lifecycle span opened at ``t0_ns`` (a
    :func:`serving_trace_now` anchor; 0 skips) onto the request's
    trace. ``seq`` is the per-request step sequence — committed-token
    count at close — so step participation is reconstructable."""
    if not _tr.enabled:
        return
    _tr.TRACER.record(req, name, t0_ns, t1_ns, replica=replica,
                      slot=slot, seq=seq, meta=meta)


def serving_trace_mark(req, name: str, replica: int = -1,
                       slot: int = -1, seq: int = -1, meta=None):
    """Zero-duration point event (preempt, dispatch, rehome, WAL
    replay, ...)."""
    if not _tr.enabled:
        return
    _tr.TRACER.mark(req, name, replica=replica, slot=slot, seq=seq,
                    meta=meta)


def serving_trace_finish(req, reason: str, replica: int = -1):
    """Terminal edge: stamp the finish reason and end timestamp."""
    if not _tr.enabled:
        return
    _tr.TRACER.finish(req, reason, replica=replica)


def serving_flight_tick():
    """One scheduler tick folded into a supervisor's flight-recorder
    ring (the ring itself lives on the supervisor; this is the
    metrics-side counter)."""
    if not enabled:
        return
    _m.counter("serving_flight_ticks_total",
               "scheduler ticks recorded into flight-recorder rings"
               ).inc()


def serving_flight_dump(reason: str, nbytes: int):
    """One flight-recorder black box written (EngineDead, an exception
    escaping step(), or on demand): per-reason counter + size gauge."""
    if not enabled:
        return
    _m.counter("serving_flight_dumps_total",
               "flight-recorder dumps written, by trigger",
               ("reason",)).labels(reason).inc()
    _m.gauge("serving_flight_dump_bytes",
             "size of the last flight-recorder dump").set(nbytes)


# ---------------- multi-process RPC + KV fabric (ISSUE 19) ----------


def serving_rpc_call(method: str, t0_ns: int, bytes_out: int,
                     bytes_in: int):
    """Close one client-side RPC exchange opened at ``t0_ns`` (a
    :func:`generate_begin` anchor): per-method call counter, frame
    bytes in both directions, latency histogram — the numerator of the
    multi-process cost model (PERF_NOTES: RPC frame bytes per step vs
    handoff payload bytes)."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record(f"Serving.rpc[{method}]", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.counter("serving_rpc_calls_total",
               "RPC calls completed, by method",
               ("method",)).labels(method).inc()
    _m.counter("serving_rpc_bytes_total",
               "RPC frame bytes on the wire, by method and direction",
               ("method", "direction")).labels(method, "out"
                                               ).inc(bytes_out)
    _m.counter("serving_rpc_bytes_total",
               "RPC frame bytes on the wire, by method and direction",
               ("method", "direction")).labels(method, "in"
                                               ).inc(bytes_in)
    _m.histogram("serving_rpc_latency_ms",
                 "wall milliseconds per RPC exchange",
                 ("method",),
                 buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50,
                          100, 250, 1000)).labels(method).observe(
        (now - t0_ns) / 1e6)


def serving_rpc_served(method: str, t0_ns: int):
    """Close one server-side dispatch (handler execution + reply
    encode) — the remote half of :func:`serving_rpc_call`."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record(f"Serving.rpc_served[{method}]", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.counter("serving_rpc_served_total",
               "RPC calls dispatched server-side, by method",
               ("method",)).labels(method).inc()
    _m.histogram("serving_rpc_served_ms",
                 "wall milliseconds per server-side RPC dispatch",
                 ("method",),
                 buckets=(0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50,
                          100, 250, 1000)).labels(method).observe(
        (now - t0_ns) / 1e6)


def serving_rpc_retry(method: str):
    """One bounded-backoff retry of an idempotent RPC after a
    transport-level failure (torn/corrupt frame, reset, injected
    fault) — retried calls replay from the server's dedupe cache, so
    this counts wire flakiness, not duplicated work."""
    if not enabled:
        return
    _m.counter("serving_rpc_retries_total",
               "RPC attempts retried after a transport failure",
               ("method",)).labels(method).inc()


def serving_rpc_timeout(method: str):
    """One RPC attempt abandoned at its deadline (the socket stayed
    silent) — counted separately from other transport failures because
    a timeout is the one failure where the server may still have
    executed the call (the dedupe cache makes the retry safe)."""
    if not enabled:
        return
    _m.counter("serving_rpc_timeouts_total",
               "RPC attempts that hit their per-call deadline",
               ("method",)).labels(method).inc()


def serving_rpc_corrupt(kind: str):
    """One inbound RPC frame rejected before decode: ``torn`` (EOF
    mid-frame) or ``crc`` (bit-flip / bad magic / bad length). Nothing
    was installed — the connection drops and the peer retries."""
    if not enabled:
        return
    _m.counter("serving_rpc_corrupt_frames_total",
               "RPC frames rejected by framing/CRC validation",
               ("kind",)).labels(kind).inc()


def serving_fabric_demote(t0_ns: int, nbytes: int):
    """Close one DEMOTE to the shared KV fabric (a replica shipped a
    prefix/adapter/swap payload to the fabric server) opened at
    ``t0_ns``: count + payload bytes + latency."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.fabric_demote", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.counter("serving_fabric_demotes_total",
               "payloads demoted to the shared KV fabric").inc()
    _m.counter("serving_fabric_demote_bytes_total",
               "payload bytes demoted to the shared KV fabric"
               ).inc(nbytes)
    _m.histogram("serving_fabric_demote_ms",
                 "wall milliseconds per fabric demote",
                 buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                          250, 1000)).observe((now - t0_ns) / 1e6)


def serving_fabric_promote(t0_ns: int, nbytes: int, hit: bool):
    """Close one PROMOTE from the shared KV fabric opened at ``t0_ns``:
    hit/miss counters and, on a hit, the payload bytes that replaced a
    cold prefill (the fabric-hit vs cold-prefill crossover in
    PERF_NOTES)."""
    if not t0_ns:
        return
    now = time.perf_counter_ns()
    _record("Serving.fabric_promote", t0_ns, now, "UserDefined")
    if not enabled:
        return
    _m.counter("serving_fabric_promotes_total",
               "fabric promote lookups, by outcome",
               ("outcome",)).labels("hit" if hit else "miss").inc()
    if hit:
        _m.counter("serving_fabric_promote_bytes_total",
                   "payload bytes promoted from the shared KV fabric"
                   ).inc(nbytes)
    _m.histogram("serving_fabric_promote_ms",
                 "wall milliseconds per fabric promote lookup",
                 buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100,
                          250, 1000)).observe((now - t0_ns) / 1e6)


def serving_fabric_quarantine(site: str):
    """A fabric payload failed CRC verification BEFORE install and was
    quarantined server-side (the ISSUE 13 integrity discipline at the
    fabric hop) — the caller falls back to the gated replay path."""
    if not enabled:
        return
    _m.counter("serving_fabric_quarantined_total",
               "fabric payloads quarantined on checksum mismatch",
               ("site",)).labels(site).inc()


# ---------------- watchdog ----------------

def watchdog_tick(name: str):
    if not enabled:
        return
    _m.counter("watchdog_ticks_total", "watchdog ticks",
               ("watchdog",)).labels(name).inc()


def watchdog_fired(name: str, stall_seconds: float):
    """A stall fired: counters + last-stall gauge, and a span into the
    profiler collector (when recording) covering the stall window so it
    shows up in exported chrome traces."""
    now = time.perf_counter_ns()
    _record(f"Watchdog.fired[{name}]",
            now - int(stall_seconds * 1e9), now, "Watchdog")
    if enabled:
        _m.counter("watchdog_fired_total", "watchdog stall firings",
                   ("watchdog",)).labels(name).inc()
        _m.gauge("watchdog_last_stall_seconds",
                 "length of the most recent stall",
                 ("watchdog",)).labels(name).set(stall_seconds)
