"""paddle_tpu.static (reference: python/paddle/static/).

The reference's static graph (ProgramDesc + Executor, SURVEY §2.1 layer 4c/5)
is subsumed on TPU by jax tracing: a "static-mode program" is a traced+jitted
function. This module keeps the API surface (enable_static, program_guard,
Executor) mapping onto that substrate so static-style user code runs.
"""
from __future__ import annotations

import contextlib
import threading

from ..jit.api import InputSpec  # noqa: F401
from .._core.tensor import Tensor

_state = threading.local()


def in_dynamic_mode() -> bool:
    return not getattr(_state, "static", False)


def in_static_mode() -> bool:
    return getattr(_state, "static", False)


def enable_static():
    _state.static = True


def disable_static():
    _state.static = False


class Program:
    """Placeholder parity object: on TPU a program is a traced function; the
    Program object carries no graph (reference: base/framework.py:5893)."""

    def __init__(self):
        self.random_seed = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    if not hasattr(_state, "main_program"):
        _state.main_program = Program()
    return _state.main_program


def default_startup_program():
    if not hasattr(_state, "startup_program"):
        _state.startup_program = Program()
    return _state.startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old = getattr(_state, "main_program", None)
    _state.main_program = main_program
    try:
        yield
    finally:
        _state.main_program = old


def data(name, shape, dtype="float32", lod_level=0):
    raise NotImplementedError(
        "static.data placeholders are not supported: use paddle.jit."
        "to_static with InputSpec (the TPU-native compile path)")


class Executor:
    """Parity shell (reference: python/paddle/base/executor.py:1234): jitted
    functions execute directly; run() only supports callables captured via
    jit."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        raise NotImplementedError(
            "static Executor.run over ProgramDesc has no TPU analog; "
            "compile with paddle.jit.to_static and call the function")


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class BuildStrategy:
    pass


class ExecutionStrategy:
    pass


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.functional import grad
    return grad(targets, inputs, grad_outputs=target_gradients,
                allow_unused=True)


# re-exports for static-style model code
from ..nn import *  # noqa: F401,F403,E402
