"""paddle_tpu.static (reference: python/paddle/static/).

The reference's static graph (ProgramDesc + Executor, SURVEY §2.1 layer 4c/5)
is subsumed on TPU by jax tracing: a "static-mode program" is a traced+jitted
function. This module keeps the API surface (enable_static, program_guard,
Executor) mapping onto that substrate so static-style user code runs.
"""
from __future__ import annotations

import contextlib
import threading

from ..jit.api import InputSpec  # noqa: F401
from .._core.tensor import Tensor

_state = threading.local()


def in_dynamic_mode() -> bool:
    return not getattr(_state, "static", False)


def in_static_mode() -> bool:
    return getattr(_state, "static", False)


def _record(fn, args, outs):
    # the hook is process-global; the static flag is thread-local — gate on
    # both, and never record while replaying
    if not getattr(_state, "static", False) or \
            getattr(_state, "replaying", False):
        return
    prog = default_main_program()
    outs_t = outs if isinstance(outs, tuple) else (outs,)
    prog.ops.append((fn, tuple(args), outs_t))
    for o in outs_t:
        if isinstance(o, Tensor):
            prog._val2out[id(o._value)] = o


def _record_bind(alias, src_tensor, new_value, old_value=None):
    """In-place rebinding (y[0]=v, t.add_(v), _inplace_from): replay must
    route the alias to the producing op's output, not the build-time
    value."""
    if not getattr(_state, "static", False) or \
            getattr(_state, "replaying", False):
        return
    prog = default_main_program()
    if src_tensor is not None:
        src = src_tensor
    else:
        # map the assigned raw value back to the recorded out that
        # produced it (setitem-style ops assign an apply output's value)
        src = prog._val2out.get(id(new_value), new_value)
    if old_value is not None and id(alias) not in prog._pre_values:
        prog._pre_values[id(alias)] = old_value
    prog.ops.append(("bind", alias, src))
    if isinstance(alias, Tensor):
        prog._val2out[id(alias._value)] = alias


def enable_static():
    from .._core import autograd as _ag
    from .._core import tensor as _tc
    _state.static = True
    _ag.set_static_hook(_record)
    _tc.set_inplace_hook(_record_bind)


def disable_static():
    from .._core import autograd as _ag
    from .._core import tensor as _tc
    _state.static = False
    _ag.set_static_hook(None)
    _tc.set_inplace_hook(None)


class Program:
    """A recorded op sequence (reference: base/framework.py:5893 Program /
    ProgramDesc). TPU-native: while static mode is on, every framework op
    that executes appends (fn, args, outs) here via the autograd static
    hook; Executor.run replays the sequence with fed placeholder values.
    The reference builds the graph WITHOUT running it; here ops also run
    once at build time (on placeholder zeros) — same API, eager-traced
    capture, and XLA still compiles the replay."""

    def __init__(self):
        self.random_seed = None
        self.ops: list = []          # (fn, args, outs) | ("bind", alias, src)
        self.placeholders: dict = {}  # name -> placeholder Tensor
        self._val2out: dict = {}      # id(out._value) -> recorded out
        # pre-mutation value of each tensor first rebound in-place: ops
        # recorded BEFORE the bind must replay against this, not the
        # final (mutated) build-time value
        self._pre_values: dict = {}

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    # None also means "unset": program_guard restores None after a scope
    # entered before any default program existed
    if getattr(_state, "main_program", None) is None:
        _state.main_program = Program()
    return _state.main_program


def default_startup_program():
    if getattr(_state, "startup_program", None) is None:
        _state.startup_program = Program()
    return _state.startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old = getattr(_state, "main_program", None)
    _state.main_program = main_program
    try:
        yield
    finally:
        _state.main_program = old


def data(name, shape, dtype="float32", lod_level=0):
    """A feedable placeholder (reference: python/paddle/static/input.py
    data). Build-time value: zeros with None/-1 dims as 1; Executor.run
    substitutes the fed array (shapes may differ in the None dims — the
    recorded ops are shape-polymorphic jnp code)."""
    import numpy as np
    import jax.numpy as jnp
    from .._core import dtype as dtypes
    shp = tuple(1 if (d is None or d == -1) else int(d) for d in shape)
    t = Tensor(jnp.zeros(shp, dtypes.convert_dtype(dtype)), _internal=True)
    t.stop_gradient = True
    t._placeholder_name = name
    t.name = name
    default_main_program().placeholders[name] = t
    return t


class Executor:
    """Replays a recorded Program with fed placeholders (reference:
    python/paddle/base/executor.py:1234 Executor.run ->
    StandaloneExecutor/PirInterpreter). The dependency-ordered instruction
    list of the reference IS the recorded op sequence; XLA compiles the
    jnp calls it replays."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True, **kwargs):
        import numpy as np
        import jax.numpy as jnp
        prog = program if isinstance(program, Program) else \
            default_main_program()
        if not prog.ops and not fetch_list and not feed:
            return []  # startup-program run: params already initialized
        env = {}
        swapped = []

        def resolve(a):
            if isinstance(a, Tensor):
                v = env.get(id(a))
                if v is not None:
                    return v
                # not yet (re)computed this replay: a tensor later rebound
                # in place must resolve to its PRE-mutation value here
                return prog._pre_values.get(id(a), a._value)
            return a

        _state.replaying = True
        try:
            for name, val in (feed or {}).items():
                ph = prog.placeholders.get(name)
                if ph is None:
                    raise KeyError(f"feed target {name!r} is not a "
                                   f"static.data placeholder of this "
                                   f"program")
                if isinstance(val, Tensor):
                    val = val._value
                # jnp.asarray passes traced arrays through (the feed may
                # be a tracer when save_inference_model exports the replay)
                fed = jnp.asarray(val)
                env[id(ph)] = fed
                # ALSO swap the fed value into the placeholder object for
                # the replay's duration: recorded closures that read an
                # external placeholder directly (e.g. a while op's cond
                # reading a fed trip count) then see the fed value —
                # the reference's sub-block variable scoping
                swapped.append((ph, ph._value))
                ph._value = fed
            for entry in prog.ops:
                if entry[0] == "bind":
                    _, alias, src = entry
                    env[id(alias)] = resolve(src) if isinstance(
                        src, Tensor) else src
                    continue
                fn, args, outs = entry
                vals = fn(*[resolve(a) for a in args])
                if not isinstance(vals, (tuple, list)):
                    vals = (vals,)
                for o, v in zip(outs, vals):
                    env[id(o)] = v
                    # swap recomputed intermediates into their Tensor
                    # objects too, so sub-block closures reading DERIVED
                    # values (e.g. while cond over `n + 1`) stay current
                    if isinstance(o, Tensor):
                        swapped.append((o, o._value))
                        o._value = v
        finally:
            _state.replaying = False
            for ph, old in swapped:
                ph._value = old

        fetches = fetch_list or []
        out = []
        for f in fetches:
            v = resolve(f) if isinstance(f, Tensor) else f
            out.append(np.asarray(v) if return_numpy else
                       Tensor(v, _internal=True))
        return out


class CompiledProgram:
    def __init__(self, program, build_strategy=None):
        self.program = program


class BuildStrategy:
    pass


class ExecutionStrategy:
    pass


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd.functional import grad
    # Static-record mode: run the backward through the create_graph
    # engine, whose VJPs are RECORDED apply ops that rebuild jax.vjp
    # from current values at execution (_core.autograd._node_vjp_graph).
    # The replay then recomputes gradients against FED values — the
    # reference's grad-block re-execution. Plain eager keeps the cheap
    # one-shot vjp closures.
    return grad(targets, inputs, grad_outputs=target_gradients,
                allow_unused=True, create_graph=in_static_mode())


# re-exports for static-style model code
from ..nn import *  # noqa: F401,F403,E402

from . import nn  # noqa: E402 — static.nn control flow + classic layers
from .extras import (  # noqa: F401,E402
    Variable, cpu_places, cuda_places, xpu_places, Scope, global_scope,
    scope_guard, name_scope, device_guard, save, load, load_program_state,
    set_program_state, serialize_program, deserialize_program,
    serialize_persistables, deserialize_persistables, save_to_file,
    load_from_file, normalize_program, save_inference_model,
    load_inference_model, create_global_var, Print, accuracy, auc,
    ctr_metric_bundle, append_backward, py_func, WeightNormParamAttr,
    ExponentialMovingAverage,
)
def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """reference: static create_parameter — the top-level factory."""
    import paddle_tpu as _p
    return _p.create_parameter(shape, dtype, name=name, attr=attr,
                               is_bias=is_bias,
                               default_initializer=default_initializer)
