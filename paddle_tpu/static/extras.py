"""Static-graph surface long tail (reference: python/paddle/static/ —
__init__.py exports; io.py save/load/save_inference_model:?; base/
framework.py name_scope/device_guard; base/executor.py scope utilities;
incubate ExponentialMovingAverage lives at static level in the reference).

The TPU static mode records eagerly-executed ops and replays them
(static/__init__.py); these utilities operate on that Program plus the
live Parameter objects captured during recording.
"""
from __future__ import annotations

import contextlib
import os
import pickle
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor, Parameter
from .._core.autograd import apply, no_grad
from ..ops._registry import as_tensor

Variable = Tensor  # reference: base/framework.py Variable — the Tensor IS it


# ---------------- places ----------------
def cpu_places(device_count=None):
    from ..device import CPUPlace
    n = device_count or int(os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    from ..device import CUDAPlace
    import jax
    ids = device_ids if device_ids is not None else \
        range(jax.device_count())
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    from ..device import XPUPlace
    ids = device_ids if device_ids is not None else [0]
    return [XPUPlace(i) for i in ids]


# ---------------- scopes / guards ----------------
class Scope:
    """reference: paddle/fluid/framework/scope.h:50 — named variable
    container."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}

    def var(self, name):
        self._vars.setdefault(name, _ScopeVar(name))
        return self._vars[name]

    def find_var(self, name):
        return self._vars.get(name)

    def drop_kids(self):
        pass


class _ScopeVar:
    def __init__(self, name):
        self.name = name
        self._tensor = None

    def get_tensor(self):
        return self._tensor

    def set_tensor(self, t):
        self._tensor = t


_global_scope = Scope()
_scope_stack: List[Scope] = []


def global_scope() -> Scope:
    return _scope_stack[-1] if _scope_stack else _global_scope


@contextlib.contextmanager
def scope_guard(scope: Scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


_name_stack: List[str] = []


@contextlib.contextmanager
def name_scope(prefix: Optional[str] = None):
    """reference: base/framework.py:7962-adjacent name_scope — nested op
    name prefixes (cosmetic in the recorded program)."""
    _name_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_stack.pop()


@contextlib.contextmanager
def device_guard(device: Optional[str] = None):
    """reference: base/framework.py device_guard — device placement hint;
    XLA owns placement on TPU, so this is a recorded annotation."""
    yield


# ---------------- program state / IO ----------------
def _program_params(program) -> Dict[str, Tensor]:
    """Parameters captured while recording ``program`` (op args that are
    Parameter instances)."""
    from . import Program, default_main_program
    prog = program if program is not None else default_main_program()
    out: Dict[str, Tensor] = {}
    seen = set()
    for entry in getattr(prog, "ops", []):
        if entry[0] == "bind":
            continue
        _fn, args, _outs = entry
        for a in args:
            if isinstance(a, Parameter) and id(a) not in seen:
                seen.add(id(a))
                name = getattr(a, "name", None) or f"param_{len(out)}"
                out[name] = a
    return out


def save(program, model_path, protocol=4, **configs):
    """reference: static/io.py save — persist the program's parameters."""
    from ..framework.io import save as _save
    _save({k: v for k, v in _program_params(program).items()},
          model_path + ".pdparams" if not model_path.endswith(".pdparams")
          else model_path)


def load(program, model_path, executor=None, var_list=None):
    """reference: static/io.py load."""
    from ..framework.io import load as _load
    path = model_path + ".pdparams" if not \
        model_path.endswith(".pdparams") else model_path
    state = _load(path)
    params = _program_params(program)
    with no_grad():
        for k, p in params.items():
            if k in state:
                v = state[k]
                p._inplace_assign(v._value if isinstance(v, Tensor)
                                  else jnp.asarray(np.asarray(v)))
    return state


def load_program_state(model_path, var_list=None):
    """reference: static/io.py load_program_state."""
    from ..framework.io import load as _load
    path = model_path + ".pdparams" if not \
        model_path.endswith(".pdparams") else model_path
    st = _load(path)
    return {k: (np.asarray(v._value) if isinstance(v, Tensor)
                else np.asarray(v)) for k, v in st.items()}


def set_program_state(program, state_dict):
    """reference: static/io.py set_program_state."""
    params = _program_params(program)
    with no_grad():
        for k, p in params.items():
            if k in state_dict:
                p._inplace_assign(jnp.asarray(np.asarray(state_dict[k])))


def serialize_program(feed_vars=None, fetch_vars=None, program=None,
                      **kwargs) -> bytes:
    """reference: static/io.py serialize_program — program structure as
    bytes (placeholder names + op count; the executable form is
    save_inference_model's jit artifact)."""
    from . import default_main_program
    prog = program or default_main_program()
    meta = {"placeholders": list(prog.placeholders.keys()),
            "num_ops": len(prog.ops)}
    return pickle.dumps(meta, protocol=4)


def deserialize_program(data: bytes):
    return pickle.loads(data)


def serialize_persistables(feed_vars=None, fetch_vars=None, program=None,
                           **kwargs) -> bytes:
    params = _program_params(program)
    return pickle.dumps({k: np.asarray(v._value)
                         for k, v in params.items()}, protocol=4)


def deserialize_persistables(program, data: bytes, executor=None):
    set_program_state(program, pickle.loads(data))


def save_to_file(path: str, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """reference: static/io.py normalize_program — prune to the
    feed->fetch slice. The recorded program replays only what resolves,
    so pruning is implicit; returned as-is."""
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """reference: static/io.py save_inference_model — persist a
    feed->fetch callable. TPU-native: trace the Program replay into a
    jit.save (StableHLO) artifact."""
    from . import Executor, default_main_program
    from ..jit import save as jit_save
    from ..jit.api import InputSpec
    prog = program or default_main_program()
    feed_vars = feed_vars if isinstance(feed_vars, (list, tuple)) \
        else [feed_vars]
    fetch_vars = fetch_vars if isinstance(fetch_vars, (list, tuple)) \
        else [fetch_vars]
    ex = Executor()

    from ..nn.layer.layers import Layer as _Layer

    class _ReplayModule(_Layer):
        """jit.save exports compiled programs for Layers; the recorded
        replay is wrapped as one (captured Parameters become constants
        in the exported StableHLO — an inference artifact)."""

        def forward(self, *feeds):
            feed = {fv._placeholder_name: t
                    for fv, t in zip(feed_vars, feeds)}
            outs = ex.run(prog, feed=feed, fetch_list=list(fetch_vars),
                          return_numpy=False)
            return tuple(outs) if len(outs) > 1 else outs[0]

    specs = [InputSpec(list(fv.shape), str(fv.dtype).split(".")[-1])
             for fv in feed_vars]
    jit_save(_ReplayModule(), path_prefix, input_spec=specs)


def load_inference_model(path_prefix, executor=None, **kwargs):
    """reference: static/io.py load_inference_model — returns
    [program(=loaded callable), feed_names, fetch_targets]."""
    from ..jit import load as jit_load
    loaded = jit_load(path_prefix)
    return [loaded, list(getattr(loaded, "_input_names", [])), None]


# ---------------- ops / helpers ----------------
def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """reference: tensor/creation.py create_global_var."""
    t = Tensor(jnp.full(tuple(shape), value,
                        _cv(dtype)), _internal=True)
    t.stop_gradient = True
    t.persistable = persistable
    if name:
        t.name = name
    sv = global_scope().var(name or f"gvar_{id(t)}")
    sv.set_tensor(t)
    return t


def _cv(dtype):
    from .._core.dtype import convert_dtype
    return convert_dtype(dtype)


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """reference: static/nn/control_flow.py Print — debug-print the
    tensor when the op runs (eagerly AND on every Executor replay)."""
    x = as_tensor(input)
    msg = message or ""
    state = {"n": 0}

    def f(v):
        if first_n < 0 or state["n"] < first_n:
            state["n"] += 1
            head = f"{msg} " if msg else ""
            print(f"{head}shape={tuple(v.shape)} dtype={v.dtype} "
                  f"values={np.asarray(v).reshape(-1)[:summarize]}")
        return v
    return apply(f, x, name="print")


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """reference: static/nn/metric.py accuracy (top-k)."""
    from ..metric.metrics import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """reference: static/nn/metric.py auc — returns (auc_out,
    batch_auc_out, [state vars]); single-batch trapezoidal AUC here (the
    streaming state lives in paddle_tpu.metric.Auc for the dygraph
    path)."""
    x = as_tensor(input)
    y = as_tensor(label)

    def f(p, t):
        pos_score = p[:, 1] if p.ndim == 2 and p.shape[1] == 2 else \
            p.reshape(-1)
        t = t.reshape(-1).astype(jnp.float32)
        thr = jnp.linspace(0.0, 1.0, num_thresholds + 1)
        pred_pos = pos_score[None, :] >= thr[:, None]
        tp = jnp.sum(pred_pos * t[None, :], axis=1)
        fp = jnp.sum(pred_pos * (1 - t)[None, :], axis=1)
        pos = jnp.maximum(jnp.sum(t), 1e-12)
        neg = jnp.maximum(jnp.sum(1 - t), 1e-12)
        tpr = tp / pos
        fpr = fp / neg
        return -jnp.trapezoid(tpr, fpr)
    a = apply(f, x, y, name="auc")
    return a, a, []


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """reference: static/nn/metric.py ctr_metric_bundle — (sqrerr, abserr,
    prob, q, pos, total) running CTR metrics, single-batch form."""
    x = as_tensor(input)
    y = as_tensor(label)

    def f(p, t):
        p = p.reshape(-1)
        t = t.reshape(-1).astype(jnp.float32)
        sqrerr = jnp.sum((p - t) ** 2)
        abserr = jnp.sum(jnp.abs(p - t))
        prob = jnp.sum(p)
        q = jnp.sum(p * p)
        pos = jnp.sum(t)
        total = jnp.float32(t.shape[0])
        return sqrerr, abserr, prob, q, pos, total
    return apply(f, x, y, name="ctr_metric_bundle", multi_out=True)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """reference: base/backward.py append_backward — static autodiff:
    returns [(param, grad)] for trainable params reaching ``loss``. The
    gradient computation itself is recorded onto the program (apply-based
    VJPs), so Executor replays include it."""
    from ..autograd.functional import grad as _grad
    from . import default_main_program
    params = parameter_list
    if params is None:
        params = list(_program_params(default_main_program()).values())
    params = [p for p in params
              if isinstance(p, Tensor) and not p.stop_gradient]
    grads = _grad(loss, params, retain_graph=True, allow_unused=True)
    return [(p, g) for p, g in zip(params, grads) if g is not None]


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference: static/nn/common.py py_func — embed a python callable as
    an op. Replay calls the python function again (the replay engine is
    host-side, like the reference's CPU py_func op)."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    xs = [as_tensor(v) for v in xs]

    def f(*vals):
        ts = [Tensor(v, _internal=True) for v in vals]
        res = func(*ts)
        rs = res if isinstance(res, (tuple, list)) else [res]
        vals_out = tuple(r._value if isinstance(r, Tensor)
                         else jnp.asarray(np.asarray(r)) for r in rs)
        return vals_out if len(vals_out) > 1 else vals_out[0]

    result = apply(f, *xs, name="py_func",
                   multi_out=isinstance(out, (list, tuple)))
    return result


class WeightNormParamAttr:
    """reference: static/nn/common.py WeightNormParamAttr — ParamAttr that
    requests the weight_norm reparametrization (consumed by nn.utils)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.trainable = trainable
        self.need_clip = need_clip


class ExponentialMovingAverage:
    """reference: static/__init__.py ExponentialMovingAverage — shadow
    EMA of every trainable parameter; ``update()`` after each step,
    ``apply()``/``restore()`` swap for evaluation (with the reference's
    bias-corrected decay when ``thres_steps`` is None)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._step = 0
        self._shadow: Dict[int, Any] = {}
        self._backup: Dict[int, Any] = {}
        self._params: List[Tensor] = []

    def _tracked(self):
        if not self._params:
            from . import default_main_program
            self._params = list(
                _program_params(default_main_program()).values())
        return self._params

    def register(self, parameters):
        self._params = [p for p in parameters if not p.stop_gradient]

    @no_grad()
    def update(self):
        self._step += 1
        d = min(self._decay, (1 + self._step) / (10 + self._step))
        for p in self._tracked():
            prev = self._shadow.get(id(p))
            if prev is None:
                self._shadow[id(p)] = p._value
            else:
                self._shadow[id(p)] = d * prev + (1 - d) * p._value

    @no_grad()
    def apply(self, executor=None, need_restore=True):
        for p in self._tracked():
            sh = self._shadow.get(id(p))
            if sh is not None:
                self._backup[id(p)] = p._value
                p._inplace_assign(sh)
        return _EMAGuard(self, need_restore)

    @no_grad()
    def restore(self, executor=None):
        for p in self._tracked():
            bk = self._backup.pop(id(p), None)
            if bk is not None:
                p._inplace_assign(bk)


class _EMAGuard:
    def __init__(self, ema, need_restore):
        self._ema = ema
        self._need_restore = need_restore

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._need_restore:
            self._ema.restore()
        return False
