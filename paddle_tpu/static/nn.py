"""static.nn — control flow + classic static-graph layers.

TPU-native re-design of the reference's static control-flow ops
(reference: python/paddle/static/nn/control_flow.py cond:1080,
while_loop:1383, case:?, switch_case — which build ConditionalBlock /
While ops into a Program). Here the predicate decides the lowering:

- **concrete predicate** (eager / static-record mode): run the taken
  branch directly as plain Python — full autograd-tape support, and the
  static recorder captures the executed ops.
- **traced predicate** (inside ``to_static`` / ``jax.jit``): lower to
  ``lax.cond`` / ``lax.while_loop`` / ``lax.switch`` so the function stays
  ONE compiled XLA program instead of graph-breaking to eager.

This is the compiled-control-flow companion to StaticFunction's
graph-break fallback (jit/api.py).
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .._core.tensor import Tensor
from .._core import autograd as ag

# classic static.nn members that already live elsewhere in this package
from .extras import (  # noqa: F401
    Print, accuracy, auc, ctr_metric_bundle, py_func)


def _scalar(pred):
    v = pred._value if isinstance(pred, Tensor) else pred
    return jnp.asarray(v).reshape(())


def _is_traced(v) -> bool:
    return isinstance(v, jax.core.Tracer)


def _as_pred_eq(idx, k):
    import paddle_tpu as _p
    t = idx if isinstance(idx, Tensor) else _p.to_tensor(idx)
    return t == k


def _select_pytree(pred, tval, fval):
    """Elementwise select between two same-structure pytrees of Tensors,
    recorded as ordinary `where` ops (replay-safe)."""
    import paddle_tpu as _p
    pred_t = pred if isinstance(pred, Tensor) else _p.to_tensor(pred)
    return jax.tree_util.tree_map(
        lambda a, b: _p.where(pred_t.reshape([]), a, b), tval, fval,
        is_leaf=lambda x: isinstance(x, Tensor))


def _call_nograd(fn):
    """Run a branch under trace: jit differentiates the traced program, so
    the python tape is skipped (same contract as StaticFunction.traced)."""
    with ag.no_grad():
        return fn() if fn is not None else None


def cond(pred, true_fn: Optional[Callable] = None,
         false_fn: Optional[Callable] = None, name=None, return_names=None):
    """reference: python/paddle/static/nn/control_flow.py:1080 cond.

    Both branches must return pytrees of identical structure/shape/dtype
    when the predicate is traced (XLA requirement)."""
    pv = _scalar(pred)
    if not _is_traced(pv):
        from . import in_static_mode
        if in_static_mode() and true_fn is not None \
                and false_fn is not None:
            # static-record mode: the predicate's BUILD value must not
            # bake the branch (the reference's ConditionalBlock runs the
            # select at execution) — record both branches + a select so
            # Executor replay re-evaluates against the fed values.
            # CONTRACT: branches must be PURE here (both are executed and
            # recorded; in-place side effects in the untaken branch would
            # replay unconditionally — XLA select semantics, same rule as
            # the traced lax.cond path below)
            return _select_pytree(pred, true_fn(), false_fn())
        fn = true_fn if bool(pv) else false_fn
        return fn() if fn is not None else None
    if true_fn is None or false_fn is None:
        raise ValueError(
            "static.nn.cond with a traced predicate needs BOTH branches: "
            "XLA requires the two branch outputs to have identical pytree "
            "structure (a missing branch would return None). Pass a "
            "false_fn/true_fn returning the same-shaped outputs.")
    return lax.cond(pv.astype(bool),
                    lambda _: _call_nograd(true_fn),
                    lambda _: _call_nograd(false_fn), None)


def _bounded_while_raw(cond_fn, body_fn, n):
    """Reverse-differentiable while: lax.scan over ``n`` steps with an
    active mask (lax.while_loop has no reverse rule; scan does). The
    body runs all ``n`` steps — finished iterations select the old
    carry — so the body must be pure and shape-stable, and a loop whose
    condition is still true after ``n`` steps is truncated (the bounded
    XLA While contract)."""
    def run(*vals):
        def step(carry, _):
            vs, active = carry
            ts = tuple(Tensor(v, _internal=True) for v in vs)
            with ag.no_grad():
                # no python tape inside the scan: jax differentiates the
                # traced program itself (same contract as the other
                # compiled control-flow paths)
                pred = jnp.logical_and(
                    active, _scalar(cond_fn(*ts)).astype(bool))
                out = body_fn(*ts)
            out = tuple(out) if isinstance(out, (tuple, list)) else (out,)
            new = tuple(o._value if isinstance(o, Tensor)
                        else jnp.asarray(o) for o in out)
            sel = tuple(jnp.where(pred, nv, ov)
                        for nv, ov in zip(new, vs))
            return (sel, pred), None
        (vs, _), _ = lax.scan(step, (tuple(vals), jnp.asarray(True)),
                              None, length=int(n))
        return vs
    return run


def _harvest_grad_captures(body_fn, loop_vars):
    """Differentiable PRE-EXISTING tensors the body directly reads (loop
    vars and closure captures alike, leaf or derived) — the reference
    While grad block's external-variable grads. Discovered by running
    the body once at build with an op-observer hook collecting every
    Tensor operand not itself created during the probe; they become
    explicit inputs of the recorded op so the VJP and the fed replay
    both see them. (A tape-leaf walk would miss DERIVED captures like
    ``w = a * 3`` read in the body: the body reads w's value, not
    a's.)"""
    from .._core import autograd as _ag
    hook = _ag._static_hook[0]
    reads, rids, created = [], set(), set()

    def collector(fn, args, outs):
        for a in args:
            if isinstance(a, Tensor) and id(a) not in created and \
                    id(a) not in rids and not a.stop_gradient and \
                    jnp.issubdtype(jnp.result_type(a._value),
                                   jnp.floating):
                rids.add(id(a))
                reads.append(a)
        outs_t = outs if isinstance(outs, tuple) else (outs,)
        for o in outs_t:
            if isinstance(o, Tensor):
                created.add(id(o))

    _ag.set_static_hook(collector)   # probe ops are not program ops
    try:
        body_fn(*loop_vars)
    finally:
        _ag.set_static_hook(hook)
    return reads


def while_loop(cond_fn: Callable, body_fn: Callable, loop_vars: Sequence,
               is_test: bool = False, name=None,
               maximum_trip_count: Optional[int] = None):
    """reference: python/paddle/static/nn/control_flow.py:1383 while_loop.

    ``body_fn`` must return loop vars with unchanged shapes/dtypes (XLA
    static-shape requirement — same contract as the reference's While op,
    whose block also fixes var shapes).

    ``maximum_trip_count`` (TPU-native extension): bounds the loop at N
    iterations and lowers it to a masked ``lax.scan``, which HAS a
    reverse-mode rule — gradients then flow through the loop in static
    mode (with FED trip counts, the reference While + append_backward
    capability) and under jit tracing, where the unbounded
    ``lax.while_loop`` is forward-only. A loop still live after N steps
    is truncated."""
    loop_vars = list(loop_vars)
    bounded = maximum_trip_count is not None

    def c(vs):
        with ag.no_grad():
            return _scalar(cond_fn(*vs)).astype(bool)

    def b(vs):
        with ag.no_grad():
            out = body_fn(*vs)
        return tuple(out) if isinstance(out, (tuple, list)) else (out,)

    pv0 = _scalar(cond_fn(*loop_vars))
    if not _is_traced(pv0) and not any(
            _is_traced(v._value if isinstance(v, Tensor) else v)
            for v in loop_vars):
        from . import in_static_mode
        needs_grad = any(
            isinstance(v, Tensor) and not v.stop_gradient
            and jnp.issubdtype(jnp.result_type(v._value), jnp.floating)
            for v in loop_vars)
        captures = []
        if in_static_mode() and bounded and ag.is_grad_enabled():
            # grads may also enter purely through closure captures (all
            # loop vars non-differentiable) — harvest decides
            captures = _harvest_grad_captures(body_fn, loop_vars)
            needs_grad = needs_grad or bool(captures)
        if in_static_mode() and (not needs_grad or bounded):
            # static-record mode: the trip count must come from the FED
            # values, not the build values — record the whole loop as ONE
            # op whose body is a lax.while_loop (the reference's While op
            # with its sub-block). Replay re-executes it. Differentiable
            # loop vars: with maximum_trip_count the body is a masked
            # scan and the recorded op carries a VJP (the reference's
            # While grad block); unbounded, they keep the taped
            # eager-unroll path below (reverse-mode through a dynamic
            # lax.while_loop has no rule).
            n_lv = len(loop_vars)
            if not needs_grad:
                captures = []

            def f(*vals):
                # suspend the recorder inside the sub-trace (the loop's
                # interior ops belong to the while op's body, not the
                # program) and intercept in-place mutation of EXTERNAL
                # tensors: writing a trace-local tracer into a concrete
                # tensor would leak it past the trace
                from .._core import autograd as _ag
                from .._core import tensor as _tc
                hook = _ag._static_hook[0]
                ip_hook = _tc._inplace_hook[0]
                lv_vals, cap_vals = vals[:n_lv], vals[n_lv:]
                # closure captures read THROUGH the tensor objects: swap
                # the op-input values in for the body's duration so the
                # vjp trace (and the fed replay) sees them as inputs
                cap_swap = [(t, t._value) for t in captures]
                for (t, _), v in zip(cap_swap, cap_vals):
                    t._value = v

                def guard(alias, src_tensor, new_value, old_value=None):
                    old = old_value if old_value is not None else \
                        getattr(alias, "_value", None)
                    nv = new_value if new_value is not None else \
                        getattr(src_tensor, "_value", None)
                    if not _is_traced(old) and _is_traced(nv):
                        raise RuntimeError(
                            "static.nn.while_loop body mutated a tensor "
                            "defined OUTSIDE the loop in place; carry it "
                            "as a loop var instead (the While sub-block "
                            "is pure, like lax.while_loop)")
                _ag.set_static_hook(None)
                _tc.set_inplace_hook(guard)

                # FRESH closures per execution: lax.while_loop caches the
                # traced body by function identity, so reusing c/b would
                # bake the build-time value of any closure-captured
                # placeholder (e.g. a fed trip count) into the cached
                # jaxpr as a constant
                def c_(vs):
                    with ag.no_grad():
                        return _scalar(cond_fn(*vs)).astype(bool)

                def b_(vs):
                    with ag.no_grad():
                        out = body_fn(*vs)
                    return tuple(out) if isinstance(out, (tuple, list)) \
                        else (out,)
                try:
                    if bounded and needs_grad:
                        # masked scan: full N steps, but reverse-
                        # differentiable — the grad-carrying lowering
                        raw = tuple(v._value if isinstance(v, Tensor)
                                    else jnp.asarray(v)
                                    for v in lv_vals)
                        outs = _bounded_while_raw(
                            cond_fn, body_fn, maximum_trip_count)(*raw)
                    elif bounded:
                        # forward-only: keep the early-exiting while —
                        # a fed trip count of 3 must not execute an
                        # N=10000 bound — with the cap in the condition
                        ts = tuple(Tensor(v, _internal=True)
                                   for v in lv_vals)

                        def c_cap(carry):
                            return jnp.logical_and(
                                c_(carry[:-1]),
                                carry[-1] < maximum_trip_count)

                        def b_cap(carry):
                            return b_(carry[:-1]) + (carry[-1] + 1,)
                        outs = lax.while_loop(
                            c_cap, b_cap,
                            ts + (jnp.asarray(0, jnp.int32),))[:-1]
                    else:
                        ts = tuple(Tensor(v, _internal=True)
                                   for v in lv_vals)
                        outs = lax.while_loop(c_, b_, ts)
                finally:
                    _ag.set_static_hook(hook)
                    _tc.set_inplace_hook(ip_hook)
                    for t, old in cap_swap:
                        t._value = old
                return tuple(t._value if isinstance(t, Tensor) else t
                             for t in outs)
            import contextlib
            from .._core.autograd import apply as _apply
            grad_ctx = contextlib.nullcontext() if (needs_grad and
                                                    bounded) \
                else ag.no_grad()
            with grad_ctx:
                outs = _apply(f, *[v if isinstance(v, Tensor) else
                                   Tensor(jnp.asarray(v), _internal=True)
                                   for v in loop_vars],
                              *captures,
                              name="while_loop", multi_out=True)
            return list(outs if isinstance(outs, tuple) else (outs,))
        trips = 0
        while bool(_scalar(cond_fn(*loop_vars))):
            if bounded and trips >= maximum_trip_count:
                break    # the bounded contract: truncate, like the scan
            out = body_fn(*loop_vars)
            loop_vars = list(out) if isinstance(out, (tuple, list)) \
                else [out]
            trips += 1
        return loop_vars

    if bounded:
        # traced + bounded: the masked scan keeps the loop differentiable
        # under jit (lax.while_loop below is forward-only)
        raw = tuple(v._value if isinstance(v, Tensor) else jnp.asarray(v)
                    for v in loop_vars)
        outs = _bounded_while_raw(cond_fn, body_fn,
                                  maximum_trip_count)(*raw)
        return [Tensor(o, _internal=True) for o in outs]
    return list(lax.while_loop(c, b, tuple(loop_vars)))


def case(pred_fn_pairs, default: Optional[Callable] = None, name=None):
    """reference: static/nn/control_flow.py case — first true pred wins;
    like the reference, the LAST pair's fn is the default when none given."""
    pairs = list(pred_fn_pairs)
    if not pairs:
        raise ValueError("case() needs at least one (pred, fn) pair")
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]
    # nest cond from the last pair outward so the FIRST true pred wins;
    # each level is a zero-arg callable usable as the outer cond's false_fn
    out_fn = default
    for p, f in reversed(pairs):
        out_fn = (lambda p=p, f=f, nxt=out_fn: cond(p, f, nxt))
    return out_fn()


def switch_case(branch_index, branch_fns, default: Optional[Callable] = None,
                name=None):
    """reference: static/nn/control_flow.py switch_case.

    ``branch_fns``: dict {int: fn}, list of (int, fn), or list of fns
    (implicit keys 0..n-1)."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(k), f) for k, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    if not fns:
        raise ValueError("switch_case() needs at least one branch fn")
    if default is None:
        default = fns[-1]  # reference: last branch doubles as default
    iv = _scalar(branch_index)
    if not _is_traced(iv):
        from . import in_static_mode
        if in_static_mode():
            # record every branch + select chain (replay re-evaluates)
            out = default()
            for k, f in items:
                out = _select_pytree(
                    _as_pred_eq(branch_index, k), f(), out)
            return out
        k = int(iv)
        fn = dict(items).get(k, default)
        return fn()
    # selector: position of branch_index among keys, else the default slot
    sel = jnp.full((), len(fns), jnp.int32)
    for pos, k in enumerate(keys):
        sel = jnp.where(iv.astype(jnp.int32) == k, jnp.int32(pos), sel)
    return lax.switch(sel, [lambda _, f=f: _call_nograd(f) for f in fns]
                      + [lambda _: _call_nograd(default)], None)


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
       bias_attr=None, activation=None, name=None):
    """reference: python/paddle/static/nn/common.py fc.

    A program-BUILD api, like the reference: each call instantiates one fc
    layer (fresh ``create_parameter`` weights, auto-named, recorded into the
    active Program) — build the program once under ``program_guard`` and
    replay it with ``Executor.run``; don't call fc per training step."""
    import paddle_tpu as _p
    xs = [x] if isinstance(x, Tensor) else list(x)
    outs = []
    for i, xi in enumerate(xs):
        shape = tuple(xi.shape)
        nfd = num_flatten_dims if num_flatten_dims > 0 \
            else len(shape) + num_flatten_dims
        in_dim = 1
        for d in shape[nfd:]:
            in_dim *= int(d)
        w = _p.create_parameter([in_dim, size], str(xi.dtype),
                                attr=weight_attr)
        if len(shape) == nfd + 1:
            flat = xi  # trailing dim already flat; keeps dynamic batches
        else:
            # -1 for the (possibly None/dynamic) leading extent
            flat = xi.reshape([-1] + [int(d) for d in shape[1:nfd]]
                              + [in_dim])
        outs.append(flat.matmul(w))
    out = outs[0]
    for o in outs[1:]:
        out = out + o
    if bias_attr is not False:
        b = _p.create_parameter([size], str(out.dtype), attr=bias_attr,
                                is_bias=True)
        out = out + b
    if activation:
        import paddle_tpu.nn.functional as F
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """reference: python/paddle/static/nn/common.py embedding.
    Program-build api (see ``fc``): one call = one embedding table."""
    import paddle_tpu as _p
    import paddle_tpu.nn.functional as F
    w = _p.create_parameter(list(size), dtype, attr=param_attr)
    ids = input if isinstance(input, Tensor) else _p.to_tensor(input)
    return F.embedding(ids, w, padding_idx=padding_idx)


def sparse_embedding(*args, **kwargs):
    """reference: static/nn/common.py sparse_embedding — PS-backed lookup.
    Delegates to the PS-native API (distributed/ps/the_one_ps.py
    sparse_embedding(client, table, ids)); see tests/test_ps.py for the
    pull/push-on-backward flow."""
    from ..distributed.ps.the_one_ps import sparse_embedding as _se
    return _se(*args, **kwargs)


# -------------------------------------------------- legacy sequence ops
# (reference: python/paddle/static/nn/sequence_lod.py — the LoD-tensor
# forms become padded (batch, max_len, width) + ``lengths`` here: dynamic
# per-row lengths defeat XLA static shapes, and the reference's own
# padded-tensor branches of these kernels use exactly this layout.)

def continuous_value_model(input, cvm, use_cvm: bool = True):
    """CVM feature transform for rec-sys CTR models: the first two
    columns of each row are show/click counters. ``use_cvm=True`` keeps
    the width and rewrites them to ``log(show+1)`` and ``log(click+1) -
    log(show+1)``; ``use_cvm=False`` drops both columns. The backward
    writes the ``cvm`` values into the counter-column grads (reference
    grad-kernel contract).

    reference: python/paddle/static/nn/common.py:412 +
    phi/kernels/impl/cvm_kernel_impl.h (CvmComputeKernel /
    CvmGradComputeKernel).
    """
    import jax as _jax
    from .._core.autograd import apply as _apply
    from ..ops._registry import as_tensor as _as

    xt, ct = _as(input), _as(cvm)

    @_jax.custom_vjp
    def _cvm(x, cv):
        if use_cvm:
            c0 = jnp.log(x[:, :1] + 1)
            c1 = jnp.log(x[:, 1:2] + 1) - c0
            return jnp.concatenate([c0, c1, x[:, 2:]], axis=1)
        return x[:, 2:]

    def _fwd(x, cv):
        return _cvm(x, cv), (cv, x.shape[1])

    def _bwd(res, dy):
        cv, width = res
        if use_cvm:
            body = dy[:, 2:]
        else:
            body = dy
        dx = jnp.concatenate([cv[:, :2].astype(dy.dtype), body], axis=1)
        return dx, jnp.zeros_like(cv)

    _cvm.defvjp(_fwd, _bwd)
    return _apply(_cvm, xt, ct, name="cvm", nondiff=(1,))


def sequence_pool(input, pool_type: str, lengths=None, is_test=False,
                  pad_value: float = 0.0):
    """Pool each sequence of a padded (batch, max_len, width) tensor down
    to (batch, width). ``pool_type``: average | sum | sqrt (sum /
    sqrt(len)) | max | last | first; empty sequences yield ``pad_value``.

    reference: python/paddle/static/nn/sequence_lod.py:250 +
    funcs/sequence_pooling.cc (SequencePoolFunctor).
    """
    from .._core.autograd import apply as _apply
    from ..ops._registry import as_tensor as _as
    pt = pool_type.lower()
    if pt not in ("average", "sum", "sqrt", "max", "last", "first"):
        raise ValueError(f"unsupported pool_type {pool_type!r}")
    xt = _as(input)
    if xt.ndim != 3:
        raise ValueError("sequence_pool expects (batch, max_len, width) + "
                         "lengths (LoD-free padded form)")
    b, L = int(xt.shape[0]), int(xt.shape[1])
    args = [xt]
    if lengths is not None:
        args.append(_as(lengths))

    def fn(v, *rest):
        ln = rest[0].reshape(-1).astype(jnp.int32) if rest else \
            jnp.full((b,), L, jnp.int32)
        pos = jnp.arange(L)[None, :, None]
        valid = pos < ln[:, None, None]
        lnf = jnp.maximum(ln, 1).astype(v.dtype)[:, None]
        if pt in ("average", "sum", "sqrt"):
            s = jnp.where(valid, v, 0).sum(axis=1)
            out = {"average": s / lnf, "sum": s,
                   "sqrt": s / jnp.sqrt(lnf)}[pt]
        elif pt == "max":
            out = jnp.where(valid, v, -jnp.inf).max(axis=1)
        elif pt == "first":
            out = v[:, 0, :]
        else:  # last
            idx = jnp.maximum(ln - 1, 0)
            out = jnp.take_along_axis(
                v, idx[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        return jnp.where((ln > 0)[:, None], out,
                         jnp.asarray(pad_value, v.dtype))

    return _apply(fn, *args, name="sequence_pool")


def sequence_first_step(input, lengths=None):
    """reference: sequence_lod.py:367 — first-timestep pooling."""
    return sequence_pool(input, "first", lengths)


def sequence_last_step(input, lengths=None):
    """reference: sequence_lod.py:425 — last-valid-timestep pooling."""
    return sequence_pool(input, "last", lengths)


def sequence_conv(input, filter_weight, lengths=None, context_length=3,
                  context_start=None, bias=None, act=None):
    """Context-window convolution over padded (batch, max_len, width)
    sequences: each position concatenates ``context_length`` rows
    starting at offset ``context_start`` (default ``-context_length//2``,
    zeros outside the valid range) and multiplies
    ``filter_weight (context_length*width, num_filters)``.

    reference: python/paddle/static/nn/sequence_lod.py:23 +
    impl/sequence_conv_kernel_impl.h (ContextProjectFunctor + gemm).
    ``padding_trainable`` is not carried over — the reference marks it
    deprecated/untrainable-by-default; zero padding is the supported
    contract here.
    """
    from .._core.autograd import apply as _apply
    from ..ops._registry import as_tensor as _as
    xt, wt = _as(input), _as(filter_weight)
    if xt.ndim != 3:
        raise ValueError("sequence_conv expects (batch, max_len, width) + "
                         "lengths (LoD-free padded form)")
    start = -int(context_length // 2) if context_start is None \
        else context_start
    b, L = int(xt.shape[0]), int(xt.shape[1])
    args = [xt, wt]
    if bias is not None:
        args.append(_as(bias))
    if lengths is not None:
        args.append(_as(lengths))

    def fn(v, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if bias is not None else None
        ln = rest.pop(0).reshape(-1).astype(jnp.int32) if rest else \
            jnp.full((b,), L, jnp.int32)
        pos = jnp.arange(L)
        valid_row = pos[None, :] < ln[:, None]          # (B, L)
        cols = []
        for o in range(start, start + context_length):
            sh = jnp.roll(v, -o, axis=1)
            src = pos + o
            ok = (src >= 0) & (src < ln[:, None])
            cols.append(jnp.where(ok[..., None], sh, 0))
        col = jnp.concatenate(cols, axis=-1)            # (B, L, ctx*W)
        y = jnp.einsum("blc,cf->blf", col, wv)
        if bv is not None:
            y = y + bv
        if act == "relu":
            y = jnp.maximum(y, 0)
        elif act == "tanh":
            y = jnp.tanh(y)
        elif act is not None:
            raise ValueError(f"unsupported act {act!r}")
        return jnp.where(valid_row[..., None], y, 0)

    return _apply(fn, *args, name="sequence_conv")
