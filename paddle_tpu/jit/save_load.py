"""jit.save / jit.load (reference: python/paddle/jit/api.py:955 save,
translated_layer.py).

TPU-native serialization: parameters/buffers via the framework pickle format
plus the compiled program exported as StableHLO (jax.export) when input specs
are given — the analog of the reference's ProgramDesc+params artifact.
"""
from __future__ import annotations

import os
import pickle
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from .._core import autograd as ag
from ..framework.io import save as _save, load as _load
from ..nn.layer.layers import Layer
from .api import InputSpec, StaticFunction


def save(layer, path, input_spec=None, **configs):
    """reference: jit/api.py:955 — writes <path>.pdiparams (state) and
    <path>.pdmodel (StableHLO text, if exportable)."""
    state = layer.state_dict() if isinstance(layer, Layer) else {}
    _save(state, path + ".pdiparams")
    meta = {"class": type(layer).__name__,
            "input_spec": [(s.shape, str(s.dtype)) for s in input_spec]
            if input_spec else None}
    if input_spec and isinstance(layer, Layer):
        try:
            from jax import export as jexport
            params = layer.raw_parameters()
            buffers = layer.raw_buffers()

            def fn(params, buffers, *xs):
                with ag.no_grad():
                    out = layer.functional_call(
                        params,
                        *[Tensor(x, _internal=True) for x in xs],
                        buffers=buffers, training=False)
                if isinstance(out, (tuple, list)):
                    return tuple(o._value if isinstance(o, Tensor) else o
                                 for o in out)
                return out._value if isinstance(out, Tensor) else out

            def spec_args(symbolic):
                if symbolic and any(
                        d is None or d == -1
                        for s in input_spec for d in s.shape):
                    scope = jexport.SymbolicScope()
                    out = []
                    for si, s in enumerate(input_spec):
                        dims = ",".join(
                            f"b{si}_{di}" if d is None or d == -1 else str(d)
                            for di, d in enumerate(s.shape))
                        out.append(jax.ShapeDtypeStruct(
                            jexport.symbolic_shape(dims, scope=scope),
                            jnp.dtype(str(np.dtype(s.dtype)))))
                    return out
                return [jax.ShapeDtypeStruct(
                    tuple(d if d is not None and d != -1 else 1
                          for d in s.shape),
                    jnp.dtype(str(np.dtype(s.dtype)))) for s in input_spec]

            def do_export(symbolic):
                return jexport.export(jax.jit(fn))(
                    jax.tree_util.tree_map(
                        lambda a: jax.ShapeDtypeStruct(a.shape,
                                                       jnp.result_type(a)),
                        params),
                    jax.tree_util.tree_map(
                        lambda a: jax.ShapeDtypeStruct(a.shape,
                                                       jnp.result_type(a)),
                        buffers),
                    *spec_args(symbolic))

            try:
                # None dims export shape-polymorphic (any batch at serving
                # time); ops that can't be polymorphic fall back to 1
                exported = do_export(symbolic=True)
            except Exception:
                exported = do_export(symbolic=False)
            with open(path + ".pdmodel", "wb") as f:
                f.write(exported.serialize())
            meta["exported"] = True
        except Exception as e:  # export is best-effort; params always saved
            meta["exported"] = False
            meta["export_error"] = str(e)
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer(Layer):
    """reference: jit/translated_layer.py — a loaded inference program."""

    def __init__(self, state_dict, exported=None):
        super().__init__()
        self._state = state_dict
        self._exported = exported

    def forward(self, *args):
        if self._exported is None:
            raise RuntimeError(
                "this artifact was saved without an exported program "
                "(no input_spec at save time); only state_dict is available")
        from jax import export as jexport
        raw = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
               for a in args]
        params = {k: v._value for k, v in self._state.items()}
        out = self._exported.call(params, {}, *raw)
        if isinstance(out, (tuple, list)):
            return tuple(Tensor(o, _internal=True) for o in out)
        return Tensor(out, _internal=True)

    def state_dict(self, *a, **k):
        return dict(self._state)


def load(path, **configs):
    """reference: python/paddle/jit/api.py load."""
    state = _load(path + ".pdiparams")
    exported = None
    model_file = path + ".pdmodel"
    if os.path.exists(model_file):
        try:
            from jax import export as jexport
            with open(model_file, "rb") as f:
                exported = jexport.deserialize(f.read())
        except Exception:
            exported = None
    return TranslatedLayer(state, exported)
