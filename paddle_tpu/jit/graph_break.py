"""SOT-equivalent graph-break recovery: compiled regions around eager breaks.

Re-design of the reference's SOT executor (reference:
python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py — a
2,525-LoC CPython bytecode simulator that builds a FunctionGraph and, on a
graph break, emits resume bytecode so the rest of the function still runs
compiled). The TPU-native equivalent works at the AST/statement level
instead of the bytecode level: when ``jax.jit`` tracing hits a
concretization error, the function body is split at the breaking top-level
statement into

    [compiled prefix] -> [eager break statement] -> [compiled suffix]

and re-split recursively if another statement inside a compiled region
breaks. Regions are memoized per input signature at the
:class:`~paddle_tpu.jit.api.StaticFunction` level; a single untraceable
statement no longer de-compiles the matmul regions around it.

Mechanics:
- Region code executes via ``exec`` in a merged globals+locals namespace
  (the eager break statement uses the identical namespace, so name
  resolution — including comprehension scopes — matches plain Python).
- ``return`` anywhere in a region is rewritten to ``raise _ReturnSignal``;
  reaching it stops the region exactly like a real return (at trace time
  for compiled regions — sound, because reaching it cannot depend on
  tensor values without first raising the very concretization error that
  triggers a further split).
- Values crossing a region boundary: tensors/arrays stay dynamic jit
  arguments; everything else is wrapped ``jax.tree_util.register_static``
  so it rides the jit cache key (the guard semantics of SOT: a changed
  static value retraces).
- Autograd ACROSS regions (the reference keeps compiled regions live
  under autograd — opcode_executor.py resumes with grad state): each
  compiled region is recorded as ONE tape node whose vjp is the region's
  ``jax.vjp`` — grad-tracked env tensors and Layer parameters enter as
  differentiated jit arguments, region outputs carry the node, and the
  eager break statement records per-op nodes as usual, so ``backward()``
  walks the whole splice. ``create_graph=True`` through a region is not
  supported (the region node has no re-traceable primitive).
- Layers bound in the env (e.g. ``self`` of a Layer.forward): their
  parameters/buffers are passed as *dynamic* jit inputs and patched into
  the module during tracing (the ``functional_call`` idiom,
  nn/layer/layers.py:326), so optimizer updates are picked up without
  retracing and param gradients flow; in-trace buffer mutations (BN
  running stats) are captured as region outputs and written back.

Scope limits (whole-function eager fallback otherwise): no
generators/async, no writes to closure variables, no grad-tracked
tensors captured via globals/closure (only env/args/Layer state is
differentiated), no Layers nested inside containers (top-level env
bindings only).
"""
from __future__ import annotations

import ast
import copy
import dataclasses
import inspect
import textwrap
import traceback
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from .._core import autograd as ag


class SplitUnsupported(Exception):
    """This function/break-site cannot be split — caller should fall back
    to whole-function eager execution."""


class _ReturnSignal(Exception):
    def __init__(self, value):
        self.value = value


@jax.tree_util.register_static
@dataclasses.dataclass(frozen=True)
class _Static:
    """A non-tensor value crossing a region boundary: part of the jit
    cache key (treedef), so changing it retraces — SOT's value guard."""
    value: Any


def _wrap(v, deny_layers=False, dynamic_scalars=False):
    """Classify env values for the jit boundary: tensors dynamic, the
    rest static (hashable) or unsupported. ``deny_layers`` rejects Layer
    instances (nested in containers or flowing OUT of a region) — their
    parameters would be baked as stale constants (inputs) or carry
    tracers (outputs); only top-level env Layers get the dynamic-state
    treatment in :class:`_JitSegment`.

    ``dynamic_scalars`` (region INPUTS only): Python floats cross as
    weak-typed 0-d arrays instead of static guards. Floats are
    overwhelmingly data-derived (``.item()`` values — the archetypal
    break) and churn every batch; as statics they would retrace per call
    until the churn guard poisons the split. As dynamics the region
    compiles once; float control flow inside just splits further (the
    failing statement goes eager, the rest stays compiled). Ints/bools
    stay static: they are overwhelmingly structural (shapes, counts,
    flags) and low-cardinality. Weak typing (``jnp.asarray`` without a
    dtype) preserves Python-scalar promotion — ``bf16 * n`` stays bf16."""
    if isinstance(v, (Tensor, jax.Array, np.ndarray)):
        return v
    if v is None:
        return None
    if dynamic_scalars and isinstance(v, float):
        return jnp.asarray(v)
    if deny_layers:
        from ..nn.layer.layers import Layer
        if isinstance(v, Layer):
            raise SplitUnsupported(
                "a Layer nested in a container (or created inside a "
                "compiled region) crosses a graph-break boundary")
    if isinstance(v, tuple) and hasattr(v, "_fields"):  # namedtuple
        v2 = type(v)(*(_wrap(x, deny_layers, dynamic_scalars) for x in v))
        return v2
    if isinstance(v, (list, tuple)):
        return type(v)(_wrap(x, deny_layers, dynamic_scalars) for x in v)
    if isinstance(v, dict):
        return {k: _wrap(x, deny_layers, dynamic_scalars)
                for k, x in v.items()}
    try:
        hash(v)
    except TypeError:
        raise SplitUnsupported(
            f"unhashable non-tensor value of type {type(v).__name__} "
            f"crosses a graph-break boundary")
    return _Static(v)


def _unwrap(v):
    if isinstance(v, _Static):
        return v.value
    if isinstance(v, tuple) and hasattr(v, "_fields"):  # namedtuple
        return type(v)(*(_unwrap(x) for x in v))
    if isinstance(v, (list, tuple)):
        return type(v)(_unwrap(x) for x in v)
    if isinstance(v, dict):
        return {k: _unwrap(x) for k, x in v.items()}
    return v


def _has_grad_tracked(v, depth: int = 4) -> bool:
    """Scan captured state for grad-tracked Tensors. Containers too big
    or too deep to scan are treated AS grad-tracked (reject the split):
    a silently-missed trainable would mean silently-wrong gradients,
    while a false positive only costs the eager fallback."""
    if isinstance(v, Tensor):
        return not v.stop_gradient
    if isinstance(v, (list, tuple, dict)):
        items = list(v.values()) if isinstance(v, dict) else list(v)
        if depth <= 0 or len(items) > 256:
            return True   # unscannable — assume the worst
        return any(_has_grad_tracked(x, depth - 1) for x in items)
    return False


class _ReturnRewriter(ast.NodeTransformer):
    """``return X`` -> ``raise _ReturnSignal_(X)`` at region level; nested
    function/class bodies keep their own returns."""

    def visit_FunctionDef(self, node):
        return node

    def visit_AsyncFunctionDef(self, node):
        return node

    def visit_ClassDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node

    def visit_Return(self, node):
        value = node.value or ast.Constant(value=None)
        call = ast.Call(
            func=ast.Name(id="_ReturnSignal_", ctx=ast.Load()),
            args=[value], keywords=[])
        return ast.copy_location(
            ast.Raise(exc=ast.copy_location(call, node), cause=None), node)


def _root_name(node) -> Optional[str]:
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _collect_names(stmts) -> Tuple[set, set]:
    """(loaded names, stored names) across the statements, nested scopes
    included (conservative for stores: extra names are filtered by an
    ``in namespace`` check at runtime). An aug-assign target and the root
    of a subscript/attribute store are both load AND store: ``h += n`` and
    ``h[0] = n`` read h and must also propagate the updated h."""
    loads, stores = set(), set()
    for stmt in stmts:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name):
                (loads if isinstance(node.ctx, ast.Load)
                 else stores).add(node.id)
            elif isinstance(node, ast.AugAssign):
                root = _root_name(node.target)
                if root is not None:
                    loads.add(root)
                    stores.add(root)
            elif isinstance(node, (ast.Subscript, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Store):
                root = _root_name(node)
                if root is not None:
                    loads.add(root)
                    stores.add(root)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                stores.add(node.name)
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                for alias in node.names:
                    stores.add((alias.asname or
                                alias.name.split(".")[0]))
            elif isinstance(node, ast.ExceptHandler) and node.name:
                stores.add(node.name)
    return loads, stores


def _compile_stmts(stmts, filename):
    body = [_ReturnRewriter().visit(copy.deepcopy(s)) for s in stmts]
    module = ast.Module(body=body, type_ignores=[])
    ast.fix_missing_locations(module)
    return compile(module, filename, "exec")


class _Segment:
    """A contiguous run of top-level statements. ``globals_fn`` returns
    the LIVE merged globals (module globals + closure snapshot), so eager
    execution sees rebound module globals like plain Python would;
    compiled regions bake them at trace time — the same semantics jax.jit
    gives whole functions."""

    def __init__(self, stmts, globals_fn, filename):
        self.stmts = stmts
        self.lo = min(s.lineno for s in stmts)
        self.hi = max(getattr(s, "end_lineno", s.lineno) for s in stmts)
        self._globals_fn = globals_fn
        self._filename = filename
        self._loads, self._stores = _collect_names(stmts)
        self._code = _compile_stmts(stmts, filename)

    def _exec(self, env):
        """Run the statements over ``env``; returns (updates, flag, rv)."""
        g = self._globals_fn()
        g["_ReturnSignal_"] = _ReturnSignal
        g.update(env)
        try:
            exec(self._code, g)
            flag, rv = False, None
        except _ReturnSignal as s:
            flag, rv = True, s.value
        updates = {k: g[k] for k in self._stores if k in g}
        return updates, flag, rv

    def run_eager(self, env, amp_ctx):
        with amp_ctx():
            updates, flag, rv = self._exec(env)
        env.update(updates)
        return flag, rv


class _EagerSegment(_Segment):
    kind = "eager"

    run = _Segment.run_eager


class _JitSegment(_Segment):
    kind = "jit"

    # distinct static boundary values retrace (that IS the guard); past
    # this many entries the break pattern is value-churning (e.g. a
    # tensor-derived int changing every batch) and compiling is a net
    # loss — the caller poisons the split and completes eagerly
    MAX_TRACES = 8

    def __init__(self, stmts, globals_fn, filename):
        super().__init__(stmts, globals_fn, filename)
        self._jitted = None
        self._amp_ctx = None
        self._trace_count = 0
        # per-call layer state, read by _traced at trace time only (a
        # changed layer identity is a changed _Static in the treedef, so
        # cache hits never see a stale map)
        self._cur_layer_maps = None
        # id(layer) -> (params, buffers) enumeration, cached: walking a
        # big module's tree + sorting every step would dominate the
        # split-path hot loop. A param/buffer ADDED to the module after
        # the first split call is not picked up — same accepted
        # staleness class as rebound globals (see module docstring)
        self._layer_enum = {}

    def cache_churned(self) -> bool:
        return self._trace_count > self.MAX_TRACES

    def _traced(self, diff_vals, rest, dyn_vals, treedef, diff_pos,
                lp_diff_spec, lp_dyn_spec):
        """The jitted region body. ``diff_vals``: raw values of the
        differentiated inputs (env tensor leaves then layer params);
        ``rest``: non-diff env leaves (None at diff positions);
        ``dyn_vals``: raw values of frozen layer params + buffers.
        Statics: env treedef, diff positions, and the (layer, name)
        specs. Returns ``(primal_diff_outputs, aux)`` — the shape
        ``jax.vjp(..., has_aux=True)`` differentiates."""
        self._trace_count += 1
        n_env = len(diff_pos)
        leaves = list(rest)
        for j, p in enumerate(diff_pos):
            leaves[p] = Tensor(diff_vals[j], stop_gradient=True,
                               _internal=True)
        wenv = jax.tree_util.tree_unflatten(treedef, leaves)
        raw = {k: _unwrap(v) for k, v in wenv.items()}
        # patch layer params/buffers with the traced inputs (the
        # functional_call idiom): restore originals in finally so no
        # tracer ever survives in module state
        patched = []  # (tensor, old_value, old_node, old_oi, set_value)
        mut_spec, mut_vals = [], []
        try:
            for j, (li, pn) in enumerate(lp_diff_spec):
                t = self._cur_layer_maps[li][pn]
                patched.append((t, t._value, t._node, t._out_index,
                                diff_vals[n_env + j]))
                t._value = diff_vals[n_env + j]
                t._node, t._out_index = None, 0
            for j, (li, pn) in enumerate(lp_dyn_spec):
                t = self._cur_layer_maps[li][pn]
                patched.append((t, t._value, t._node, t._out_index,
                                dyn_vals[j]))
                t._value = dyn_vals[j]
                t._node, t._out_index = None, 0
            with self._amp_ctx(), ag.no_grad():
                updates, flag, rv = self._exec(raw)
            # in-trace mutations of layer state (BN running stats,
            # in-place param writes) become extra region outputs,
            # written back by run(); identity check against the patched
            # value keeps this free when nothing mutates
            for idx, (t, _, _, _, setv) in enumerate(patched):
                if t._value is not setv:
                    mut_spec.append(idx)
                    mut_vals.append(t._value)
        finally:
            for t, old, node, oi, _ in patched:
                t._value, t._node, t._out_index = old, node, oi
        tree = ({k: _wrap(v, deny_layers=True) for k, v in updates.items()},
                _wrap(flag), _wrap(rv, deny_layers=True),
                _Static(tuple(mut_spec)), list(mut_vals))
        oflat, otreedef = jax.tree_util.tree_flatten(
            tree, is_leaf=lambda x: isinstance(x, Tensor))
        odiff = tuple(i for i, v in enumerate(oflat)
                      if isinstance(v, Tensor)
                      and ag._is_diff_dtype(v.dtype))
        od = set(odiff)
        primal = tuple(oflat[i]._value for i in odiff)
        aux_leaves = [None if i in od else v for i, v in enumerate(oflat)]
        return primal, (aux_leaves, _Static((otreedef, odiff)))

    def run(self, env, amp_ctx):
        from ..nn.layer.layers import Layer
        if self._amp_ctx is None:
            self._amp_ctx = amp_ctx
        # -- partition the env: Layers get dynamic-state handling, the
        # rest the usual wrap (nested Layers rejected -> SplitUnsupported)
        wrapped = {}
        layers = []  # (name, layer, [(pname, ptensor)], [(bufname, btensor)])
        for k in self._loads:
            if k not in env:
                continue
            v = env[k]
            if isinstance(v, Layer):
                enum = self._layer_enum.get(id(v))
                if enum is None:
                    ps = sorted(dict(v.named_parameters()).items())
                    bs = sorted(dict(v.named_buffers()).items())
                    enum = (ps, bs,
                            {**dict(ps), **{"buf:" + bn: b
                                            for bn, b in bs}})
                    self._layer_enum[id(v)] = enum
                layers.append((k, v, enum[0], enum[1], enum[2]))
                wrapped[k] = _Static(v)
            else:
                wrapped[k] = _wrap(v, deny_layers=True,
                                   dynamic_scalars=True)
        layers.sort(key=lambda e: e[0])  # deterministic (li, pn) specs
        flat, treedef = jax.tree_util.tree_flatten(
            wrapped, is_leaf=lambda x: isinstance(x, Tensor))
        grad_on = ag.is_grad_enabled()
        diff_pos = tuple(
            i for i, v in enumerate(flat)
            if grad_on and isinstance(v, Tensor) and not v.stop_gradient
            and ag._is_diff_dtype(v.dtype))
        dset = set(diff_pos)
        diff_tensors = [flat[i] for i in diff_pos]
        rest = [None if i in dset else v for i, v in enumerate(flat)]
        lp_diff, lp_dyn = [], []  # (li, name, tensor)
        for li, (_, _, ps, bs, _) in enumerate(layers):
            for pn, p in ps:
                if grad_on and not p.stop_gradient and \
                        ag._is_diff_dtype(p.dtype):
                    lp_diff.append((li, pn, p))
                else:
                    lp_dyn.append((li, pn, p))
            for bn, b in bs:
                lp_dyn.append((li, "buf:" + bn, b))
        diff_tensors += [p for _, _, p in lp_diff]
        lp_diff_spec = tuple((li, pn) for li, pn, _ in lp_diff)
        lp_dyn_spec = tuple((li, pn) for li, pn, _ in lp_dyn)
        dyn_vals = [p._value for _, _, p in lp_dyn]
        self._cur_layer_maps = [m for (_, _, _, _, m) in layers]

        if self._jitted is None:
            self._jitted = jax.jit(self._traced,
                                   static_argnums=(3, 4, 5, 6))
        dv = tuple(t._value for t in diff_tensors)
        if diff_tensors:
            primals, vjp_fn, aux = jax.vjp(
                lambda d: self._jitted(d, rest, dyn_vals, treedef,
                                       diff_pos, lp_diff_spec,
                                       lp_dyn_spec),
                dv, has_aux=True)
        else:
            primals, aux = self._jitted(dv, rest, dyn_vals, treedef,
                                        diff_pos, lp_diff_spec,
                                        lp_dyn_spec)
            vjp_fn = None
        aux_leaves, stat = aux
        otreedef, odiff = stat.value
        # the region is ONE tape node; its vjp routes cotangents to the
        # diff env tensors and layer params (SOT's compiled-region-under-
        # autograd capability, reference opcode_executor.py)
        node = None
        if vjp_fn is not None and odiff:
            out_meta = [(tuple(p.shape), p.dtype) for p in primals]

            def _region_vjp(cots, _vjp=vjp_fn):
                (gs,) = _vjp(tuple(cots))
                return list(gs)
            node = ag.Node(_region_vjp, diff_tensors, out_meta, True,
                           name=f"jit_region@{self.lo}")
        leaves = list(aux_leaves)
        for k, i in enumerate(odiff):
            t = Tensor(primals[k], stop_gradient=node is None,
                       _internal=True)
            if node is not None:
                t._node, t._out_index = node, k
            leaves[i] = t
        wup, wflag, wrv, mut_stat, mut_vals = jax.tree_util.tree_unflatten(
            otreedef, leaves)
        # write back in-trace layer-state mutations (BN running stats)
        patch_list = lp_diff + lp_dyn
        for ms, mv in zip(mut_stat.value, mut_vals):
            patch_list[ms][2]._inplace_assign(
                mv._value if isinstance(mv, Tensor) else mv)
        env.update({k: _unwrap(v) for k, v in wup.items()})
        return bool(_unwrap(wflag)), _unwrap(wrv)


def _concretization_errors():
    import jax.errors as jerr
    return (jerr.JAXTypeError, jerr.NonConcreteBooleanIndexError)


class SplitProgram:
    """Executable splice of compiled regions and eager break statements
    for one function, refined lazily as break sites are discovered."""

    MAX_BREAKS = 16

    def __init__(self, fn: Callable, amp_key=None):
        self._fn = getattr(fn, "__func__", fn)
        self._self = getattr(fn, "__self__", None)
        code = self._fn.__code__
        if code.co_freevars:
            closure = self._fn.__closure__ or ()
            # read-only closure use is supported by injecting a snapshot;
            # writes would silently diverge from real cell semantics
            self._closure = {}
            for name, cell in zip(code.co_freevars, closure):
                try:
                    self._closure[name] = cell.cell_contents
                except ValueError:
                    raise SplitUnsupported(f"empty closure cell {name!r}")
        else:
            self._closure = {}
        try:
            src = textwrap.dedent(inspect.getsource(self._fn))
        except (OSError, TypeError) as e:
            raise SplitUnsupported(f"source unavailable: {e}")
        try:
            tree = ast.parse(src)
        except SyntaxError as e:
            raise SplitUnsupported(f"unparseable source: {e}")
        if not tree.body or not isinstance(tree.body[0], ast.FunctionDef):
            raise SplitUnsupported("not a plain function definition")
        node = tree.body[0]
        ast.increment_lineno(tree, code.co_firstlineno - node.lineno)
        for sub in ast.walk(node):
            if isinstance(sub, (ast.Yield, ast.YieldFrom, ast.Await)):
                raise SplitUnsupported("generators/async not splittable")
            if isinstance(sub, (ast.Global, ast.Nonlocal)):
                raise SplitUnsupported("global/nonlocal not splittable")
            if isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store) \
                    and sub.id in self._closure:
                raise SplitUnsupported(
                    f"write to closure variable {sub.id!r}")
        self._filename = code.co_filename
        self._name = code.co_name
        self._sig = inspect.signature(self._fn)
        # grad-tracked tensors captured via globals/closure would lose
        # their tape in the no-tape compiled regions — unsupported
        # (checked against names the body actually loads; a later
        # rebinding of such a global is an accepted staleness edge,
        # documented above)
        body_loads, _ = _collect_names(node.body)
        for nm in body_loads:
            v = self._closure.get(nm, self._fn.__globals__.get(nm))
            if _has_grad_tracked(v):
                raise SplitUnsupported(
                    f"captured variable {nm!r} holds a grad-tracked "
                    f"Tensor; split regions are no-tape")
        from .api import _amp_ctx as _mk_amp_ctx
        self._amp_ctx = lambda: _mk_amp_ctx(amp_key)
        self._breaks = 0
        # split execution has run side effects for this signature class;
        # future calls must go whole-function eager instead
        self.poisoned = False

        def globals_fn():
            g = dict(self._fn.__globals__)
            g.update(self._closure)
            return g
        self._globals_fn = globals_fn
        self.segments: List[_Segment] = [
            _JitSegment(list(node.body), globals_fn, self._filename)]

    # -- execution --
    def __call__(self, args, kwargs):
        """Run the splice. Mid-call problems never re-run the function
        (earlier segments' side effects already happened): the CURRENT
        call completes eagerly from the failing segment onward, and the
        program marks itself ``poisoned`` so the caller routes future
        calls of this signature to whole-function eager."""
        env = self._bind(args, kwargs)
        i = 0
        while i < len(self.segments):
            seg = self.segments[i]
            if seg.kind == "eager":
                flag, rv = seg.run(env, self._amp_ctx)
            elif seg.cache_churned():
                # static boundary values change every call — compiling
                # is a net loss; finish eagerly and poison
                self.poisoned = True
                flag, rv = seg.run_eager(env, self._amp_ctx)
            else:
                try:
                    flag, rv = seg.run(env, self._amp_ctx)
                except _concretization_errors() as e:
                    try:
                        self._split_at(i, e)
                        continue
                    except SplitUnsupported:
                        self.poisoned = True
                        flag, rv = seg.run_eager(env, self._amp_ctx)
                except SplitUnsupported:
                    # unhashable boundary value (raised by _wrap; user
                    # exceptions propagate untouched) — finish this call
                    # eagerly, poison for the future
                    self.poisoned = True
                    flag, rv = seg.run_eager(env, self._amp_ctx)
            if flag:
                return rv
            i += 1
        return None

    def _bind(self, args, kwargs) -> Dict[str, Any]:
        if self._self is not None:
            args = (self._self,) + tuple(args)
        bound = self._sig.bind(*args, **kwargs)
        bound.apply_defaults()
        return dict(bound.arguments)

    # -- refinement --
    def _split_at(self, i: int, err: BaseException):
        if self._breaks >= self.MAX_BREAKS:
            raise SplitUnsupported(
                f"more than {self.MAX_BREAKS} break sites")
        seg = self.segments[i]
        lineno = self._find_break_lineno(err, seg)
        if lineno is None:
            raise SplitUnsupported(
                "could not locate the break site in the traceback")
        idx = None
        for j, stmt in enumerate(seg.stmts):
            if stmt.lineno <= lineno <= getattr(stmt, "end_lineno",
                                                stmt.lineno):
                idx = j
                break
        if idx is None:
            raise SplitUnsupported(
                f"break line {lineno} outside segment statements")
        new: List[_Segment] = []
        if seg.stmts[:idx]:
            new.append(_JitSegment(seg.stmts[:idx], self._globals_fn,
                                   self._filename))
        new.append(_EagerSegment([seg.stmts[idx]], self._globals_fn,
                                 self._filename))
        if seg.stmts[idx + 1:]:
            new.append(_JitSegment(seg.stmts[idx + 1:], self._globals_fn,
                                   self._filename))
        self.segments[i:i + 1] = new
        self._breaks += 1

    def _find_break_lineno(self, err, seg) -> Optional[int]:
        """Outermost traceback frame inside this function's code within
        the segment's line range. Region code executes with the original
        filename and linenos (name ``<module>``); the first failure comes
        from the un-split function itself (name == the function's)."""
        for fr in traceback.extract_tb(err.__traceback__):
            if fr.filename != self._filename:
                continue
            if fr.name not in (self._name, "<module>"):
                continue
            if fr.lineno is not None and seg.lo <= fr.lineno <= seg.hi:
                return fr.lineno
        return None


