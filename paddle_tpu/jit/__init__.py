"""paddle_tpu.jit (reference: python/paddle/jit/)."""
from .api import (  # noqa: F401
    to_static, not_to_static, ignore_module, enable_to_static, InputSpec,
    StaticFunction, TrainStep, EvalStep, train_step,
)
from .save_load import save, load, TranslatedLayer  # noqa: F401
