"""paddle_tpu.jit (reference: python/paddle/jit/)."""
from .api import (  # noqa: F401
    to_static, not_to_static, ignore_module, enable_to_static, InputSpec,
    StaticFunction, TrainStep, EvalStep, train_step,
)
from .save_load import save, load, TranslatedLayer  # noqa: F401


_logging_state = {"code_level": 100, "verbosity": 0}


def set_code_level(level=100, also_to_stdout=False):
    """reference: jit/dy2static/logging_utils.py set_code_level — controls
    transformed-code logging. The trace-based to_static has no generated
    code to print; the knob is accepted and recorded."""
    _logging_state["code_level"] = int(level)


def set_verbosity(level=0, also_to_stdout=False):
    """reference: jit/dy2static/logging_utils.py set_verbosity."""
    _logging_state["verbosity"] = int(level)
