"""jit: to_static + compiled train step.

TPU-native re-design of the reference's dy2static stack
(reference: python/paddle/jit/api.py:197 to_static; SOT bytecode tracer
python/paddle/jit/sot/ — 2,500-line opcode interpreter). On XLA none of that
machinery is needed: Tensors are jax pytree nodes, so ``jax.jit`` traces the
same imperative code directly. What remains of the reference's semantics is
guard-based retracing (shape/dtype guards == jax's abstract-value cache keys)
and graph-break-free capture of Layer state (via Layer.functional_call).

``train_step`` is the performance path: forward+backward+optimizer update in
ONE compiled XLA program with donated buffers — the analog of the reference's
whole-Program executor path (SURVEY §3.3) but fused end-to-end.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from .._core import autograd as ag
from .._core.random import rng_scope, next_rng_key
from ..nn.layer.layers import Layer


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape=None, dtype="float32", name=None,
                 stop_gradient=True):
        self.shape = shape
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _raw(x):
    if isinstance(x, Tensor):
        return x._value
    return x


def _current_amp_key():
    """Snapshot of the thread-local autocast state — used as a static jit
    argument so entering/exiting auto_cast retraces instead of silently
    hitting a cached program."""
    from ..amp.auto_cast import (is_auto_cast_enabled, get_amp_dtype,
                                 get_amp_level)
    if not is_auto_cast_enabled():
        return None
    return (str(get_amp_dtype()), get_amp_level())


def _amp_ctx(amp_key):
    import contextlib
    if amp_key is None:
        return contextlib.nullcontext()
    from ..amp.auto_cast import auto_cast
    return auto_cast(level=amp_key[1], dtype=amp_key[0])


class StaticFunction:
    """Callable produced by to_static (reference: dy2static
    program_translator.py StaticFunction). Guards = jax jit cache keys.

    Graph-break fallback (reference: SOT's graph-break + eager resume,
    python/paddle/jit/sot/opcode_translator/executor/opcode_executor.py):
    data-dependent Python control flow on tensor VALUES cannot be traced by
    ``jax.jit`` — instead of surfacing a concretization error, the call
    falls back to eager execution with a one-time warning. Code that should
    stay compiled can use :mod:`paddle.static.nn` ``cond`` / ``while_loop``
    / ``switch_case``, which lower to ``lax`` control flow.
    """

    def __init__(self, fn: Callable, layer: Optional[Layer] = None,
                 input_spec=None, build_strategy=None, backend=None,
                 full_graph=True):
        self._fn = fn
        self._layer = layer
        self._input_spec = input_spec
        # graph breaks are per input-signature (shape/dtype/static-arg
        # guard), not whole-function: one untraceable input class must not
        # de-optimize signatures that compiled fine (reference SOT breaks
        # per-graph-site)
        self._eager_keys = set()
        # signature -> graph_break.SplitProgram: compiled prefix/suffix
        # regions around the eager break statements (SOT-equivalent
        # recovery); signatures absent here run whole-function eager
        self._split_programs = {}
        self._warned_break = False
        functools.update_wrapper(self, fn)

        if layer is not None:
            orig_forward = fn

            def traced(params, buffers, training, amp_key, args, kwargs):
                with _amp_ctx(amp_key), ag.no_grad():
                    # jax.jit differentiates; skip the tape
                    out, new_buffers = layer.functional_call(
                        params, *args, buffers=buffers, training=training,
                        capture_buffers=True, forward_fn=orig_forward,
                        **kwargs)
                return out, new_buffers
            self._jitted = jax.jit(traced, static_argnums=(2, 3))
        else:
            def traced(amp_key, args, kwargs):
                with _amp_ctx(amp_key), ag.no_grad():
                    return fn(*args, **kwargs)
            self._jitted = jax.jit(traced, static_argnums=(0,))

    @property
    def _cache_size(self):
        try:
            return self._jitted._cache_size()
        except Exception:
            return -1

    def _signature(self, args, kwargs):
        """Mirror of the jit cache key: Tensor leaves by (shape, dtype),
        everything else by value — so an eager-fallback decision applies to
        exactly the input class that failed to trace."""
        def leaf(x):
            if isinstance(x, Tensor):
                return (tuple(x.shape), str(x.dtype))
            if hasattr(x, "shape") and hasattr(x, "dtype"):
                return (tuple(x.shape), str(x.dtype))
            return repr(x)
        flat, treedef = jax.tree_util.tree_flatten(
            (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))
        training = self._layer.training if self._layer is not None else None
        return (tuple(leaf(x) for x in flat), str(treedef), training,
                _current_amp_key())

    def __call__(self, *args, **kwargs):
        # enable_to_static(False) is CALL-time (reference
        # ProgramTranslator.enable): already-decorated functions drop to
        # eager while the switch is off and recompile when it returns
        if not _to_static_enabled[0]:
            return self._fn(*args, **kwargs)
        # fast path: no graph break has ever occurred -> skip the
        # signature computation entirely (it is only needed to route
        # already-broken input classes to their recovery path)
        if self._eager_keys:
            sig = self._signature(args, kwargs)
            if sig in self._eager_keys:
                return self._run_broken(sig, args, kwargs)
        import jax.errors as jerr
        try:
            if self._layer is not None:
                params = self._layer.raw_parameters()
                buffers = self._layer.raw_buffers()
                out, new_buffers = self._jitted(params, buffers,
                                                self._layer.training,
                                                _current_amp_key(), args,
                                                kwargs)
                if new_buffers:
                    namedb = dict(self._layer.named_buffers())
                    for k, v in new_buffers.items():
                        namedb[k]._inplace_assign(v)
                return out
            return self._jitted(_current_amp_key(), args, kwargs)
        except (jerr.JAXTypeError,
                jerr.NonConcreteBooleanIndexError) as e:
            # JAXTypeError covers every tracer-concretization variant
            # (ConcretizationTypeError, TracerArrayConversionError,
            # TracerBool/IntegerConversionError). If the function is
            # genuinely broken the re-run below raises the real error.
            # data-dependent control flow: break the graph for THIS input
            # signature and recover SOT-style — compiled regions around
            # the eager break statement (graph_break.SplitProgram), or
            # whole-function eager where splitting is unsupported
            sig = self._signature(args, kwargs)
            self._eager_keys.add(sig)
            if not self._warned_break:
                import warnings
                self._warned_break = True
                warnings.warn(
                    f"to_static({getattr(self._fn, '__name__', '?')}): "
                    f"data-dependent Python control flow cannot be compiled "
                    f"({type(e).__name__}); falling back to eager execution "
                    f"at the break site (surrounding regions stay compiled "
                    f"where possible). Use paddle.static.nn.cond/while_loop "
                    f"to keep the whole function compiled.",
                    stacklevel=2)
            return self._run_broken(sig, args, kwargs)

    def _run_broken(self, sig, args, kwargs):
        """Recovery path for signatures that graph-broke: split execution
        (compiled regions + eager break statements) when supported, else
        whole-function eager. Grad-tracked inputs and Layer forwards are
        handled by the split path itself: compiled regions are recorded
        as single tape nodes and Layer params enter as dynamic
        differentiated inputs (graph_break._JitSegment), so a break
        inside a training forward keeps its prefix/suffix compiled."""
        from . import graph_break as gb
        sp = self._split_programs.get(sig, _NO_SPLIT)
        if sp is _NO_SPLIT:   # first broken call for this signature
            try:
                sp = gb.SplitProgram(self._fn, amp_key=_current_amp_key())
            except gb.SplitUnsupported:
                sp = None
            self._split_programs[sig] = sp
        if sp is not None:
            out = sp(args, kwargs)
            if sp.poisoned:
                # the split proved unviable mid-call (value churn,
                # unstable locals); THIS call completed correctly via
                # eager completion — future ones go whole-eager
                self._split_programs[sig] = None
            return out
        return self._fn(*args, **kwargs)

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    @property
    def forward(self):
        return self


_NO_SPLIT = object()   # sentinel: "no split decision made yet"

_to_static_enabled = [True]


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """reference: python/paddle/jit/api.py:197. Honors
    ``enable_to_static(False)`` (global dygraph switch) and
    ``@not_to_static`` marks — both return the function un-compiled,
    matching ProgramTranslator.enable semantics."""
    def decorate(f):
        if getattr(f, "_not_to_static", False) or \
                getattr(getattr(f, "forward", None), "_not_to_static",
                        False) or not _to_static_enabled[0]:
            return f
        if isinstance(f, Layer):
            sf = StaticFunction(f.forward, layer=f, input_spec=input_spec)
            f.forward = sf
            return f
        # bound method of a Layer?
        self_obj = getattr(f, "__self__", None)
        if isinstance(self_obj, Layer):
            return StaticFunction(f, layer=self_obj, input_spec=input_spec)
        return StaticFunction(f, input_spec=input_spec)
    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    """reference: jit/api.py not_to_static — mark a function/Layer so
    to_static leaves it eager (SOT's skip list). Bound methods are marked
    through their underlying function (method objects reject attributes).
    """
    target = getattr(fn, "__func__", fn)
    target._not_to_static = True
    return fn


def ignore_module(modules):
    pass


def enable_to_static(flag: bool):
    """reference: jit/api.py enable_to_static / ProgramTranslator.enable —
    global switch: when False, to_static returns functions unwrapped (pure
    dygraph), the standard debugging escape hatch."""
    _to_static_enabled[0] = bool(flag)


def _write_back_opt_state(optimizer, trainable, state, step_count):
    """Map functional state {pname: {slot: arr}} into
    optimizer._accumulators {slot: {id(param): Tensor}} (+ global step)."""
    import jax.numpy as _jnp
    for pname, slots in state.items():
        p = trainable.get(pname)
        if p is None:
            continue
        for slot, val in slots.items():
            d = optimizer._accumulators.setdefault(slot, {})
            v = _jnp.array(val)
            if id(p) in d:
                d[id(p)]._inplace_assign(v)
            else:
                d[id(p)] = Tensor(v, _internal=True)
    optimizer._global_step = max(optimizer._global_step, int(step_count))


def _snapshot_model(model):
    """(trainable params, frozen raw values, donated param copies, buffer
    copies) — the state a compiled step needs. Copies: step arguments are
    donated to XLA and the model's Tensors must stay valid for eager
    access mid-training."""
    named = dict(model.named_parameters())
    trainable = {k: p for k, p in named.items() if not p.stop_gradient}
    frozen = {k: p._value for k, p in named.items() if p.stop_gradient}
    params = {k: jnp.array(p._value) for k, p in trainable.items()}
    buffers = {k: jnp.array(v) for k, v in model.raw_buffers().items()}
    return named, trainable, frozen, params, buffers


def _capture_amp_state():
    """amp autocast config is trace-time, not part of jit cache keys."""
    from ..amp.auto_cast import (is_auto_cast_enabled, get_amp_dtype,
                                 get_amp_level)
    return (is_auto_cast_enabled(), str(get_amp_dtype()), get_amp_level())


def _unscale_and_check(grads, scale, use_scaler):
    """Undo loss scaling and detect non-finite grads (inside the compiled
    program)."""
    if not use_scaler:
        return grads, jnp.asarray(False)
    inv = 1.0 / scale
    grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
    found_inf = jnp.any(jnp.stack([
        ~jnp.all(jnp.isfinite(g.astype(jnp.float32)))
        for g in jax.tree_util.tree_leaves(grads)]))
    return grads, found_inf


def _build_forward_loss(model, loss_fn, frozen, amp_state, use_scaler):
    """forward + loss closure shared by the fused TrainStep and the
    offloaded variant (distributed.sharding.offload.OffloadTrainStep)."""
    amp_enabled, amp_dtype, amp_level = amp_state

    def forward_loss(p, buffers, rng, inputs, labels, scale):
        allp = dict(frozen)
        allp.update(p)
        ctx = rng_scope(rng)
        from ..amp.auto_cast import auto_cast as _autocast
        import contextlib
        amp_ctx = _autocast(level=amp_level, dtype=amp_dtype) \
            if amp_enabled else contextlib.nullcontext()
        with ctx, amp_ctx, ag.no_grad():
            # no_grad skips the python tape; jax.value_and_grad
            # differentiates the traced program itself
            out, new_buffers = model.functional_call(
                allp,
                *[Tensor(b, _internal=True) for b in inputs],
                buffers=buffers, training=True,
                capture_buffers=True)
            outs = out if isinstance(out, (tuple, list)) else (out,)
            lbls = [Tensor(l, _internal=True) for l in labels]
            loss = loss_fn(*outs, *lbls)
            lv = loss._value if isinstance(loss, Tensor) else loss
        out_vals = tuple(o._value if isinstance(o, Tensor) else o
                         for o in outs)
        if use_scaler:
            lv_scaled = lv * scale
            return lv_scaled, (new_buffers, out_vals, lv)
        return lv, (new_buffers, out_vals, lv)

    return forward_loss


class TrainStep:
    """One fused XLA program per (shapes, training-config): forward + loss +
    grad + (scaled/accumulated) optimizer update + buffer update, with
    params/opt-state donated.

    - ``scaler``: a GradScaler — loss scaling, grad unscaling, non-finite
      skip, and dynamic scale update all happen INSIDE the compiled step
      (lax.cond), with only the scalar scale/counters living host-side.
    - ``accumulate_steps``: gradient accumulation (reference:
      gradient_merge_optimizer) — grads accumulate in device buffers and the
      optimizer applies every N calls.
    - ``return_outputs``: also return the forward outputs so callers (hapi
      metrics) don't need a second forward.

    Usage:
        step = TrainStep(model, loss_fn, optimizer)
        loss = step(x, y)          # device-resident fast path
        step.sync_to_model()       # write params back into the Layer
    """

    def __init__(self, model: Layer, loss_fn, optimizer, scaler=None,
                 accumulate_steps=1, return_outputs=False):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.scaler = scaler if (scaler is not None and
                                 getattr(scaler, "_enable", True)) else None
        self.accumulate_steps = int(accumulate_steps)
        self.return_outputs = return_outputs
        (named, self._trainable, self._frozen, self.params,
         self.buffers) = _snapshot_model(model)
        init_state, self._opt_update = optimizer.build_functional(named)
        self.opt_state = init_state(self.params)
        if self.accumulate_steps > 1:
            self.opt_state = {
                "opt": self.opt_state,
                "acc": jax.tree_util.tree_map(
                    lambda p: jnp.zeros(jnp.shape(p), jnp.float32),
                    self.params)}
        self._step_count = 0
        self._amp_state = _capture_amp_state()
        self._compiled = jax.jit(self._make_fn(), donate_argnums=(0, 1, 2))

    def _make_fn(self):
        loss_fn = self.loss_fn
        opt_update = self._opt_update
        use_scaler = self.scaler is not None
        accum = self.accumulate_steps

        forward_loss = _build_forward_loss(
            self.model, loss_fn, self._frozen, self._amp_state, use_scaler)

        def step_fn(params, opt_state, buffers, step, lr, rng, scale,
                    inputs, labels):
            (_, (new_buffers, out_vals, loss_val)), grads = \
                jax.value_and_grad(forward_loss, has_aux=True)(
                    params, buffers, rng, inputs, labels, scale)
            grads, found_inf = _unscale_and_check(grads, scale, use_scaler)

            if accum > 1:
                acc = opt_state["acc"]
                # skip accumulating non-finite microbatch grads entirely
                acc = {k: jnp.where(found_inf, acc[k],
                                    acc[k] + grads[k].astype(jnp.float32) /
                                    accum)
                       for k in acc}
                apply_now = (step % accum == 0) & (~found_inf)

                def do_update(_):
                    np_, ns = opt_update(params, acc, opt_state["opt"],
                                         step // accum, lr)
                    zero = jax.tree_util.tree_map(jnp.zeros_like, acc)
                    return np_, {"opt": ns, "acc": zero}

                def no_update(_):
                    return params, {"opt": opt_state["opt"], "acc": acc}
                new_params, new_state = jax.lax.cond(apply_now, do_update,
                                                     no_update, None)
            elif use_scaler:
                def do_update(_):
                    return opt_update(params, grads, opt_state, step, lr)

                def no_update(_):
                    return params, opt_state
                new_params, new_state = jax.lax.cond(~found_inf, do_update,
                                                     no_update, None)
            else:
                new_params, new_state = opt_update(params, grads, opt_state,
                                                   step, lr)
            return (loss_val, new_params, new_state, new_buffers, found_inf,
                    out_vals)
        return step_fn

    def __call__(self, inputs, labels=()):
        if isinstance(inputs, Tensor):
            inputs = (inputs,)
        if isinstance(labels, Tensor):
            labels = (labels,)
        self._step_count += 1
        lr = self.optimizer.get_lr()
        rng = next_rng_key()
        scale = jnp.float32(self.scaler.get_scale()) if self.scaler \
            else jnp.float32(1.0)
        (loss, self.params, self.opt_state, self.buffers, found_inf,
         out_vals) = self._compiled(
            self.params, self.opt_state, self.buffers,
            self._step_count, lr, rng, scale,
            tuple(_raw(b) for b in inputs), tuple(_raw(l) for l in labels))
        if self.scaler is not None:
            self.scaler._found_inf = bool(found_inf)
            self.scaler.update()
        self._last_outputs = out_vals
        if self.return_outputs:
            return (Tensor(loss, _internal=True),
                    tuple(Tensor(o, _internal=True) for o in out_vals))
        return Tensor(loss, _internal=True)

    def sync_to_model(self):
        # copies: self.params will be donated on the next call, and the
        # model must keep independently-owned arrays
        for k, p in self._trainable.items():
            p._inplace_assign(jnp.array(self.params[k]))
        namedb = dict(self.model.named_buffers())
        for k, v in self.buffers.items():
            namedb[k]._inplace_assign(jnp.array(v))
        self.sync_optimizer_state()

    def sync_optimizer_state(self):
        """Write the functional opt state back into the Optimizer's
        accumulators so optimizer.state_dict() reflects training (the jit
        path never touches the eager accumulators otherwise)."""
        state = self.opt_state["opt"] if self.accumulate_steps > 1 \
            else self.opt_state
        _write_back_opt_state(self.optimizer, self._trainable, state,
                              self._step_count)

    def sync_from_model(self):
        self.params = {k: jnp.array(p._value)
                       for k, p in self._trainable.items()}
        self.buffers = {k: jnp.array(v)
                        for k, v in self.model.raw_buffers().items()}


def train_step(model, loss_fn, optimizer, scaler=None):
    return TrainStep(model, loss_fn, optimizer, scaler)


class EvalStep:
    """Compiled inference/eval step (no grad, no state mutation)."""

    def __init__(self, model: Layer):
        self.model = model

        def fn(params, buffers, batch):
            with ag.no_grad():
                out = model.functional_call(
                    params, *[Tensor(b, _internal=True) for b in batch],
                    buffers=buffers, training=False)
            if isinstance(out, Tensor):
                return out._value
            if isinstance(out, (tuple, list)):
                return tuple(o._value if isinstance(o, Tensor) else o
                             for o in out)
            return out
        self._compiled = jax.jit(fn)

    def __call__(self, *batch):
        params = self.model.raw_parameters()
        buffers = self.model.raw_buffers()
        out = self._compiled(params, buffers,
                             tuple(_raw(b) for b in batch))
        if isinstance(out, tuple):
            return tuple(Tensor(o, _internal=True) for o in out)
        return Tensor(out, _internal=True)
