"""Predictor implementation (reference: paddle/fluid/inference/api/
analysis_predictor.h AnalysisPredictor; python surface
python/paddle/inference/wrapper.py)."""
from __future__ import annotations

import enum
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..observability import hooks as _obs
from ..serving.resilience import fault_point as _fault_point


class PlaceType(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2


class PrecisionType(enum.Enum):
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


def get_version() -> str:
    import paddle_tpu
    return paddle_tpu.__version__


class Config:
    """reference: AnalysisConfig (paddle/fluid/inference/api/
    analysis_config.cc). TensorRT/OneDNN toggles are accepted for parity
    and map to XLA (always-on compilation)."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        self._model_path = model_path
        self._params_path = params_path
        self._device = "tpu" if any(
            d.platform == "tpu" for d in jax.devices()) else "cpu"
        self._precision = PrecisionType.Float32
        self._memory_pool_mb = 0
        self._enable_profile = False
        self._optim = True
        self._mesh = None
        self._input_pspec = None
        self._param_spec_fn = None

    # --- multi-chip serving (TPU-native analog of the reference's
    # multi-device inference paths: TRT multi-stream, fleet inference
    # helper) — the compiled program runs SPMD over a device mesh ---
    def enable_mesh(self, mesh, input_spec=None, param_spec_fn=None):
        """Serve over ``mesh``. ``input_spec``: a PartitionSpec (or one
        per input) for the data inputs — default shards dim 0 over the
        mesh's first axis (data-parallel serving). ``param_spec_fn(name,
        array) -> PartitionSpec | None`` places parameters (None =
        replicate); supply Column/Row splits for tensor-parallel serving.
        """
        self._mesh = mesh
        self._input_pspec = input_spec
        self._param_spec_fn = param_spec_fn

    def mesh(self):
        return self._mesh

    # --- model location ---
    def set_model(self, model_path, params_path=None):
        self._model_path = model_path
        self._params_path = params_path

    def model_dir(self):
        return self._model_path

    def prog_file(self):
        return self._model_path

    def params_file(self):
        return self._params_path

    # --- device selection (GPU API parity maps to the TPU chip) ---
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        self._memory_pool_mb = memory_pool_init_size_mb
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    def enable_xpu(self, *a, **k):
        pass

    def enable_custom_device(self, device_type, device_id=0):
        self._device = device_type

    # --- optimization toggles ---
    # XLA subsumes the reference's IR/memory/TensorRT/OneDNN pipeline:
    # every toggle is accepted for parity but has no engine to configure.
    # Toggles that a user might rely on semantically (turning optimization
    # OFF, routing to TensorRT) warn ONCE instead of silently no-opping.
    @staticmethod
    def _inert(what, detail):
        import warnings
        warnings.warn(
            f"inference.Config.{what}: accepted for API parity but inert "
            f"on TPU — {detail}", stacklevel=3)

    def switch_ir_optim(self, flag=True):
        if not flag:
            self._inert("switch_ir_optim(False)",
                        "XLA always compiles/optimizes; there is no "
                        "unoptimized executor to fall back to")
        self._optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        self._inert("enable_tensorrt_engine",
                    "the compiled engine is XLA; TensorRT is a GPU "
                    "deployment path")

    def enable_mkldnn(self):
        self._inert("enable_mkldnn", "OneDNN is a CPU kernel library; "
                    "XLA:CPU compiles the fallback path")

    def enable_memory_optim(self, flag=True):
        if flag:
            return  # XLA's buffer assignment already reuses/donates
        self._inert("enable_memory_optim(False)",
                    "XLA buffer reuse cannot be disabled")

    def switch_use_feed_fetch_ops(self, flag):
        pass  # feed/fetch are jit arguments; nothing to switch

    def switch_specify_input_names(self, flag=True):
        pass  # inputs are always named (get_input_names order)

    def enable_profile(self):
        self._enable_profile = True

    def summary(self) -> str:
        return (f"Config(model={self._model_path}, device={self._device}, "
                f"precision={self._precision.name})")


class Tensor:
    """Input/output handle (reference: ZeroCopyTensor,
    paddle/fluid/inference/api/details/zero_copy_tensor.cc)."""

    def __init__(self, name: str, owner: "Predictor"):
        self.name = name
        self._owner = owner
        self._value: Optional[jax.Array] = None

    def reshape(self, shape):
        pass  # shapes come from the bound array

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = jnp.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def share_external_data(self, arr):
        self._value = arr if isinstance(arr, jax.Array) else jnp.asarray(arr)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []

    def type(self):
        return self._value.dtype if self._value is not None else None


class Predictor:
    """reference: AnalysisPredictor. Loads a jit.save artifact (a
    TranslatedLayer) or wraps a live Layer/function."""

    def __init__(self, config: Config, layer=None):
        self._config = config
        if layer is None:
            from ..jit.save_load import load as jit_load
            layer = jit_load(config.model_dir())
        self._layer = layer
        self._input_names: List[str] = getattr(
            layer, "input_names", None) or ["x"]
        self._inputs: Dict[str, Tensor] = {
            n: Tensor(n, self) for n in self._input_names}
        self._outputs: Dict[str, Tensor] = {}
        self._jitted = None
        # snapshot the mesh config: enable_mesh must be called BEFORE
        # create_predictor (a later call changing the live Config would
        # otherwise shard inputs but silently skip param placement)
        self._mesh = config._mesh
        self._input_pspec = config._input_pspec
        if self._mesh is not None and hasattr(self._layer, "state_dict"):
            # plain-function layers have no params to place; the input
            # sharding below still applies
            self._place_params(self._mesh, config._param_spec_fn)

    def _place_params(self, mesh, spec_fn):
        """Install mesh placements on the layer's parameters in place
        (replicated unless spec_fn says otherwise)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        for name, t in self._layer.state_dict().items():
            # state_dict entries are always framework Tensors (Layer
            # wraps buffers; TranslatedLayer._state holds Tensors)
            spec = None
            if spec_fn is not None:
                spec = spec_fn(name, t._value)
            sh = NamedSharding(mesh, spec if spec is not None else P())
            t._value = jax.device_put(t._value, sh)

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def _compiled(self):
        """One compiled XLA program per input-shape set (reference: the
        analysis passes + engine of AnalysisPredictor::Run — here jit
        compile-and-cache does both)."""
        if self._jitted is None:
            import jax
            from .._core.tensor import Tensor as FrameworkTensor
            layer = self._layer

            def f(*raw):
                out = layer(*[FrameworkTensor(r, _internal=True)
                              for r in raw])
                outs = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._value if isinstance(o, FrameworkTensor)
                             else o for o in outs)

            mesh = self._mesh
            if mesh is None:
                self._jitted = jax.jit(f)
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P
                spec = self._input_pspec
                if spec is None:
                    spec = P(mesh.axis_names[0])   # batch over axis 0
                specs = (list(spec) if isinstance(spec, (list, tuple))
                         and not isinstance(spec, P)
                         else [spec] * len(self._input_names))
                shards = tuple(NamedSharding(mesh, s) for s in specs)
                self._jitted = jax.jit(f, in_shardings=shards)
        return self._jitted

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """reference: AnalysisPredictor::Run / ZeroCopyRun.

        Telemetry (paddle_tpu.observability): per-request latency
        histogram + request/sample counters, plus a ``Predictor.run``
        span when the profiler is recording — zero-cost when neither
        sink is active."""
        if not _obs.active():
            return self._run_impl(inputs)
        t0 = time.perf_counter_ns()
        out = self._run_impl(inputs)
        first = next(iter(self._inputs.values()), None)
        batch = (first._value.shape[0]
                 if first is not None and first._value is not None
                 and getattr(first._value, "ndim", 0) else 0)
        _obs.predictor_run(t0, int(batch))
        return out

    def _run_impl(self, inputs: Optional[List[np.ndarray]] = None):
        from .._core.tensor import Tensor as FrameworkTensor
        if inputs is not None:
            for n, arr in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(arr))
        raw = [self._inputs[n]._value for n in self._input_names]
        out = None
        jit_failed = False
        if self._jitted is not False:
            try:
                out = self._compiled()(*raw)
            except Exception:
                if self._mesh is not None:
                    # the user asked for SPMD serving: a sharding
                    # misconfiguration (uneven batch, wrong spec count)
                    # must surface, not silently degrade to one chip
                    raise
                jit_failed = True
                self._jitted = None  # decide after the eager attempt
        if out is None:
            args = [FrameworkTensor(v, _internal=True) for v in raw]
            # bad inputs re-raise here for the user to fix — that's an
            # input error, not a non-jittable forward
            out = self._layer(*args)
            if jit_failed:
                # eager worked where jit didn't: the forward itself is
                # non-jittable; latch eager so we don't re-trace per run
                self._jitted = False
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = {}
        results = []
        for i, o in enumerate(outs):
            t = Tensor(f"out_{i}", self)
            val = o._value if isinstance(o, FrameworkTensor) else jnp.asarray(o)
            t.share_external_data(val)
            self._outputs[t.name] = t
            results.append(np.asarray(val))
        if inputs is not None:
            return results
        return True

    def get_output_names(self) -> List[str]:
        return list(self._outputs.keys())

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config, layer=None) -> Predictor:
    """reference: paddle_infer::CreatePredictor."""
    return Predictor(config, layer=layer)


# ---------------- continuous-batching decode engine ----------------

class InFlightStep:
    """One dispatched-but-uncommitted decode/verify program (ISSUE 12).

    The async overlapped runtime splits every engine step into a
    DISPATCH half (launch the jitted program — JAX dispatch is
    asynchronous, so this returns while the device works) and a COMMIT
    half (the single device→host fetch plus all host bookkeeping). The
    handle carries everything commit needs: the device output array,
    the mask, and a SNAPSHOT of the per-slot request ids AND seat
    generations at dispatch time — commit only applies a slot's result
    when the slot still holds the same SEATING of the same request (a
    slot preempted-and-readmitted between dispatch and commit must not
    receive the old seating's token, even when the re-admission seated
    the SAME request back into its own slot — its pages and lengths
    were reset, so the in-flight token belongs to freed pages; the
    victim re-decodes the dropped token on resume, greedy-identically,
    so no stream ever forks)."""
    __slots__ = ("kind", "mask", "rids", "seats", "out", "drafts",
                 "dlen", "t0", "t0f", "raw", "ttr", "qs", "rows")

    def __init__(self, kind, mask, rids, seats, out, drafts=None,
                 dlen=None, t0=0, t0f=0, raw=None, ttr=0, qs=None,
                 rows=None):
        self.kind = kind                # "decode" | "spec" | "tree"
        self.mask = mask
        self.rids = rids                # per-slot rid snapshot at dispatch
        self.seats = seats              # per-slot seating generation
        self.out = out                  # device array: nxt (B,) / (B, T)
        self.drafts = drafts
        self.dlen = dlen
        self.t0 = t0
        self.t0f = t0f
        self.raw = raw                  # UNCONSTRAINED argmax (B,) when
        #                                 the engine masks sampling — the
        #                                 violation-avoided counter input
        self.ttr = ttr                  # trace-clock anchor (ISSUE 16)
        self.qs = qs                    # slot -> (j, V) draft-model q
        #                                 distributions (ISSUE 20): the
        #                                 real proposal law the rejection
        #                                 sampler's min(1, p/q) needs
        self.rows = rows                # tree verify's un-placed per-node
        #                                 KV (ISSUE 20) — scattered by
        #                                 paged_tree_commit at commit


class GenerationRequest:
    """One in-flight generation request tracked by the engine.

    ``finish_reason`` is STRUCTURED (the string values of
    :class:`paddle_tpu.serving.policy.FinishReason`): ``eos`` /
    ``max_len`` on completion, ``deadline_exceeded`` when a scheduler
    cancels a queued request, and the transient ``preempted`` while the
    request sits evicted awaiting resume (``done`` stays False and the
    reason clears when its replay prefill completes).

    ``priority`` (lower = more important), ``deadline_at`` /
    ``submitted_at`` / ``enqueued_at`` (scheduler-clock seconds; the
    last resets on every requeue) and ``preemptions`` are
    scheduler-facing metadata; the engine's own FIFO path ignores them.
    """
    __slots__ = ("rid", "prompt", "max_new_tokens", "eos_token_id",
                 "tokens", "done", "finish_reason", "slot",
                 "priority", "deadline_at", "submitted_at",
                 "enqueued_at", "preemptions", "swapped",
                 "adapter_id", "constraint", "trace")

    def __init__(self, rid, prompt, max_new_tokens, eos_token_id):
        self.rid = rid
        self.prompt = np.asarray(prompt, np.int32).reshape(1, -1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.tokens: List[int] = []      # generated tokens (no prompt)
        self.done = False
        self.finish_reason: Optional[str] = None
        self.slot: Optional[int] = None
        self.priority = 1                # serving.policy.Priority.NORMAL
        self.deadline_at: Optional[float] = None
        self.submitted_at: Optional[float] = None
        self.enqueued_at: Optional[float] = None   # latest (re)queue time
        self.preemptions = 0
        self.swapped = False    # KV currently host-resident (ISSUE 10)
        self.adapter_id = 0     # 0 = the base model (ISSUE 14)
        self.constraint = None  # live ConstraintState or None (ISSUE 14)
        self.trace = None       # RequestTrace riding the handle (ISSUE 16)

    def resume_sequence(self) -> np.ndarray:
        """The tokens whose KV must be in the pool before this request
        can (re)enter decode: the prompt plus — after a preemption —
        every generated token EXCEPT the last (decode feeds the last
        sampled token back through the step program, which writes its
        KV then; replaying ``tokens[:-1]`` through the continuation
        prefill reproduces the evicted cache bit-for-bit)."""
        if not self.tokens:
            return self.prompt[0]
        return np.concatenate(
            [self.prompt[0], np.asarray(self.tokens[:-1], np.int32)])

    @property
    def output(self) -> np.ndarray:
        """prompt + generated tokens, one row."""
        return np.concatenate(
            [self.prompt[0], np.asarray(self.tokens, np.int32)])


class ContinuousBatchingEngine:
    """Continuous-batching decode over a paged KV cache (reference: the
    serving stack around block_multi_head_attention; design: vLLM-style
    continuous batching on TPU-static shapes).

    ``max_batch`` decode slots run ONE jitted single-token program per
    step (static shapes throughout); new prompts are admitted into free
    slots MID-DECODE, finished rows retire immediately and their pages
    recycle — so short requests stop pad-burning the long ones' HBM and
    decode throughput at mixed request lengths rises with occupancy.

    Prefill is CHUNKED: an admission's prompt advances by at most one
    fixed-size chunk (``prefill_chunk`` tokens, page-rounded; default
    unbounded = one chunk) per engine step, interleaved with the decode
    program — so a 4k-token admission adds one chunk's latency per step
    to the in-flight decodes instead of stalling them for a monolithic
    prefill. And prefill is PREFIX-CACHED: the paged cache's hash-trie
    maps previously prefilled prompt pages (shared system prompts,
    few-shot headers) straight into the new request's block table —
    refcounted, copy-on-write on the first partial page — so the shared
    span costs neither prefill FLOPs nor fresh KV HBM.

    Admission control is page-pool back-pressure: a request is admitted
    only when the allocator can cover ``prompt + max_new_tokens``
    (prefix-cache-held pages are evicted LRU-first under pressure); a
    :class:`~paddle_tpu.serving.PoolExhausted` defers it until running
    requests retire (OOM-free by construction). The engine's own
    :meth:`step` admits FIFO; the SLO-aware control plane
    (:class:`~paddle_tpu.serving.ServingScheduler`) composes the same
    lifecycle pieces — :meth:`admit_request`, :meth:`preempt_request`
    (pages evicted back to the pool, token-identical resume through the
    continuation-prefill program), :meth:`cancel_request`,
    :meth:`prefill_step`, :meth:`decode_step` — under priority classes,
    deadlines and a per-step token budget. Requests finish with
    STRUCTURED reasons (``eos`` / ``max_len`` / ``deadline_exceeded``,
    transient ``preempted`` — serving.policy.FinishReason).

    Sampling: greedy at ``temperature == 0`` (token-identical to the
    dense :func:`~paddle_tpu.models.generate.generate` — chunking and
    prefix sharing are bit-exact, not approximate), else temperature
    sampling with a per-step PRNG fold.

    Speculative decoding (``spec_k > 0``, greedy only): each step a
    host-side n-gram proposer (:class:`~paddle_tpu.serving.Speculator`,
    prompt-lookup over the row's own ``prompt + generated`` history —
    no draft model, no extra weights) drafts up to ``spec_k`` tokens
    per row, ONE batched verify forward
    (:func:`~paddle_tpu.models.generate.paged_verify_forward`) scores
    every speculating row's drafts against its paged KV, and the
    longest greedily-accepted prefix plus the bonus token commit — so
    a step emits up to ``spec_k + 1`` tokens per row for barely more
    HBM traffic than one. A per-row acceptance-rate EMA adapts the
    draft length and falls back to plain decode when the history does
    not repeat, and greedy output stays TOKEN-IDENTICAL to plain paged
    decode at fp and int8-KV (gated in tests/test_spec_decode.py).

    Tensor-parallel serving (``mesh=`` — ISSUE 7): pass a 1-D
    :func:`~paddle_tpu.distributed.mesh.serving_mesh` and the engine
    shards weights by regex partition rules
    (:data:`~paddle_tpu.models.llama.SERVING_TP_RULES` — column splits
    per layer matrix, vocab-sharded lm_head) and every page pool on the
    kv-head axis, lowering the decode/chunk/verify programs through
    ``shard_map``. Page IDS are identical on every shard, so the whole
    host control plane — queues, slots, allocator, refcounts, prefix
    trie, preemption — runs unchanged; per-shard HBM drops to ``1/tp``
    of the weight+pool bytes (the decode bottleneck), and the sharded
    programs stay BIT-identical to single-chip paged decode at fp and
    int8-KV (exact all-gather concats, no psum —
    tests/test_tp_serving.py). GQA configs with ``num_kv_heads < tp``
    replicate one kv head per shard; invalid head/tp combinations raise
    loudly at construction.

    Telemetry (paddle_tpu.observability): admission/eviction counters,
    prefix hit/miss token counters, per-chunk prefill latency histogram,
    per-step batch-occupancy histogram, block-pool utilization gauge —
    plus, under a mesh, the ``serving_tp_*`` family (traced all-gather
    calls/bytes, per-shard pool gauge, probed logits-collective latency
    histogram) — zero-cost when metrics are disabled.
    """

    def __init__(self, params, cfg, *, max_batch: int = 4,
                 page_size: int = 16, max_len: Optional[int] = None,
                 num_pages: Optional[int] = None, kv_cache_dtype=None,
                 temperature: float = 0.0, eos_token_id=None,
                 use_kernel: Optional[bool] = None,
                 key: Optional[jax.Array] = None,
                 prefill_chunk: Optional[int] = None,
                 enable_prefix_cache: bool = True,
                 spec_k: int = 0, spec_ngram: int = 3,
                 speculator=None, draft_layers: Optional[int] = None,
                 draft_pages: Optional[int] = None,
                 spec_tree: Optional[Tuple[int, int]] = None,
                 mesh=None,
                 host_tier: bool = False,
                 host_tier_kw: Optional[Dict] = None,
                 weight_bits: Optional[int] = None,
                 fused: Optional[bool] = None,
                 overlap: bool = False,
                 adapters=None,
                 constraints: bool = False):
        from ..serving import PagedKVCache
        self.cfg = cfg
        self.temperature = float(temperature)
        self.eos_token_id = eos_token_id
        self.use_kernel = use_kernel
        # --- async overlapped runtime (ISSUE 12): overlap=True marks
        # this engine for the double-buffered scheduler pipeline — a
        # ServingScheduler attached without its own overlap= knob
        # inherits it, and the host tier's swap-out DMAs go
        # NON-BLOCKING (issued at preemption, fenced at the next
        # commit) so the device→host copy rides under the in-flight
        # decode step. The dispatch/commit split itself is always
        # available (decode_step == dispatch immediately followed by
        # commit), so the synchronous path stays the bit-identity
        # reference.
        self.overlap = bool(overlap)
        # --- low-bit decode tiers (ISSUE 11): weight_bits quantizes the
        # weights at construction (8 = per-channel int8, 4 = per-group
        # int4 — models/generate.quantize_weights); every serving
        # program dequants on the fly inside its matmul reads, and the
        # quant scales shard under the same regex partition rules as
        # their matrices. weight_bits=8 + kv_cache_dtype="int8" is the
        # w8/kv8 tier (weight AND cache HBM halved). Pre-quantized
        # param trees pass through untouched (weight_bits=None).
        self.weight_bits = weight_bits
        if weight_bits is not None:
            from ..models.generate import quantize_weights
            params = quantize_weights(params, cfg, bits=weight_bits)
        # --- fused serving kernels (ISSUE 11): route the decode /
        # chunked-prefill / spec-verify programs through the fused
        # Pallas kernels (ops/pallas/serving_fused.py — in-VMEM q-RoPE
        # + KV dequant for decode, flash chunk attention for
        # prefill/verify). Default OFF, same contract as
        # LlamaConfig.fused_kernels: flip only with a sweep showing >=
        # parity (the decode_fused_speedup bench rider measures it);
        # off-TPU the fused path is the bit-identical reference, and
        # the kernels themselves are gated token-identical per tier
        # (tests/test_lowbit_decode.py) + Mosaic-lowered by
        # aot_validate --config serving-lowbit.
        self.fused = bool(fused)
        # --- tensor-parallel serving (ISSUE 7): a 1-D mesh shards the
        # weights (llama.SERVING_TP_RULES: column splits + vocab-sharded
        # lm_head) and every page pool on the kv-head axis; the jitted
        # step programs below lower through shard_map. ALL host logic —
        # queues, slots, block tables, allocator, trie — is unchanged:
        # page ids are the same on every shard.
        # --- 2-D serving mesh (ISSUE 17): a ("tp", "dp") mesh
        # additionally splits the BATCH axis of the decode and verify
        # programs over dp — each dp shard computes max_batch/dp rows
        # against its own (dp-replicated, tp-head-sharded) page pool
        # replica, and the per-layer KV rows + scatter indices
        # all-gather across dp before the pool write so every replica
        # receives the full batch's writes in single-chip row order.
        # Chunked prefill stays dp-replicated (one row per program).
        # MoE configs shard their expert stacks over dp with per-token
        # all-to-all dispatch (llama.validate_serving_mesh accepts what
        # validate_serving_tp rejects). Host logic is still unchanged.
        self.mesh = mesh
        self._tp = None
        self._tp_axis = None
        self._dp_axis = None
        self.dp = 1
        self._param_specs = None
        self._tp_probe = None
        if mesh is not None:
            from ..models import llama as _llama
            names = mesh.axis_names
            if len(names) > 2 or (len(names) == 2 and "tp" not in names):
                raise ValueError(
                    f"ContinuousBatchingEngine: the serving mesh must "
                    f"be 1-D (a tp axis) or 2-D (tp, dp), got axes "
                    f"{names}")
            self._tp_axis = "tp" if "tp" in names else names[0]
            self._tp = int(mesh.shape[self._tp_axis])
            if len(names) == 2:
                self._dp_axis = next(a for a in names
                                     if a != self._tp_axis)
                self.dp = int(mesh.shape[self._dp_axis])
                if max_batch % self.dp:
                    raise ValueError(
                        f"ContinuousBatchingEngine: max_batch="
                        f"{max_batch} is not divisible by dp={self.dp}"
                        f" — the decode batch splits into equal "
                        f"per-dp-shard row blocks")
            # validates num_heads/num_kv_heads divisibility loudly and
            # takes the KV-replication path when num_kv_heads < tp
            # (validate_serving_mesh also checks the MoE expert/dp and
            # expert-matrix/tp splits on 2-D meshes)
            params, self._param_specs = _llama.shard_serving_params(
                params, cfg, mesh, axis=self._tp_axis)
        self.params = params
        # --- hierarchical KV (ISSUE 10): host_tier=True swaps the
        # cache for a TieredKVCache — preemption victims swap out to
        # host RAM and resume by swap-in scatter instead of the replay
        # prefill, evicted prefix-trie chains demote/promote, and
        # registered prompt chains persist to the standing store
        # (host_tier_kw: host_capacity_pages / prefix_store_dir /
        # store — a shared HostPageStore across engines).
        cache_kw = dict(page_size=page_size, num_pages=num_pages,
                        kv_dtype=kv_cache_dtype,
                        enable_prefix_cache=enable_prefix_cache,
                        mesh=mesh)
        if host_tier:
            from ..serving.host_tier import TieredKVCache
            self.cache = TieredKVCache(
                cfg, max_batch, max_len or cfg.max_seq_len,
                **cache_kw, **(host_tier_kw or {}))
        else:
            self.cache = PagedKVCache(
                cfg, max_batch, max_len or cfg.max_seq_len, **cache_kw)
        if prefill_chunk is not None:
            # page-rounded so chunk boundaries stay page-aligned (the
            # chunk program's static ctx_cap) and >= one page
            prefill_chunk = self.cache.pages_for(
                max(1, int(prefill_chunk))) * self.cache.page_size
        self.prefill_chunk = prefill_chunk
        self.max_batch = max_batch
        self._key = key if key is not None else jax.random.key(0)
        # --- multi-tenant adapter plane (ISSUE 14): a device-resident
        # AdapterPool of packed per-layer LoRA factors, paged like KV —
        # per-request adapter_id pins a slot at admission (refcounted;
        # LRU reclaim demotes cold adapters to the host tier) and the
        # per-row slot ids gather into every forward. None compiles
        # the adapter term out of every program (the plain engine).
        # A dict builds the pool in place (slots/rank/registry/store —
        # serving.adapters.AdapterPool kwargs); a pre-built pool must
        # match this engine's mesh (the B factors column-shard with
        # the weights).
        from ..serving.adapters import AdapterPool
        if isinstance(adapters, dict):
            adapters = AdapterPool(cfg, mesh=mesh, **adapters)
        if adapters is not None and adapters.mesh is not mesh:
            raise ValueError(
                "ContinuousBatchingEngine: the AdapterPool's mesh does "
                "not match the engine's — build the pool with the same "
                "serving mesh (its B factors shard with the weights)")
        self.adapters = adapters
        self._aslot = np.zeros((max_batch,), np.int32)
        # --- constrained decoding (ISSUE 14): constraints=True grows
        # the decode program a per-row (B, vocab) allowed-token mask
        # (logits[~mask] = -inf before the argmax/categorical) plus a
        # violation-avoided output; per-request DFA state advances at
        # commit. Default OFF so the plain engine's programs (and the
        # bit-identity gates) are untouched.
        self.constraints = bool(constraints)
        # the (B, vocab) mask is real memory at serving vocab sizes —
        # only constrained engines pay for it
        self._cmask = (np.ones((max_batch, cfg.vocab_size), bool)
                       if self.constraints else None)
        # device copy of the mask, re-uploaded only after a host-side
        # mutation (commit refresh, seat/clear) — steady-state traffic
        # with no constrained rows pays zero per-step transfer
        self._cmask_dev = None
        self._cmask_dirty = True
        self._queue: List[GenerationRequest] = []
        self._slots: List[Optional[GenerationRequest]] = [None] * max_batch
        self._last = np.zeros((max_batch,), np.int32)
        # per-slot request state MIRRORED into flat numpy arrays so the
        # decode commit is vectorized host bookkeeping (ISSUE 12): one
        # fancy-indexed update per step instead of a per-row Python
        # loop of scalar conversions. _install_slot/_clear_slot are the
        # only writers; -1 rid == empty slot.
        self._rids = np.full((max_batch,), -1, np.int64)
        # seating GENERATION per slot, bumped on every _install_slot:
        # the commit guard compares it so a request preempted and
        # re-seated (even into its own slot, rid unchanged) between
        # dispatch and commit never receives the stale seating's token
        self._seat = np.zeros((max_batch,), np.int64)
        self._ntok = np.zeros((max_batch,), np.int64)
        self._maxnew = np.zeros((max_batch,), np.int64)
        self._eos = np.full((max_batch,), -1, np.int64)
        # in-flight dispatched-but-uncommitted work (overlap pipeline):
        # at most ONE decode/verify program plus this step's prefill
        # chunk handles — committed in dispatch order by commit_inflight
        self._inflight: Optional[InFlightStep] = None
        self._inflight_chunks: List[Dict] = []
        self._fence_ns = 0      # device-wait accumulated since last take
        self._next_rid = 0
        self._steps = 0
        # replica id spans carry (ISSUE 16) — stamped by the cluster /
        # supervisor; -1 renders as the "router" lane in exports
        self.replica_id = -1
        self._decode_fn = None
        # slot -> [request, sequence being prefilled (prompt, or the
        # preemption-resume replay), tokens already in pages]
        self._pending: Dict[int, List] = {}
        self._chunk_fns: Dict[tuple, object] = {}
        # --- speculative decoding (ISSUE 5 / ISSUE 14): n-gram draft +
        # batched verify; spec_k = max drafts per row per step, 0 = off.
        # temperature == 0 verifies against the greedy argmax (the
        # PR 5 path, token-identical to plain decode); temperature > 0
        # runs standard REJECTION SAMPLING against the verify logits
        # (serving.speculative.rejection_sample_tokens — q is the
        # deterministic proposer's point mass, so acceptance is p(x)
        # and the corrected residual keeps the output distribution
        # exactly the plain sampled-decode law), which is what gives
        # temperature>0 traffic the 1+k speedup.
        # --- model-based draft + tree speculation (ISSUE 20):
        # draft_layers builds a truncated-layer shared-embedding DRAFT
        # model (models/generate.make_draft_params) that proposes
        # spec_k tokens autoregressively on device, with its own KV in
        # a SECOND small paged pool under the same BlockAllocator
        # machinery; verification rides the existing verify forward,
        # and the rejection sampler is fed the draft's REAL q
        # distribution instead of a point mass. spec_tree=(width,
        # depth) additionally fans each draft step's top-``width``
        # candidates into a token TREE verified in ONE forward (the
        # tree-attention ancestor mask folds into the chunk kernel's
        # ragged masking); the longest accepted root path commits.
        # Draft pool state is DISPOSABLE: it is never journaled, never
        # swapped — preemption/recovery rebuild it cold through the
        # catch-up forward, token-identically.
        if spec_tree is not None:
            w, d = int(spec_tree[0]), int(spec_tree[1])
            if draft_layers is None:
                raise ValueError(
                    "spec_tree requires draft_layers: the tree's "
                    "candidates come from the draft model's per-step "
                    "top-width distributions")
            if w < 1 or d < 1:
                raise ValueError(
                    f"spec_tree=(width, depth) must both be >= 1, got "
                    f"{spec_tree}")
            if spec_k and int(spec_k) != d:
                raise ValueError(
                    f"spec_tree depth {d} conflicts with spec_k="
                    f"{spec_k}: the tree's chain IS the linear draft "
                    f"(leave spec_k at 0 or pass spec_k={d})")
            spec_k = d
            if 1 + w * d > 32:
                raise ValueError(
                    f"spec_tree=({w}, {d}) needs {1 + w * d} tree "
                    f"nodes; the fused kernel's per-query ancestor "
                    f"bitmask holds at most 32")
            self.spec_tree = (w, d)
            self._tree_T = 1 + w * d
        else:
            self.spec_tree = None
            self._tree_T = None
        if draft_layers is not None and int(spec_k) < 1:
            raise ValueError(
                "draft_layers requires spec_k >= 1: the draft model "
                "proposes spec_k tokens per step")
        self.spec_k = int(spec_k)
        if self.spec_k:
            if self.constraints:
                raise ValueError(
                    "spec_k > 0 cannot combine with constraints=True: "
                    "a verify batch commits tokens the per-row grammar "
                    "mask never saw — run constrained requests on a "
                    "plain-decode engine (the scenarios compose at the "
                    "cluster tier, one engine per workload class)")
            from ..serving.speculative import Speculator
            self.spec = (speculator if speculator is not None
                         else Speculator(self.spec_k,
                                         ngram_max=spec_ngram))
        else:
            self.spec = None
        self._spec_fns: Dict[tuple, object] = {}
        # host-side acceptance RNG for sampled speculation, seeded from
        # the engine key so two engines built identically draw the same
        # stream (recovery keeps committed tokens; uncommitted futures
        # re-draw — the same step-granularity contract sampled decode
        # already has)
        self._accept_rng = np.random.default_rng(
            int(np.asarray(jax.random.key_data(self._key)).sum()
                & 0x7FFFFFFF))
        self.draft_layers = (int(draft_layers)
                             if draft_layers is not None else None)
        self.draft_params = self.draft_cfg = self.draft_cache = None
        if self.draft_layers is not None:
            from ..models.generate import make_draft_params
            # truncation slices the (possibly quantized, possibly
            # sharded) SERVING params — the draft inherits the target's
            # weight tier and tp partitioning by construction, and the
            # param-spec pytree structure is unchanged (only the stacked
            # layer axis shrank), so _tp_map reuses self._param_specs
            self.draft_params, self.draft_cfg = make_draft_params(
                self.params, cfg, self.draft_layers)
            # + spec_k + 1 headroom past the main pool's max_len: the
            # draft loop's speculative feeds write up to spec_k
            # positions BEYOND the committed context, so a row drafted
            # at the tail of a full-length request still has pages
            self.draft_cache = PagedKVCache(
                self.draft_cfg, max_batch,
                (max_len or cfg.max_seq_len) + self.spec_k + 1,
                page_size=page_size, num_pages=draft_pages,
                kv_dtype=kv_cache_dtype, enable_prefix_cache=False,
                mesh=mesh)
        # per-slot draft bookkeeping: _draft_base[slot] is the main
        # context length at the last propose (the draft pool's valid
        # prefix is base + the accepted tokens that MATCH the fed
        # chain); _draft_chain holds the chain tokens actually fed
        # through the draft model, _draft_q the stashed per-position q
        # distributions awaiting the next linear dispatch
        self._draft_base = np.zeros((max_batch,), np.int64)
        self._draft_chain: Dict[int, np.ndarray] = {}
        self._draft_q: Dict[int, np.ndarray] = {}
        self._draft_fns: Dict[tuple, object] = {}
        self._draft_dec_fn = None
        self._tree_fns: Dict[tuple, object] = {}
        self._tree_commit_fns: Dict[int, object] = {}

    # ---- request intake ----
    def create_request(self, prompt, max_new_tokens: int = 16,
                       eos_token_id=None, adapter_id: int = 0,
                       constraint=None) -> GenerationRequest:
        """Validate and build a request WITHOUT queueing it — external
        schedulers (:class:`~paddle_tpu.serving.ServingScheduler`) own
        their queues and place requests via :meth:`admit_request`.

        ``adapter_id`` (ISSUE 14): the LoRA variant serving this
        request (0 = base model); needs an engine built with an
        :class:`~paddle_tpu.serving.adapters.AdapterPool`. The slot is
        pinned at ADMISSION, not here — a queued request holds no
        device residency. ``constraint``: a
        :class:`~paddle_tpu.serving.constraints.TokenDFA` (wrapped
        into a fresh per-request state) or a live
        :class:`~paddle_tpu.serving.constraints.ConstraintState`;
        needs ``constraints=True``."""
        if int(adapter_id) != 0:
            if self.adapters is None:
                raise ValueError(
                    f"create_request: adapter_id={adapter_id} on an "
                    f"engine without an adapter pool — pass adapters= "
                    f"at construction")
            # resolvability check at INTAKE: an unknown/oversized id
            # must reject this request here, not raise at admission
            # inside the serving loop (a poison-pill that would crash
            # every step and every recovery re-admission)
            self.adapters.validate_id(adapter_id)
        if constraint is not None and not self.constraints:
            raise ValueError(
                "create_request: a grammar constraint needs an engine "
                "built with constraints=True (the decode program "
                "carries the per-row mask input)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            raise ValueError("submit: empty prompt")
        need = self.cache.pages_for(prompt.size + int(max_new_tokens))
        if need > self.cache.pages_per_seq:
            raise ValueError(
                f"request of {prompt.size}+{max_new_tokens} tokens "
                f"exceeds max_len={self.cache.max_len}")
        usable = (self.cache.allocator.num_pages
                  - self.cache.allocator.reserved)
        if need > usable:
            # reject up front: queued, this request would deadlock
            # admission once it reached the head (no amount of
            # retirement frees more than the whole pool)
            raise ValueError(
                f"request needs {need} pages but the pool holds only "
                f"{usable}; grow num_pages or shrink the request")
        req = GenerationRequest(
            self._next_rid, prompt, max_new_tokens,
            self.eos_token_id if eos_token_id is None else eos_token_id)
        req.adapter_id = int(adapter_id)
        if constraint is not None:
            from ..serving.constraints import ConstraintState, TokenDFA
            if isinstance(constraint, TokenDFA):
                constraint = ConstraintState(constraint,
                                             eos_token_id=req.eos_token_id)
            req.constraint = constraint
        self._next_rid += 1
        return req

    def attach_constraint(self, req: GenerationRequest,
                          constraint) -> GenerationRequest:
        """Attach a live
        :class:`~paddle_tpu.serving.constraints.ConstraintState` to an
        EXISTING request handle — the restore/cold-recovery path
        (ISSUE 15): checkpointed grammar state rebuilds outside
        :meth:`create_request`, and re-attaching through the engine
        keeps the one validation that matters — an engine whose decode
        program carries no mask input must refuse loudly, never
        silently finish the session unconstrained."""
        if constraint is None:
            return req
        if not self.constraints:
            raise ValueError(
                "attach_constraint: this engine was built without "
                "constraints=True — restoring a grammar-constrained "
                "session into it would decode unconstrained; rebuild "
                "the engine with constraints=True")
        req.constraint = constraint
        return req

    def submit(self, prompt, max_new_tokens: int = 16,
               eos_token_id=None, adapter_id: int = 0,
               constraint=None) -> GenerationRequest:
        """Queue a prompt (1D int sequence); returns the request handle
        (``.done`` / ``.tokens`` / ``.output`` fill in as steps run)."""
        req = self.create_request(prompt, max_new_tokens=max_new_tokens,
                                  eos_token_id=eos_token_id,
                                  adapter_id=adapter_id,
                                  constraint=constraint)
        self._queue.append(req)
        return req

    # ---- jitted programs (one decode; one prefill per page bucket) ----
    def _rows_specs(self):
        """PartitionSpecs for the tree verify's un-placed per-node KV
        rows (ISSUE 20): ``rows[name]`` is (L, B, T, nkv[, hd]) — the
        kv-head axis shards over tp exactly like the pool's (same axis
        index 3), and the BATCH axis rides the dp split (the rows come
        out of the per-shard dense temp cache, one row block per dp
        shard; paged_tree_commit all-gathers them before the
        scatter)."""
        from jax.sharding import PartitionSpec as P
        ax, dpx = self._tp_axis, self._dp_axis
        return {name: (P(None, dpx, None, ax, None) if a.ndim == 5
                       else P(None, dpx, None, ax))
                for name, a in self.cache.pool.items()}

    def _tp_map(self, fn, arg_kinds, out_kinds=("rep", "pool"),
                cache=None):
        """Lower a per-shard serving forward through shard_map on the
        engine's serving mesh. ``arg_kinds``: one of ``"params"`` (the
        regex-rule spec pytree), ``"pool"`` (page pools, head axis
        sharded over tp, replicated across dp), ``"rep"`` (replicated
        host-side small args), ``"batch"`` (per-row batch args —
        last tokens, block tables, lengths, the active mask, adapter
        slots — split over the dp axis on a 2-D mesh, replicated on a
        1-D one) or ``"rows"`` (tree-verify per-node KV,
        :meth:`_rows_specs`) per positional argument. ``out_kinds``
        names the output positions the same way (default ``(logits,
        pool)``; a single kind maps the output pytree directly) —
        logits are replicated (the per-shard body already all-gathered
        them over tp AND dp; ``check_rep=False`` skips the symbolic
        replication proof, same as the training-side ring-attention
        shard_map). ``cache`` picks whose pool specs "pool" means —
        the DRAFT pool's programs (ISSUE 20) pass their own cache."""
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P
        pool_specs = (cache if cache is not None else self.cache
                      ).pool_specs
        kinds = {"params": self._param_specs,
                 "pool": pool_specs, "rep": P(),
                 "batch": (P(self._dp_axis)
                           if self._dp_axis is not None else P()),
                 "rows": self._rows_specs()}
        if self.adapters is not None:
            # adapter-pool factor dict: B factors column-sharded on the
            # same output axis as the base weights, A + scales
            # replicated (llama.adapter_partition_specs)
            kinds["adapters"] = self.adapters.specs
        out_specs = (kinds[out_kinds[0]] if len(out_kinds) == 1
                     else tuple(kinds[k] for k in out_kinds))
        return shard_map(
            fn, mesh=self.mesh,
            in_specs=tuple(kinds[k] for k in arg_kinds),
            out_specs=out_specs, check_rep=False)

    def _decode(self):
        if self._decode_fn is None:
            from ..models import generate as gen
            cfg, temp, uk = self.cfg, self.temperature, self.use_kernel
            ax, fz = self._tp_axis, self.fused
            dpx = self._dp_axis
            ad_on, cons = self.adapters is not None, self.constraints

            if ad_on:
                def fwd(params, last, paged, tables, lengths, active,
                        ad, aslot):
                    return gen.paged_decode_forward(
                        params, last, paged, tables, lengths, cfg,
                        active=active, use_kernel=uk, tp_axis=ax,
                        dp_axis=dpx, fused=fz, adapters=ad,
                        adapter_slots=aslot)
                if self.mesh is not None:
                    fwd = self._tp_map(fwd, ("params", "batch", "pool",
                                             "batch", "batch", "batch",
                                             "adapters", "batch"))
            else:
                def fwd(params, last, paged, tables, lengths, active):
                    return gen.paged_decode_forward(
                        params, last, paged, tables, lengths, cfg,
                        active=active, use_kernel=uk, tp_axis=ax,
                        dp_axis=dpx, fused=fz)
                if self.mesh is not None:
                    fwd = self._tp_map(fwd, ("params", "batch", "pool",
                                             "batch", "batch", "batch"))

            def f(params, last, paged, tables, lengths, active, key,
                  *extra):
                # extra layout (engine-config-static): [adapter arrays,
                # adapter slots] when the pool is on, then [the (B, V)
                # allowed-token mask] when constraints are on
                extra = list(extra)
                if ad_on:
                    logits, paged = fwd(params, last, paged, tables,
                                        lengths, active, extra.pop(0),
                                        extra.pop(0))
                else:
                    logits, paged = fwd(params, last, paged, tables,
                                        lengths, active)
                raw = None
                if cons:
                    # the UNCONSTRAINED argmax rides along so the commit
                    # can count violations the mask avoided; masking
                    # happens BEFORE the temperature split so greedy and
                    # sampled constrained decode share one rule
                    cmask = extra.pop(0)
                    raw = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    logits = jnp.where(cmask, logits, -jnp.inf)
                if temp == 0.0:
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                else:
                    nxt = jax.random.categorical(
                        key, logits / temp, axis=-1).astype(jnp.int32)
                if cons:
                    return (nxt, raw), paged
                return nxt, paged

            self._decode_fn = jax.jit(f, donate_argnums=(2,))
        return self._decode_fn

    def _chunk_fn(self, ctx_cap: int, width: int):
        """One compiled chunked-prefill program per static ``(context
        cap, chunk width)`` pair. ``ctx_cap`` is power-of-two-bucketed
        and ``width`` page-bucketed (capped at ``prefill_chunk``), so a
        long-lived server compiles O(width_buckets x log(pages_per_seq))
        variants — not one per distinct prompt or shared-prefix
        length."""
        key = (ctx_cap, width)
        if key not in self._chunk_fns:
            from ..models import generate as gen
            cfg, ax, fz = self.cfg, self._tp_axis, self.fused
            uk, dpx = self.use_kernel, self._dp_axis

            # chunked prefill stays dp-REPLICATED (one row per
            # program): every batch arg keeps the "rep" kind and only
            # dp_axis threads through, so a MoE config's expert
            # dispatch can still all-to-all over the dp axis
            if self.adapters is not None:
                def f(params, chunk, paged, table, ctx_len, chunk_len,
                      ad, aslot):
                    return gen.paged_prefill_chunk(
                        params, chunk, paged, table, cfg,
                        ctx_cap=ctx_cap, ctx_len=ctx_len,
                        chunk_len=chunk_len, tp_axis=ax, dp_axis=dpx,
                        fused=fz, use_kernel=uk, adapters=ad,
                        adapter_slot=aslot)
                if self.mesh is not None:
                    f = self._tp_map(f, ("params", "rep", "pool", "rep",
                                         "rep", "rep", "adapters",
                                         "rep"))
            else:
                def f(params, chunk, paged, table, ctx_len, chunk_len):
                    return gen.paged_prefill_chunk(
                        params, chunk, paged, table, cfg,
                        ctx_cap=ctx_cap, ctx_len=ctx_len,
                        chunk_len=chunk_len, tp_axis=ax, dp_axis=dpx,
                        fused=fz, use_kernel=uk)
                if self.mesh is not None:
                    f = self._tp_map(f, ("params", "rep", "pool", "rep",
                                         "rep", "rep"))
            self._chunk_fns[key] = jax.jit(f, donate_argnums=(2,))
        return self._chunk_fns[key]

    def _spec_fn(self, ctx_cap: int, T: int):
        """One compiled speculative-verify program per static ``(context
        cap, chunk width)`` pair: the batched verify forward + greedy
        argmax at every position. ``ctx_cap`` buckets to power-of-two
        page counts (same rule as :meth:`_chunk_fn`) and ``T`` is
        ``spec_k + 1``, so a long-lived server compiles
        O(log(pages_per_seq)) variants."""
        key = (ctx_cap, T)
        if key not in self._spec_fns:
            from ..models import generate as gen
            cfg, uk, ax = self.cfg, self.use_kernel, self._tp_axis
            fz, dpx = self.fused, self._dp_axis
            ad_on, temp = self.adapters is not None, self.temperature

            if ad_on:
                def fwd(params, chunk, paged, tables, lengths, active,
                        ad, aslot):
                    return gen.paged_verify_forward(
                        params, chunk, paged, tables, lengths, cfg,
                        ctx_cap=ctx_cap, active=active, use_kernel=uk,
                        tp_axis=ax, dp_axis=dpx, fused=fz, adapters=ad,
                        adapter_slots=aslot)
                if self.mesh is not None:
                    fwd = self._tp_map(fwd, ("params", "batch", "pool",
                                             "batch", "batch", "batch",
                                             "adapters", "batch"))
            else:
                def fwd(params, chunk, paged, tables, lengths, active):
                    return gen.paged_verify_forward(
                        params, chunk, paged, tables, lengths, cfg,
                        ctx_cap=ctx_cap, active=active, use_kernel=uk,
                        tp_axis=ax, dp_axis=dpx, fused=fz)
                if self.mesh is not None:
                    fwd = self._tp_map(fwd, ("params", "batch", "pool",
                                             "batch", "batch", "batch"))

            def f(params, chunk, paged, tables, lengths, active,
                  *extra):
                logits, paged = (fwd(params, chunk, paged, tables,
                                     lengths, active, *extra) if ad_on
                                 else fwd(params, chunk, paged, tables,
                                          lengths, active))
                if temp == 0.0:
                    # greedy verify: only the per-position argmax leaves
                    # the device (the ISSUE 5 path, unchanged)
                    return (jnp.argmax(logits, axis=-1)
                            .astype(jnp.int32), paged)
                # sampled verify (ISSUE 14): rejection sampling needs
                # the full (B, T, V) verify distributions on the host —
                # acceptance is min(1, p/q) per draft position and the
                # corrected residual draws from p with the draft zeroed
                return logits.astype(jnp.float32), paged

            self._spec_fns[key] = jax.jit(f, donate_argnums=(2,))
        return self._spec_fns[key]

    # ---- draft-model + tree speculation programs (ISSUE 20) ----
    def _draft_catchup_fn(self, ctx_cap: int, T: int):
        """One compiled draft-pool CATCH-UP program per static
        ``(context cap, width)`` pair: the verify forward over the
        DRAFT model writing a ``T``-token chunk of already-committed
        context into the draft pool (logits discarded — only the KV
        matters). Cold draft pools (first propose after prefill,
        post-preemption resume, crash recovery) replay through this,
        which is what makes the rebuilt pool token-identical."""
        key = (ctx_cap, T)
        if key not in self._draft_fns:
            from ..models import generate as gen
            cfg, uk, ax = self.draft_cfg, self.use_kernel, self._tp_axis
            fz, dpx = self.fused, self._dp_axis

            def f(params, chunk, paged, tables, lengths, active):
                _, paged = gen.paged_verify_forward(
                    params, chunk, paged, tables, lengths, cfg,
                    ctx_cap=ctx_cap, active=active, use_kernel=uk,
                    tp_axis=ax, dp_axis=dpx, fused=fz)
                return paged
            if self.mesh is not None:
                f = self._tp_map(f, ("params", "batch", "pool",
                                     "batch", "batch", "batch"),
                                 out_kinds=("pool",),
                                 cache=self.draft_cache)
            self._draft_fns[key] = jax.jit(f, donate_argnums=(2,))
        return self._draft_fns[key]

    def _draft_decode(self):
        """The draft model's one-token decode program: same ragged
        paged decode as :meth:`_decode` but over the draft params/pool
        and returning the full (B, V) f32 LOGITS — the proposer needs
        the real distribution q on the host (chain token + tree
        candidates + the rejection sampler's min(1, p/q))."""
        if self._draft_dec_fn is None:
            from ..models import generate as gen
            cfg, uk, ax = self.draft_cfg, self.use_kernel, self._tp_axis
            fz, dpx = self.fused, self._dp_axis

            def f(params, last, paged, tables, lengths, active):
                logits, paged = gen.paged_decode_forward(
                    params, last, paged, tables, lengths, cfg,
                    active=active, use_kernel=uk, tp_axis=ax,
                    dp_axis=dpx, fused=fz)
                return logits.astype(jnp.float32), paged
            if self.mesh is not None:
                f = self._tp_map(f, ("params", "batch", "pool",
                                     "batch", "batch", "batch"),
                                 cache=self.draft_cache)
            self._draft_dec_fn = jax.jit(f, donate_argnums=(2,))
        return self._draft_dec_fn

    def _tree_fn(self, ctx_cap: int, T: int):
        """One compiled TREE-VERIFY program per static ``(context cap,
        node count)`` pair: the verify forward in tree mode — rope
        positions ``lengths + depth``, the ancestor mask folded into
        the chunk attention — returning the greedy per-node argmax
        (temp 0) or the full per-node logits (sampled), PLUS the
        un-placed per-node KV rows (no scatter: placement waits for
        the host's accepted root path, :meth:`_tree_commit_fn`). The
        main pool passes through untouched, so it is NOT donated."""
        key = (ctx_cap, T)
        if key not in self._tree_fns:
            from ..models import generate as gen
            cfg, uk, ax = self.cfg, self.use_kernel, self._tp_axis
            fz, dpx = self.fused, self._dp_axis
            ad_on, temp = self.adapters is not None, self.temperature

            def fwd(params, chunk, paged, tables, lengths, active,
                    depths, anc, *extra):
                kw = {}
                if ad_on:
                    kw = {"adapters": extra[0],
                          "adapter_slots": extra[1]}
                return gen.paged_verify_forward(
                    params, chunk, paged, tables, lengths, cfg,
                    ctx_cap=ctx_cap, active=active, use_kernel=uk,
                    tp_axis=ax, dp_axis=dpx, fused=fz,
                    tree_depth=depths, tree_mask=anc, **kw)

            def f(params, chunk, paged, tables, lengths, active,
                  depths, anc, *extra):
                logits, rows = fwd(params, chunk, paged, tables,
                                   lengths, active, depths, anc,
                                   *extra)
                if temp == 0.0:
                    return (jnp.argmax(logits, axis=-1)
                            .astype(jnp.int32), rows)
                return logits.astype(jnp.float32), rows
            if self.mesh is not None:
                kinds = ["params", "batch", "pool", "batch", "batch",
                         "batch", "batch", "batch"]
                if ad_on:
                    kinds += ["adapters", "batch"]
                f = self._tp_map(f, tuple(kinds),
                                 out_kinds=("rep", "rows"))
            self._tree_fns[key] = jax.jit(f)
        return self._tree_fns[key]

    def _tree_commit_fn(self, T: int):
        """The tree commit's jitted placement: gather each row's
        accepted root-path nodes out of the verify's KV rows and
        scatter them into the main pool at ``lengths + d`` —
        bit-identical to what a linear verify of the accepted path
        would have written. Only the pool is donated — the rows'
        ``(L, B, T, nkv, hd)`` buffers never match an output shape,
        so donating them would just warn."""
        if T not in self._tree_commit_fns:
            from ..models import generate as gen
            dpx = self._dp_axis

            def f(paged, rows, tables, lengths, path_nodes, path_len):
                return gen.paged_tree_commit(
                    paged, rows, tables, lengths, path_nodes,
                    path_len, dp_axis=dpx)
            if self.mesh is not None:
                f = self._tp_map(f, ("pool", "rows", "batch", "batch",
                                     "batch", "batch"),
                                 out_kinds=("pool",))
            self._tree_commit_fns[T] = jax.jit(f, donate_argnums=(0,))
        return self._tree_commit_fns[T]

    # ---- scheduling ----
    def _install_slot(self, slot: int, req: GenerationRequest):
        """Seat ``req`` in ``slot`` and mirror its commit-relevant
        state into the flat per-slot arrays the vectorized decode
        commit indexes (rid guard, token count, max_new, eos id)."""
        req.slot = slot
        self._slots[slot] = req
        self._rids[slot] = req.rid
        self._seat[slot] += 1
        self._ntok[slot] = len(req.tokens)
        self._maxnew[slot] = req.max_new_tokens
        self._eos[slot] = (-1 if req.eos_token_id is None
                           else int(req.eos_token_id))
        # per-row adapter slot mirror (ISSUE 14): the pool pin taken at
        # admission guarantees the slot id stays valid while seated
        self._aslot[slot] = (self.adapters.slot_of(req.adapter_id)
                             if self.adapters is not None else 0)
        if self.constraints:
            self._cmask[slot] = (
                req.constraint.mask(self.cfg.vocab_size)
                if req.constraint is not None else True)
            self._cmask_dirty = True

    def _clear_slot(self, slot: int):
        self._slots[slot] = None
        self._rids[slot] = -1
        self._aslot[slot] = 0
        if self.draft_cache is not None:
            # draft pool state is DISPOSABLE (ISSUE 20): retire,
            # preempt, swap and cancel all just drop the slot's draft
            # pages — resume/recovery rebuild them cold through the
            # catch-up forward, token-identically
            if self.draft_cache.active[slot]:
                self.draft_cache.release(slot)
            self._draft_chain.pop(slot, None)
            self._draft_q.pop(slot, None)
            self._draft_base[slot] = 0
        if self.constraints:
            self._cmask[slot] = True
            self._cmask_dirty = True

    def admit_request(self, req: GenerationRequest) -> bool:
        """Place ``req`` into a free slot, reserving its pages (prefix-
        shared where the trie already holds them). Returns False when
        every slot is busy; raises
        :class:`~paddle_tpu.serving.PoolExhausted` when the pool can't
        cover it. Admission only RESERVES pages; the request's tokens
        prefill chunk-by-chunk in :meth:`prefill_step`.

        A previously PREEMPTED request re-admits through the same path:
        its replay sequence (``resume_sequence()`` — prompt + generated
        tokens minus the last) reserves pages and replays through the
        continuation-prefill program, so resume is token-identical to
        an uninterrupted run. Under the host tier (ISSUE 10) a victim
        that was SWAPPED OUT resumes by swap-in scatter instead: its
        exact KV bytes return from host RAM in one donated scatter —
        bit-identical and decode-ready immediately, no replay forward.
        A missing/stale payload (LRU-dropped) falls back to the replay
        path, which remains the one gated resume code path."""
        cache = self.cache
        free = cache.free_slots()
        if not free:
            return False
        slot = free[0]
        # adapter residency (ISSUE 14): pin the request's adapter slot
        # BEFORE any page work — acquire may itself defer
        # (AdapterPoolExhausted is a PoolExhausted: every slot pinned is
        # back-pressure, same as a full page pool), and a later
        # PoolExhausted from the page side must drop the pin it took so
        # a deferred admission leaks nothing
        pinned = False
        if self.adapters is not None and req.adapter_id:
            self.adapters.acquire(req.adapter_id)
            pinned = True
        try:
            return self._admit_pinned(req, slot)
        except BaseException:
            if pinned:
                self.adapters.release(req.adapter_id)
            raise

    def _admit_pinned(self, req: GenerationRequest, slot: int) -> bool:
        """The page-side half of :meth:`admit_request`, run with the
        request's adapter pin (if any) already held."""
        cache = self.cache
        seq = req.resume_sequence()
        # trace: queue_wait closes at the admission INSTANT (anchored
        # here), so the swap-in work below lands in swap_ms, not queue
        t_adm = _obs.serving_trace_now()
        if (req.swapped and req.tokens
                and getattr(cache, "host", None) is not None):
            # a raised swap_in (injected fault, PoolExhausted) leaves
            # the flag SET — the payload committed nothing and survives
            # for the retried admission after recovery/back-pressure
            length = cache.swap_in(
                slot, req.rid, req.prompt.shape[1] + req.max_new_tokens,
                expect_tokens=seq.size)
            req.swapped = False
            if length is not None:
                self._install_slot(slot, req)
                # decode continues from the already-sampled last token,
                # exactly as the replay path would after its final chunk
                self._last[slot] = np.int32(req.tokens[-1])
                req.finish_reason = None    # clears transient "preempted"
                _obs.serving_resumed(1, 0)  # zero replay tokens: swap-in
                _obs.serving_trace_admitted(
                    req, replica=self.replica_id, slot=slot, t_ns=t_adm)
                _obs.serving_trace_span(
                    req, "swap_in", t_adm, replica=self.replica_id,
                    slot=slot, seq=len(req.tokens),
                    meta={"tokens": int(length)})
                return True
            # payload gone (capacity drop / stale — swap_in counted the
            # fallback): replay below, the gated resume path
            if t_adm:
                _obs.serving_trace_mark(
                    req, "swap_fallback", replica=self.replica_id,
                    slot=slot, meta={"why": getattr(
                        cache, "last_swap_fallback", None)})
        _, shared = cache.admit_prompt(
            slot, seq, req.prompt.shape[1] + req.max_new_tokens)
        self._install_slot(slot, req)
        self._pending[slot] = [req, seq, int(shared)]
        _obs.serving_trace_admitted(
            req, replica=self.replica_id, slot=slot, t_ns=t_adm,
            meta={"shared": int(shared)} if t_adm else None)
        if req.preemptions > 0:
            if t_adm:
                _obs.serving_trace_mark(
                    req, "resume_replay", replica=self.replica_id,
                    slot=slot,
                    meta={"replay": int(seq.size) - int(shared)})
            # resume re-entry: the replay cost has its own counter —
            # counting it as an admission would drift the occupancy
            # identity (admissions - evictions - preemptions), and its
            # generated-token replay is NOT a prompt prefix miss (it
            # would collapse the dashboarded prefix hit rate)
            _obs.serving_resumed(1, seq.size - int(shared))
        else:
            # full sequence size here — the prefix hit/miss split is
            # the serving_prefix pair's job, and the chunk-token
            # counter already measures tokens actually forwarded
            _obs.serving_admitted(1, seq.size)
            _obs.serving_prefix(int(shared), seq.size - int(shared))
        return True

    def swap_candidate(self, req: GenerationRequest) -> bool:
        """True when preempting ``req`` would SWAP its KV to the host
        tier (near-free resume) rather than evict-and-replay: the
        cache is tiered and the request is decode-phase (committed KV
        exists — mid-prefill victims have nothing worth moving). The
        :class:`~paddle_tpu.serving.PreemptionPolicy` prefers such
        victims when the scheduler passes this predicate through."""
        return (getattr(self.cache, "host", None) is not None
                and req.slot is not None
                and req.slot not in self._pending
                and int(self.cache.lengths[req.slot]) > 0)

    def preempt_request(self, req: GenerationRequest) -> int:
        """Evict a RUNNING request's pages back to the pool (the
        scheduler's evict-for-preempt: refcounts drop; pages shared
        with the prefix trie or other tables survive under those
        references) and reset the request for a token-identical resume
        via :meth:`admit_request`. Under the host tier (ISSUE 10) a
        decode-phase victim's live pages SWAP OUT to host RAM first,
        so the later resume is a swap-in scatter instead of the
        ``O(resident tokens)`` replay prefill. ``finish_reason`` reads
        the transient ``preempted`` until the resume completes;
        ``done`` stays False. Returns the number of pages actually
        returned to the free list."""
        slot = req.slot
        if slot is None or self._slots[slot] is not req:
            raise ValueError(
                f"preempt_request: request {req.rid} is not running")
        swap = self.swap_candidate(req)
        self._pending.pop(slot, None)
        t_tr = _obs.serving_trace_now()
        if swap:
            # overlap engines issue the swap-out DMA NON-BLOCKING: the
            # device→host copy rides under the in-flight decode step
            # and the host-store entry materializes at the next commit
            # fence (ISSUE 12 satellite a)
            freed = self.cache.swap_out(slot, req.rid,
                                        nonblocking=self.overlap)
            req.swapped = True
            if t_tr:
                _obs.serving_trace_span(
                    req, "swap_out", t_tr, replica=self.replica_id,
                    slot=slot, seq=len(req.tokens),
                    meta={"pages": int(freed),
                          "nonblocking": bool(self.overlap)})
        else:
            freed = self.cache.evict_for_preempt(slot)
        if t_tr:
            _obs.serving_trace_mark(
                req, "preempt", replica=self.replica_id, slot=slot,
                seq=len(req.tokens), meta={"swap": bool(swap)})
        self._clear_slot(slot)
        req.slot = None
        req.preemptions += 1
        req.finish_reason = "preempted"
        if self.adapters is not None and req.adapter_id:
            # the evicted request holds no device residency of any kind
            # while preempted: re-admission re-pins (and, if the slot
            # was reclaimed meanwhile, promotes the adapter back)
            self.adapters.release(req.adapter_id)
        _obs.serving_preempted(1, freed)
        return freed

    def cancel_request(self, req: GenerationRequest,
                       reason: str = "cancelled"):
        """Finish ``req`` without further decode (e.g. a scheduler's
        ``deadline_exceeded``): a running request releases its slot and
        pages, a queued/preempted one just marks done. Idempotent on
        finished requests."""
        if req.done:
            return
        if req.slot is not None and self._slots[req.slot] is req:
            self._pending.pop(req.slot, None)
            self._retire(req, reason)
            return
        try:
            self._queue.remove(req)
        except ValueError:
            pass                        # scheduler-owned queue entry
        req.done = True
        req.finish_reason = reason
        _obs.serving_trace_finish(req, reason, replica=self.replica_id)
        if getattr(self.cache, "host", None) is not None:
            # a swap-preempted victim cancelled while evicted retires
            # its host payload with it (nothing will ever swap it in)
            self.cache.drop_swapped(req.rid)
        if req.preemptions > 0:
            # preempted awaiting resume: it WAS admitted (its pages
            # already freed at preempt time) — the cancel finalizes
            # the retirement so admissions - evictions drains to zero
            _obs.serving_retired(1, reason)
        else:
            # never held a slot/pages: a cancellation, NOT an eviction
            _obs.serving_cancelled(1, reason)

    def _admit(self):
        """Fill free slots from the queue (FIFO; a head-of-line request
        the pool can't cover yet blocks admission — fairness over
        utilization). Priority-aware admission lives in
        :class:`~paddle_tpu.serving.ServingScheduler`, which calls
        :meth:`admit_request` directly."""
        from ..serving import PoolExhausted
        while self._queue:
            try:
                if not self.admit_request(self._queue[0]):
                    break               # no free slot
            except PoolExhausted:
                if not self.cache.active.any():
                    raise  # nothing running will ever free pages
                break
            self._queue.pop(0)

    def prefill_dispatch(self, slot: Optional[int] = None,
                         max_tokens: Optional[int] = None) -> int:
        """DISPATCH half of :meth:`prefill_step` (ISSUE 12): launch one
        pending admission's next static-shape chunk program and queue
        an in-flight handle; ALL host mutation (the ``done`` cursor,
        prefix registration, first-token sampling) waits for
        :meth:`commit_prefills`. On a FINAL chunk the first token is
        argmax/sampled ON DEVICE here — the PRNG split happens at
        dispatch, so the sync and overlapped paths split keys in the
        same order — and only the scalar fetch is deferred to commit.
        Returns the width actually scheduled (0 when nothing was)."""
        if not self._pending:
            return 0
        cache = self.cache
        if slot is None:
            slot = min(self._pending,
                       key=lambda s: self._pending[s][0].rid)
        req, seq, done = self._pending[slot]
        if any(h["slot"] == slot for h in self._inflight_chunks):
            raise RuntimeError(
                f"prefill_dispatch: slot {slot} already has an "
                f"in-flight chunk — commit it first")
        S = seq.size
        page = cache.page_size
        remaining = S - done
        width = cache.pages_for(remaining) * page
        if self.prefill_chunk is not None:
            width = min(width, self.prefill_chunk)
        if max_tokens is not None:
            cap = (int(max_tokens) // page) * page
            if cap < page:
                return 0
            width = min(width, cap)
        take = min(remaining, width)
        # ctx_cap buckets UP to a power-of-two page count so the
        # (ctx_cap, width) compile-key space stays O(width_buckets *
        # log(pages_per_seq)) instead of quadratic in pages_per_seq —
        # shared-prefix lengths and prompt lengths vary independently
        # across requests.
        ctx_cap = cache.ctx_cap_pages(cache.pages_for(done)) * page
        chunk = np.zeros((1, width), np.int32)
        chunk[0, :take] = seq[done:done + take]
        # resilience site: fires before the chunk program so a fault
        # commits nothing (neither ``done`` nor a sampled token)
        _fault_point("prefill_chunk")
        t0 = _obs.generate_begin()
        args = [self.params, jnp.asarray(chunk), cache.pool,
                jnp.asarray(cache.block_tables[slot]), jnp.int32(done),
                jnp.int32(take)]
        if self.adapters is not None:
            args += [self.adapters.arrays,
                     jnp.asarray(self._aslot[slot:slot + 1])]
        logits, cache.pool = self._chunk_fn(ctx_cap, width)(*args)
        samp = rawmax = None
        if done + take >= S and not req.tokens:
            # final chunk of a fresh admission (or a mid-prefill
            # victim's resume): the first token comes from these
            # logits. Keep the sample on device; fetch at commit.
            lg = logits[0]
            if self.constraints and req.constraint is not None:
                # the FIRST token obeys the grammar too: the slot mask
                # (installed at admission from the DFA start state)
                # applies before the argmax/categorical, same rule as
                # the decode program's in-graph where. The UNMASKED
                # argmax rides along so the violation-avoided counter
                # covers this commit path like the decode one.
                rawmax = jnp.argmax(lg)
                lg = jnp.where(jnp.asarray(self._cmask[slot]), lg,
                               -jnp.inf)
            if self.temperature == 0.0:
                samp = jnp.argmax(lg)
            else:
                self._key, k = jax.random.split(self._key)
                samp = jax.random.categorical(
                    k, lg / self.temperature)
        self._inflight_chunks.append(
            {"slot": slot, "req": req, "seat": int(self._seat[slot]),
             "take": take, "t0": t0, "logits": logits, "samp": samp,
             "rawmax": rawmax, "ttr": _obs.serving_trace_now()})
        return width

    def _commit_chunk(self, h: Dict) -> int:
        """COMMIT half of one dispatched prefill chunk: fence, advance
        the ``done`` cursor, and on completion publish the prompt to
        the prefix trie and seed decode — on a preemption RESUME the
        next token is already known and is fed back into decode
        instead of re-sampling (the resumed request must not fork)."""
        slot, req, take = h["slot"], h["req"], h["take"]
        cache = self.cache
        # both obs calls fence the chunk logits when a sink is active —
        # that wait is device time, not exposed host time
        t_f0 = time.perf_counter_ns()
        if self.fused:
            _obs.serving_fused_latency("chunk_flash_attn", h["t0"],
                                       h["logits"])
        _obs.serving_prefill_chunk(h["t0"], h["logits"], take)
        self._fence_ns += time.perf_counter_ns() - t_f0
        ent = self._pending.get(slot)
        if (ent is None or ent[0] is not req
                or int(self._seat[slot]) != h["seat"]):
            # cancelled/expired — or preempted and RE-ADMITTED (even
            # the same request: the seat generation moved, so this
            # chunk's KV went to the old seating's freed pages) —
            # between dispatch and commit: commit nothing; the fresh
            # admission replays the span through its own chunks
            return 0
        done = ent[2] + take
        _obs.serving_trace_span(
            req, "prefill_chunk", h.get("ttr", 0),
            replica=self.replica_id, slot=slot, seq=len(req.tokens),
            meta={"take": int(take), "done": int(done)})
        if done < ent[1].size:
            ent[2] = done
            return take
        del self._pending[slot]
        cache.register_prefix(slot, req.prompt[0])
        cache.lengths[slot] = ent[1].size
        req.finish_reason = None            # clears transient "preempted"
        if req.tokens:
            # preemption resume: the replay covered prompt +
            # tokens[:-1]; decode continues from the already-sampled
            # last token (its KV lands on the next decode step, exactly
            # as in the uninterrupted run).
            self._last[slot] = np.int32(req.tokens[-1])
        else:
            t_f = time.perf_counter_ns()
            first = int(h["samp"])          # the ONE device→host fetch
            self._fence_ns += time.perf_counter_ns() - t_f
            self._last[slot] = first
            # violation check against the PRE-advance slot mask with
            # the UNMASKED argmax, mirroring the decode commit — read
            # BEFORE _record_token, whose retirement clears the slot
            # (and its mask) when this token finishes the request
            viol = (int(not self._cmask[slot, int(h["rawmax"])])
                    if self.constraints and req.constraint is not None
                    else 0)
            self._record_token(req, first)
            if self.constraints and req.constraint is not None:
                t0m = time.perf_counter_ns()
                req.constraint.advance(first)
                if not req.done:
                    self._cmask[slot] = req.constraint.mask(
                        self.cfg.vocab_size)
                    self._cmask_dirty = True
                _obs.serving_constrain(
                    time.perf_counter_ns() - t0m, viol, 1)
        return take

    def commit_prefills(self) -> int:
        """Commit every in-flight prefill chunk in dispatch order;
        returns prompt tokens committed."""
        n = 0
        chunks, self._inflight_chunks = self._inflight_chunks, []
        for h in chunks:
            n += self._commit_chunk(h)
        return n

    def prefill_step(self, slot: Optional[int] = None,
                     max_tokens: Optional[int] = None) -> int:
        """Advance ONE pending admission by one static-shape chunk
        (default: the oldest, FIFO): the per-step latency added to
        in-flight decodes is bounded by one chunk's forward instead of
        a whole prompt's. ``max_tokens`` caps the chunk width (floored
        to a page multiple — the scheduler's token-budget debit must be
        a hard ceiling); returns the width actually scheduled (0 when
        nothing was). The final chunk's logits (taken at the last VALID
        token) seed sampling — except on a preemption RESUME, where the
        next token is already known and is fed back into decode instead
        — and the completed prompt's pages are published to the prefix
        trie for future admissions. Synchronous composition of
        :meth:`prefill_dispatch` + :meth:`commit_prefills` — the
        overlapped scheduler drives the halves separately."""
        width = self.prefill_dispatch(slot, max_tokens=max_tokens)
        self.commit_prefills()
        return width

    def _record_token(self, req: GenerationRequest, tok: int):
        req.tokens.append(int(tok))
        if len(req.tokens) == 1:
            _obs.serving_trace_first_token(req)
        if req.slot is not None:
            # keep the vectorized-commit mirror in sync on the scalar
            # paths (prefill first-token, spec commit loop)
            self._ntok[req.slot] = len(req.tokens)
        if req.eos_token_id is not None and tok == req.eos_token_id:
            self._retire(req, "eos")
        elif len(req.tokens) >= req.max_new_tokens:
            self._retire(req, "max_len")

    def _retire(self, req: GenerationRequest, reason: str):
        req.done = True
        req.finish_reason = reason
        _obs.serving_trace_finish(req, reason, replica=self.replica_id)
        self.cache.release(req.slot)
        self._clear_slot(req.slot)
        if self.adapters is not None and req.adapter_id:
            self.adapters.release(req.adapter_id)
        _obs.serving_retired(1, reason)

    def _tp_observe(self):
        """tp-serving telemetry (ISSUE 7): the per-shard pool gauge
        every step, plus — every 16th step — a TIMED logits-collective
        probe: a dedicated jitted all-gather of a logits-shard-sized
        array over the serving mesh. The step program's own collective
        time is invisible from the host (it fuses into one XLA
        program), so the probe measures the same collective in
        isolation and feeds the ``serving_tp_logits_gather_ms``
        histogram."""
        if self.mesh is None or not _obs.active():
            return
        alloc = self.cache.allocator
        _obs.serving_tp_step(self._tp, alloc.num_used, alloc.num_usable)
        if (self._steps - 1) % 16:      # first step, then every 16th
            return
        if self._tp_probe is None:
            from jax.experimental.shard_map import shard_map
            from jax.sharding import NamedSharding, PartitionSpec as P
            mesh, ax, tp = self.mesh, self._tp_axis, self._tp
            vp = -(-self.cfg.vocab_size // tp)  # per-shard logits cols
            x = jax.device_put(
                jnp.zeros((self.max_batch, vp * tp), jnp.float32),
                NamedSharding(mesh, P(None, ax)))
            f = jax.jit(shard_map(
                lambda t: jax.lax.all_gather(t, ax, axis=1, tiled=True),
                mesh=mesh, in_specs=P(None, ax), out_specs=P(),
                check_rep=False))
            np.asarray(f(x))            # compile outside the timing
            self._tp_probe = (f, x)
        probe, x = self._tp_probe
        t0 = _obs.generate_begin()
        _obs.serving_tp_logits_gather(t0, probe(x))

    # ---- prefill→decode KV handoff (ISSUE 9) ----
    def export_prefilled(self, req: GenerationRequest,
                         with_kv: bool = True) -> Dict:
        """Export a fully prefilled, decode-ready request's KV pages as
        a handoff payload (the disaggregated cluster's prefill→decode
        transfer): the slot's live page bytes
        (:meth:`~paddle_tpu.serving.PagedKVCache.export_request`), the
        committed length and the already-sampled last token. PURE READ
        — the request keeps running here until :meth:`finish_handoff`
        detaches it, so a failed import on the decode side loses
        nothing. ``with_kv=False`` (the ISSUE 11 fused direct-handoff
        path) skips materializing the page bytes on the host — the
        importer copies them device-to-device through the fused
        :func:`~paddle_tpu.serving.paged_cache._pool_move` instead;
        the payload then carries only the slot metadata."""
        slot = req.slot
        if slot is None or self._slots[slot] is not req:
            raise ValueError(
                f"export_prefilled: request {req.rid} is not running")
        if slot in self._pending:
            raise ValueError(
                f"export_prefilled: request {req.rid} is still "
                f"mid-prefill — hand off only decode-ready slots")
        out = {"rid": req.rid, "slot": slot,
               "length": int(self.cache.lengths[slot]),
               "last": int(self._last[slot])}
        if with_kv:
            out["kv"] = self.cache.export_request(slot)
        return out

    def import_prefilled(self, req: GenerationRequest,
                         payload: Dict, src_engine=None) -> bool:
        """Install an exported request DIRECTLY into a decode slot: the
        payload's pages scatter into freshly allocated pages
        (:meth:`~paddle_tpu.serving.PagedKVCache.import_request`), the
        block table / length / last-token state matches what in-place
        prefill would have left, and the prompt's pages publish to THIS
        engine's prefix trie (future same-prefix admissions here HIT).
        Returns False when no slot is free; raises
        :class:`~paddle_tpu.serving.PoolExhausted` (nothing changed)
        when the pool can't cover it. Decode from here is BIT-identical
        to having prefilled in place.

        ``src_engine`` (ISSUE 11): the exporting engine, when it shares
        this process — the pages then copy device-to-device through the
        fused :func:`~paddle_tpu.serving.paged_cache._pool_move` (one
        donated program, no host staging) and the payload needs no
        ``"kv"`` bytes (``export_prefilled(with_kv=False)``). Same
        byte-identity gate either way."""
        free = self.cache.free_slots()
        if not free:
            return False
        slot = free[0]
        # the importing engine pins the adapter on ITS pool (the KV
        # payload is adapter-agnostic by the q/o-only design, so the
        # bytes install unchanged; a failed page install drops the pin)
        pinned = False
        if self.adapters is not None and req.adapter_id:
            self.adapters.acquire(req.adapter_id)
            pinned = True
        try:
            if src_engine is not None:
                self.cache.import_request_direct(
                    slot, src_engine.cache, payload["slot"],
                    req.prompt.shape[1] + req.max_new_tokens)
            else:
                self.cache.import_request(
                    slot, payload["kv"],
                    req.prompt.shape[1] + req.max_new_tokens)
        except BaseException:
            if pinned:
                self.adapters.release(req.adapter_id)
            raise
        self.cache.lengths[slot] = np.int32(payload["length"])
        self._last[slot] = np.int32(payload["last"])
        self._install_slot(slot, req)
        self.cache.register_prefix(slot, req.prompt[0])
        return True

    def finish_handoff(self, req: GenerationRequest, slot: int):
        """Detach a handed-off request from THIS engine after a
        successful import elsewhere: the slot entry clears FIRST (so
        even a fault inside the page release cannot leave two engines
        decoding the same request), then the pages release — ones the
        prefix trie shares survive under its references, which is what
        keeps the prefill replica's trie warm for the tenant's next
        prompt. ``slot`` is the ORIGINAL slot from the export payload
        (``req.slot`` already points at the importing engine)."""
        if self._slots[slot] is not req:
            raise ValueError(
                f"finish_handoff: slot {slot} does not hold request "
                f"{req.rid}")
        self._clear_slot(slot)
        self._pending.pop(slot, None)
        self.cache.release(slot)
        if self.adapters is not None and req.adapter_id:
            # the importing engine took its own pin; this side's drops
            self.adapters.release(req.adapter_id)

    def ready_mask(self) -> np.ndarray:
        """(max_batch,) bool — slots whose sequence is fully in the
        pool and can decode this step; slots mid-prefill hold pages
        (active) but skip the decode program."""
        ready = self.cache.active.copy()
        if self._pending:
            ready[list(self._pending)] = False
        return ready

    # ---- dispatch / commit halves (ISSUE 12 overlapped runtime) ----
    def has_inflight(self) -> bool:
        """True while a dispatched decode/verify program or prefill
        chunk awaits its commit — the overlapped scheduler's signal
        that a commit fence is pending."""
        return self._inflight is not None or bool(self._inflight_chunks)

    def take_fence_ns(self) -> int:
        """Device-wait nanoseconds accumulated by commit fences since
        the last call — the scheduler's host-vs-device attribution
        input for the ``host_overhead_fraction`` gauge."""
        ns, self._fence_ns = self._fence_ns, 0
        return ns

    def decode_dispatch(self, mask) -> Optional[InFlightStep]:
        """DISPATCH half of :meth:`decode_step`: launch the jitted
        ragged decode program for the ``mask`` slots and return the
        in-flight handle WITHOUT fetching the result — the device works
        while the caller plans the next step. The PRNG split happens
        here (same order as the synchronous path). At most one
        decode/verify program may be in flight."""
        cache = self.cache
        mask = np.asarray(mask, bool)
        if not mask.any():
            return None
        if self._inflight is not None:
            raise RuntimeError(
                "decode_dispatch: a decode/verify program is already "
                "in flight — commit_inflight() first")
        # resilience sites: step execution (before the launch), then
        # the dispatch seam (after it) — neither commits host state,
        # so a fault at either recovers by journal replay
        _fault_point("decode_step")
        t0f = _obs.generate_begin() if self.fused else 0
        self._key, k = jax.random.split(self._key)
        args = [self.params, jnp.asarray(self._last), cache.pool,
                jnp.asarray(cache.block_tables),
                jnp.asarray(cache.lengths),
                jnp.asarray(mask), k]
        if self.adapters is not None:
            args += [self.adapters.arrays, jnp.asarray(self._aslot)]
        if self.constraints:
            if self._cmask_dirty or self._cmask_dev is None:
                self._cmask_dev = jnp.asarray(self._cmask)
                self._cmask_dirty = False
            args += [self._cmask_dev]
        out, cache.pool = self._decode()(*args)
        raw = None
        if self.constraints:
            out, raw = out
        _fault_point("dispatch")
        self._inflight = InFlightStep("decode", mask, self._rids.copy(),
                                      self._seat.copy(), out, t0f=t0f,
                                      raw=raw,
                                      ttr=_obs.serving_trace_now())
        return self._inflight

    def _decode_commit(self, h: InFlightStep) -> int:
        """COMMIT half of :meth:`decode_step`: the single device→host
        fetch plus VECTORIZED host bookkeeping — lengths/last-token
        scatter via one fancy-indexed update, eos/max_len finish
        detection against the mirrored per-slot arrays, per-row Python
        work only for the rows that actually finish. A slot whose
        request changed since dispatch (preempt + readmit) is skipped
        via the rid snapshot; the dropped token is re-decoded
        greedy-identically on resume."""
        cache = self.cache
        # resilience sites: the commit seam, then the device→host
        # transfer — host state commits only after both, so a fault at
        # either leaves the request handles at the previous step's
        # committed state (the supervisor's recovery contract)
        _fault_point("commit")
        # the device-wait window OPENS before the observability calls:
        # serving_fused_latency fences h.out when metrics are on, and
        # charging that wait to exposed host time would inflate the
        # host_overhead_fraction gauge exactly when it is emitted
        t_f = time.perf_counter_ns()
        _obs.serving_fused_latency("decode_rope_attn", h.t0f, h.out)
        _fault_point("transfer")
        nxt = np.asarray(h.out)
        self._fence_ns += time.perf_counter_ns() - t_f
        valid = (h.mask & (self._rids == h.rids) & (h.rids >= 0)
                 & (self._seat == h.seats))
        slots = np.flatnonzero(valid)
        if slots.size:
            toks = nxt[slots]
            cache.lengths[slots] += 1
            self._last[slots] = toks
            new_cnt = self._ntok[slots] + 1
            self._ntok[slots] = new_cnt
            fin_eos = (self._eos[slots] >= 0) & (toks == self._eos[slots])
            fin_max = new_cnt >= self._maxnew[slots]
            sl, tl = slots.tolist(), toks.tolist()
            for s, t in zip(sl, tl):
                self._slots[s].tokens.append(t)
            if h.ttr:
                # one decode_step span per committed row, closed at the
                # commit fence (h.ttr anchored at dispatch). The
                # vectorized append above bypasses _record_token, so
                # the TTFT stamp happens here for first tokens.
                t1 = _obs.serving_trace_now()
                for s in sl:
                    treq = self._slots[s]
                    _obs.serving_trace_span(
                        treq, "decode_step", h.ttr, t1,
                        replica=self.replica_id, slot=s,
                        seq=len(treq.tokens))
                    if len(treq.tokens) == 1:
                        _obs.serving_trace_first_token(treq)
            if self.constraints:
                # advance each constrained row's DFA with the token
                # that actually COMMITTED, refresh its next-step mask,
                # and count the steps where the UNCONSTRAINED argmax
                # would have violated the grammar (each one is a saved
                # parse failure). Runs BEFORE retirement clears slots.
                t0m = time.perf_counter_ns()
                raw = np.asarray(h.raw)
                viol = crows = 0
                for s, t in zip(sl, tl):
                    creq = self._slots[s]
                    if creq is None or creq.constraint is None:
                        continue
                    crows += 1
                    if not self._cmask[s, int(raw[s])]:
                        viol += 1
                    creq.constraint.advance(t)
                    self._cmask[s] = creq.constraint.mask(
                        self.cfg.vocab_size)
                    self._cmask_dirty = True
                if crows:
                    _obs.serving_constrain(
                        time.perf_counter_ns() - t0m, viol, crows)
            for i in np.flatnonzero(fin_eos | fin_max).tolist():
                self._retire(self._slots[sl[i]],
                             "eos" if fin_eos[i] else "max_len")
        self._steps += 1
        alloc = cache.allocator
        # occupancy reports the rows the DISPATCHED program computed
        # (mask), matching the synchronous path; the return counts only
        # rows that passed the seat guard and actually committed —
        # identical in sync mode (nothing re-seats between dispatch and
        # commit there), honest under overlap preemption races
        _obs.serving_step(int(h.mask.sum()), self.max_batch,
                          alloc.num_used, alloc.num_usable)
        if self._dp_axis is not None:
            # per-dp-shard row load of the DISPATCHED program: slot s
            # rides shard s // (max_batch/dp), the same contiguous
            # row-block split the "batch" in_specs apply
            _obs.serving_dp_step(
                self.dp, h.mask.reshape(self.dp, -1).sum(axis=1))
        self._tp_observe()
        return int(slots.size)

    def commit_inflight(self) -> int:
        """Commit everything in flight, in dispatch order: prefill
        chunks first (they were dispatched first — the decode program
        chained behind them on device), then the decode/verify step;
        finally fence any pending async swap-out DMAs into the host
        store (ISSUE 12 satellite a). Returns the number of committed
        units (prompt tokens + decode slots / verify tokens)."""
        n = self.commit_prefills()
        h, self._inflight = self._inflight, None
        if h is not None:
            n += (self._decode_commit(h) if h.kind == "decode"
                  else self._tree_commit(h) if h.kind == "tree"
                  else self._spec_commit(h))
        fence = getattr(self.cache, "fence_swaps", None)
        if fence is not None:
            fence()
        return n

    def decode_step(self, mask) -> int:
        """Advance every ``mask`` slot one decode token through the
        single jitted ragged decode program (callers pass
        :meth:`ready_mask` or a scheduler's budgeted subset of it).
        Returns the number of slots advanced (0 skips the program
        entirely). Synchronous composition of :meth:`decode_dispatch`
        + :meth:`commit_inflight` — the bit-identity reference the
        overlapped scheduler is gated against."""
        if self.decode_dispatch(mask) is None:
            return 0
        return self.commit_inflight()

    # ---- speculative decoding (ISSUE 5) ----
    def propose_drafts(self, mask) -> Dict[int, np.ndarray]:
        """Draft proposals for every masked ready slot — ``slot ->
        up-to-spec_k draft tokens`` (rows with no in-history match, a
        poor acceptance EMA, or no remaining token room are simply
        absent and decode plainly). Separated from :meth:`spec_step`
        so the SLO scheduler can charge each row's verify width
        against its token budget BEFORE executing.

        With a DRAFT MODEL configured (``draft_layers``, ISSUE 20) the
        proposals come from :meth:`_propose_model_drafts` instead of
        the host n-gram lookup; under ``spec_tree`` the returned
        values are :class:`~paddle_tpu.serving.speculative.TreeDraft`
        trees, which satisfy the same ``d.size`` / ``d[:k]`` planner
        contract (the budget charges tree NODES; trimming drops
        leaves, never the root path)."""
        if self.spec is None:
            return {}
        if self.draft_params is not None:
            return self._propose_model_drafts(mask)
        mask = np.asarray(mask, bool)
        drafts: Dict[int, np.ndarray] = {}
        for slot, req in enumerate(self._slots):
            if req is None or not mask[slot]:
                continue
            # a verify commits accepted + 1 (bonus) tokens: cap drafts
            # so the commit can never overshoot max_new_tokens — plain
            # decode would have stopped there, and parity is the gate
            room = req.max_new_tokens - len(req.tokens) - 1
            if room <= 0:
                continue
            d = self.spec.propose(
                slot, req.rid,
                np.concatenate([req.prompt[0],
                                np.asarray(req.tokens, np.int32)]),
                cap=min(self.spec_k, room))
            if d.size:
                drafts[slot] = d
        return drafts

    def _propose_model_drafts(self, mask) -> Dict:
        """DRAFT-MODEL proposer (ISSUE 20): k autoregressive steps of
        the truncated-layer draft model on device, against the slot's
        own pages in the SECOND (draft) paged pool.

        Protocol per masked row: (1) lazy-admit a draft-pool slot
        (PoolExhausted skips drafting — pure back-pressure, the row
        decodes plainly); (2) CATCH-UP — feed the gap between the
        draft pool's valid prefix and the committed context (all but
        the last token) through the draft verify forward. Steady state
        is zero-width: every commit leaves the pool caught up, so the
        catch-up only pays on a cold slot (first propose, resume,
        crash recovery) — which is exactly the disposable-pool
        rebuild; (3) k one-token draft decode steps from the last
        sampled token, each yielding the full distribution q on the
        host: the chain token is its argmax (or a q-sample at
        temperature — the rejection sampler's min(1, p/q) requires
        drafts ~ q), tree mode takes the top-``width`` candidates per
        depth (deterministic candidates keep sequential point-mass
        rejection exact in law).

        The draft pool's ``lengths`` stay at the VALID prefix — the
        speculative feeds advance only a local run-length, so a
        fallback plain-decode step (or a preemption) never has to roll
        anything back; the commit advances the valid prefix past
        exactly the accepted tokens that match the fed chain."""
        from ..serving import PoolExhausted
        from ..serving.speculative import TreeDraft, build_comb_tree
        mask = np.asarray(mask, bool)
        dc = self.draft_cache
        _fault_point("draft_propose")
        rows: Dict[int, np.ndarray] = {}
        rooms: Dict[int, int] = {}
        for slot, req in enumerate(self._slots):
            if req is None or not mask[slot]:
                continue
            room = req.max_new_tokens - len(req.tokens) - 1
            if room <= 0:
                continue
            if not dc.active[slot]:
                total = (req.prompt.shape[1] + req.max_new_tokens
                         + self.spec_k + 1)
                try:
                    dc.admit(slot, total)
                except PoolExhausted:
                    continue        # back-pressure: decode plainly
                dc.lengths[slot] = 0
            rows[slot] = np.concatenate(
                [req.prompt[0], np.asarray(req.tokens, np.int32)])
            rooms[slot] = room
        if not rows:
            return {}
        B, k, temp = self.max_batch, self.spec_k, self.temperature
        # --- catch-up: page-bucketed verify chunks over the draft
        # model until every row's pool covers its context minus the
        # last token (multi-chunk only for prompt-scale gaps)
        catchup = 0
        while True:
            need = {s: rows[s].size - 1 - int(dc.lengths[s])
                    for s in rows}
            cmax = max(need.values())
            if cmax <= 0:
                break
            W = 1
            while W < min(cmax, 128):
                W *= 2
            chunk = np.zeros((B, W), np.int32)
            cmask = np.zeros((B,), bool)
            adv = np.zeros((B,), np.int32)
            for s, c in need.items():
                if c <= 0:
                    continue
                c = min(c, W)
                st = int(dc.lengths[s])
                chunk[s, :c] = rows[s][st:st + c]
                cmask[s] = True
                adv[s] = c
                catchup += c
            ctx_cap = dc.ctx_cap_pages(dc.pages_for(
                int(dc.lengths[cmask].max()))) * dc.page_size
            dc.pool = self._draft_catchup_fn(ctx_cap, W)(
                self.draft_params, jnp.asarray(chunk), dc.pool,
                jnp.asarray(dc.block_tables), jnp.asarray(dc.lengths),
                jnp.asarray(cmask))
            dc.lengths[cmask] += adv[cmask]
        # --- autoregressive draft loop (speculative feeds advance
        # only the LOCAL run-length; dc.lengths stays the valid prefix)
        amask = np.zeros((B,), bool)
        for s in rows:
            amask[s] = True
            self._draft_base[s] = rows[s].size
        run_len = dc.lengths.copy()
        x = self._last.copy()
        tree_w = self.spec_tree[0] if self.spec_tree is not None else 0
        chains = {s: [] for s in rows}
        fed = {s: [] for s in rows}
        qs = ({s: [] for s in rows}
              if temp != 0.0 and not tree_w else None)
        cands = {s: [] for s in rows} if tree_w else None
        dec = self._draft_decode()
        for i in range(k):
            logits, dc.pool = dec(
                self.draft_params, jnp.asarray(x), dc.pool,
                jnp.asarray(dc.block_tables), jnp.asarray(run_len),
                jnp.asarray(amask))
            logits = np.asarray(logits)
            run_len[amask] += 1
            for s in rows:
                z = logits[s].astype(np.float64)
                if tree_w:
                    top = np.argsort(z)[::-1][:tree_w]
                    cands[s].append(top.astype(np.int32))
                    nxt = int(top[0])
                elif temp != 0.0:
                    z = z / temp
                    z -= z.max()
                    q = np.exp(z)
                    q /= q.sum()
                    nxt = int(self._accept_rng.choice(q.size, p=q))
                    qs[s].append(q)
                else:
                    nxt = int(np.argmax(z))
                if len(chains[s]) < min(k, rooms[s]):
                    chains[s].append(nxt)
                if i < k - 1:
                    fed[s].append(nxt)
                x[s] = nxt
        out: Dict = {}
        drafted = 0
        for s in rows:
            self._draft_chain[s] = np.asarray(fed[s], np.int32)
            if tree_w:
                t = build_comb_tree(
                    int(self._last[s]),
                    np.asarray(chains[s], np.int32),
                    [c[1:] for c in cands[s]])
                t = t[:min(t.size, rooms[s])]
                if t.size:
                    out[s] = t
                    drafted += t.size
            else:
                d = np.asarray(chains[s], np.int32)
                if qs is not None:
                    self._draft_q[s] = np.stack(qs[s])[:d.size]
                if d.size:
                    out[s] = d
                    drafted += d.size
        _obs.serving_draft_propose(len(rows), drafted, catchup)
        _obs.serving_draft_pool(dc.allocator.num_used,
                                dc.allocator.num_usable)
        return out

    def spec_step(self, mask, drafts: Optional[Dict] = None) -> int:
        """The speculative sibling of :meth:`decode_step`, sharing its
        ready-mask machinery: draft (host n-gram lookup), verify all
        masked rows' drafts in ONE batched forward
        (:func:`~paddle_tpu.models.generate.paged_verify_forward` +
        greedy argmax at every position), then commit each row's
        longest accepted prefix plus the bonus token. Rows without
        drafts ride the same program and commit exactly their plain
        greedy token (the static-shape program computes every lane
        regardless, like the decode program's inactive rows); when NO
        masked row drafted, this falls back to :meth:`decode_step`
        outright — the worst case is the baseline step. Returns the
        number of tokens committed (>= slots advanced).

        Rollback of rejected draft KV is pure host bookkeeping:
        ``lengths`` advances only past the accepted prefix, the length
        mask keeps the stale page rows invisible, and the strictly
        sequential writes at ``lengths`` overwrite them before the mask
        ever reaches them — no device copy, no page churn (the
        allocator never sees a verify)."""
        if self.spec_dispatch(mask, drafts) is None:
            return 0
        return self.commit_inflight()

    def spec_plan_widths(self, mask) -> Dict[int, int]:
        """Pessimistic per-row verify widths for budget planning when
        drafts cannot be proposed yet: the OVERLAPPED scheduler plans
        step N+1 before step N commits, so the history the n-gram
        proposer needs is not final. Charging ``min(spec_k, room)``
        per ready row keeps the token budget a hard ceiling (executed
        drafts are trimmed to the planned allowance at dispatch);
        rows with no token room are absent, exactly as in
        :meth:`propose_drafts`. Tree speculation (ISSUE 20) charges
        tree NODES — the verify program's width is the whole tree, so
        the pessimistic width is ``width x depth`` (the planner's trim
        then drops leaves first; the root path survives, so the token
        ceiling stays hard)."""
        if self.spec is None:
            return {}
        mask = np.asarray(mask, bool)
        nodes = (self._tree_T - 1 if self.spec_tree is not None
                 else self.spec_k)
        out: Dict[int, int] = {}
        for slot, req in enumerate(self._slots):
            if req is None or not mask[slot]:
                continue
            room = req.max_new_tokens - len(req.tokens) - 1
            if room > 0:
                out[slot] = min(nodes, room)
        return out

    def spec_dispatch(self, mask,
                      drafts: Optional[Dict] = None
                      ) -> Optional[InFlightStep]:
        """DISPATCH half of :meth:`spec_step`: build the draft chunk,
        launch the batched verify program, return the in-flight handle.
        Falls back to :meth:`decode_dispatch` when no masked row
        drafted (the worst case is the baseline step)."""
        if self.spec is None:
            return self.decode_dispatch(mask)
        cache = self.cache
        mask = np.asarray(mask, bool)
        if not mask.any():
            return None
        if self._inflight is not None:
            raise RuntimeError(
                "spec_dispatch: a decode/verify program is already "
                "in flight — commit_inflight() first")
        if drafts is None:
            drafts = self.propose_drafts(mask)
        if self.spec_tree is not None:
            # tree speculation (ISSUE 20): the proposals are TreeDraft
            # trees — one tree-mode verify forward scores every node
            return self._tree_dispatch(mask, drafts)
        drafts = {s: np.asarray(d, np.int32) for s, d in drafts.items()
                  if len(d) and mask[s]}
        if not drafts:
            return self.decode_dispatch(mask)
        # draft-model q snapshot (ISSUE 20): the stashed per-position
        # proposal distributions ride the in-flight handle, trimmed to
        # the (possibly planner-shortened) dispatched width — the
        # commit's rejection sampler accepts with min(1, p/q)
        qs = None
        if self.draft_params is not None and self.temperature != 0.0:
            qs = {s: self._draft_q[s][:d.size]
                  for s, d in drafts.items() if s in self._draft_q}
        B, T = self.max_batch, self.spec_k + 1
        chunk = np.zeros((B, T), np.int32)
        chunk[:, 0] = self._last
        dlen = np.zeros((B,), np.int32)
        for s, d in drafts.items():
            chunk[s, 1:1 + d.size] = d
            dlen[s] = d.size
        # ctx_cap: power-of-two page bucket of the longest masked
        # context (same compile-key rule as chunked prefill; ready
        # rows always hold >= 1 prefilled token, so the cap is > 0)
        ctx_cap = cache.ctx_cap_pages(cache.pages_for(
            int(cache.lengths[mask].max()))) * cache.page_size
        _fault_point("verify_step")
        t0 = _obs.generate_begin()
        args = [self.params, jnp.asarray(chunk), cache.pool,
                jnp.asarray(cache.block_tables),
                jnp.asarray(cache.lengths), jnp.asarray(mask)]
        if self.adapters is not None:
            args += [self.adapters.arrays, jnp.asarray(self._aslot)]
        out, cache.pool = self._spec_fn(ctx_cap, T)(*args)
        _fault_point("dispatch")
        self._inflight = InFlightStep("spec", mask, self._rids.copy(),
                                      self._seat.copy(), out,
                                      drafts=drafts, dlen=dlen, t0=t0,
                                      ttr=_obs.serving_trace_now(),
                                      qs=qs)
        return self._inflight

    def _spec_commit(self, h: InFlightStep) -> int:
        """COMMIT half of :meth:`spec_step`: fetch the greedy targets,
        commit each row's longest accepted prefix + bonus token.
        Rollback of rejected draft KV is pure host bookkeeping (see
        :meth:`spec_step`); slots whose request changed since dispatch
        are skipped via the rid snapshot."""
        cache = self.cache
        mask, drafts, dlen = h.mask, h.drafts, h.dlen
        _fault_point("commit")
        # device-wait window opens before the (fencing) obs call —
        # same host-attribution rule as _decode_commit
        t_f = time.perf_counter_ns()
        if self.fused:
            _obs.serving_fused_latency("verify_flash_attn", h.t0, h.out)
        _fault_point("transfer")
        out = np.asarray(h.out)   # (B, T) greedy targets — or, under
        #                           sampled speculation, (B, T, V)
        #                           verify logits for rejection sampling
        t1 = time.perf_counter_ns()        # device fence: verify done
        self._fence_ns += t1 - t_f
        from ..serving.speculative import (longest_accepted_prefix,
                                           rejection_sample_tokens)
        sampled = self.temperature != 0.0
        n_slots = committed = drafted = accepted = 0
        for slot, req in enumerate(self._slots):
            if (req is None or not mask[slot]
                    or self._rids[slot] != h.rids[slot]
                    or self._seat[slot] != h.seats[slot]):
                continue
            n_slots += 1
            j = int(dlen[slot])
            d = drafts.get(slot)
            if sampled:
                # standard rejection sampling (ISSUE 14): accept draft i
                # with p_i(draft), otherwise draw the corrective token
                # from the residual — output distribution identical in
                # law to plain sampled decode, so temperature>0 rows get
                # the 1+k speedup without changing what they emit.
                # Under the draft model (ISSUE 20) q is the REAL
                # proposal distribution (acceptance min(1, p/q),
                # residual norm_+(p - q)); None keeps the n-gram
                # point-mass law
                q = h.qs.get(slot) if h.qs is not None else None
                toks, a = rejection_sample_tokens(
                    out[slot, :j + 1], d if j else None,
                    self.temperature, self._accept_rng,
                    q=(q[:j] if q is not None and j else None))
            else:
                a = longest_accepted_prefix(d, out[slot]) if j else 0
                toks = ((list(d[:a]) if j else [])
                        + [int(out[slot, a])])
            # draft-pool valid prefix (ISSUE 20): advance past exactly
            # the accepted tokens that MATCH what was fed through the
            # draft model — a mismatch tail re-feeds via catch-up
            if (self.draft_cache is not None
                    and slot in self._draft_chain):
                ch = self._draft_chain.pop(slot)
                m = 0
                while (m < min(a, ch.size)
                       and int(toks[m]) == int(ch[m])):
                    m += 1
                self.draft_cache.lengths[slot] = int(
                    self._draft_base[slot]) + m
            # commit: the last token's KV + a accepted drafts are now
            # context; the corrective/bonus token becomes the new last
            cache.lengths[slot] += a + 1
            self._last[slot] = np.int32(toks[-1])
            for tok in toks:
                self._record_token(req, int(tok))
                committed += 1
                if req.done:
                    break                  # eos/max_len: drop the tail
            if j:
                drafted += j
                accepted += a
                self.spec.observe(slot, req.rid, j, a)
            if h.ttr:
                _obs.serving_trace_span(
                    req, "spec_verify", h.ttr, replica=self.replica_id,
                    slot=slot, seq=len(req.tokens),
                    meta={"drafted": j, "accepted": int(a)})
        if sampled and drafted:
            _obs.serving_sample_accept(drafted, accepted)
        self._steps += 1
        _obs.serving_spec_verify(h.t0, out, n_slots, drafted, accepted,
                                 t1_ns=t1)
        alloc = cache.allocator
        _obs.serving_step(n_slots, self.max_batch, alloc.num_used,
                          alloc.num_usable)
        if self._dp_axis is not None:
            _obs.serving_dp_step(
                self.dp, h.mask.reshape(self.dp, -1).sum(axis=1))
        self._tp_observe()
        return committed

    # ---- tree speculation (ISSUE 20) ----
    def _tree_dispatch(self, mask, trees) -> Optional[InFlightStep]:
        """DISPATCH half of the TREE-speculation step: pack every
        masked row's token tree into one (B, T) chunk — node 0 the
        last sampled token (the root), topology as per-node parent
        indices turned into depths + ancestor matrices — and launch
        the ONE tree-mode verify forward (:meth:`_tree_fn`). Un-drafted
        rows ride the same program as a root-only tree and commit
        exactly their plain token; pad nodes hang off the root and are
        never referenced at commit. When NO masked row holds a tree,
        falls back to plain decode — the worst case is the baseline
        step, same as the linear path."""
        from ..serving.speculative import (TreeDraft, tree_depths,
                                           tree_ancestor_matrix)
        cache = self.cache
        trees = {s: t for s, t in trees.items()
                 if mask[s] and isinstance(t, TreeDraft) and t.size}
        if not trees:
            return self.decode_dispatch(mask)
        B, T = self.max_batch, self._tree_T
        chunk = np.zeros((B, T), np.int32)
        chunk[:, 0] = self._last
        depths = np.ones((B, T), np.int32)
        depths[:, 0] = 0
        anc = np.zeros((B, T, T), bool)
        anc[:, np.arange(T), np.arange(T)] = True
        anc[:, :, 0] = True             # pad nodes hang off the root
        for s, t in trees.items():
            n = t.tokens.size
            chunk[s, :n] = t.tokens
            depths[s, :n] = tree_depths(t.parents)
            anc[s, :n, :n] = tree_ancestor_matrix(t.parents)
        ctx_cap = cache.ctx_cap_pages(cache.pages_for(
            int(cache.lengths[mask].max()))) * cache.page_size
        _fault_point("tree_verify")
        t0 = _obs.generate_begin()
        args = [self.params, jnp.asarray(chunk), cache.pool,
                jnp.asarray(cache.block_tables),
                jnp.asarray(cache.lengths), jnp.asarray(mask),
                jnp.asarray(depths), jnp.asarray(anc)]
        if self.adapters is not None:
            args += [self.adapters.arrays, jnp.asarray(self._aslot)]
        out, rows = self._tree_fn(ctx_cap, T)(*args)
        _fault_point("dispatch")
        self._inflight = InFlightStep(
            "tree", mask, self._rids.copy(), self._seat.copy(), out,
            drafts=trees, t0=t0, ttr=_obs.serving_trace_now(),
            rows=rows)
        return self._inflight

    def _tree_commit(self, h: InFlightStep) -> int:
        """COMMIT half of the tree step: fetch the per-node targets,
        pick each row's longest accepted ROOT PATH (greedy:
        :func:`~paddle_tpu.serving.speculative.longest_accepted_path`;
        sampled: sequential point-mass rejection down the tree,
        :func:`~paddle_tpu.serving.speculative.tree_rejection_sample`),
        place exactly those nodes' KV via the jitted
        :meth:`_tree_commit_fn` (positions are the PRE-commit lengths,
        bit-identical to a linear verify of the path), then run the
        host bookkeeping. Rejected nodes were never placed, so
        rejection needs NO rollback of any kind; guard-skipped slots
        pass path_len 0 and their nodes route to the trash page."""
        cache = self.cache
        mask = h.mask
        _fault_point("commit")
        t_f = time.perf_counter_ns()
        if self.fused:
            _obs.serving_fused_latency("verify_flash_attn", h.t0, h.out)
        _fault_point("transfer")
        out = np.asarray(h.out)     # (B, T) argmax — or, sampled,
        #                             (B, T, V) per-node verify logits
        t1 = time.perf_counter_ns()
        self._fence_ns += t1 - t_f
        from ..serving.speculative import (longest_accepted_path,
                                           tree_rejection_sample)
        sampled = self.temperature != 0.0
        B, T = self.max_batch, self._tree_T
        path_nodes = np.zeros((B, T), np.int32)
        path_len = np.zeros((B,), np.int32)
        base_len = cache.lengths.copy()
        plans = []
        for slot, req in enumerate(self._slots):
            if (req is None or not mask[slot]
                    or self._rids[slot] != h.rids[slot]
                    or self._seat[slot] != h.seats[slot]):
                continue
            t = h.drafts.get(slot)
            if t is None:
                # un-drafted row: exactly the plain token at the root
                if sampled:
                    z = out[slot, 0].astype(np.float64)
                    z /= self.temperature
                    z -= z.max()
                    p = np.exp(z)
                    p /= p.sum()
                    toks = [int(self._accept_rng.choice(p.size, p=p))]
                else:
                    toks = [int(out[slot, 0])]
                path, a = [0], 0
            elif sampled:
                path, toks, a = tree_rejection_sample(
                    t.tokens, t.parents, out[slot],
                    self.temperature, self._accept_rng)
            else:
                path, toks, a = longest_accepted_path(
                    t.tokens, t.parents, out[slot])
            path_nodes[slot, :len(path)] = path
            path_len[slot] = len(path)
            plans.append((slot, req, t, toks, a))
        # device placement FIRST, against the pre-commit tables and
        # lengths (retirement below resets them for finished rows —
        # their already-placed rows die with their freed pages, the
        # contract every release relies on)
        cache.pool = self._tree_commit_fn(T)(
            cache.pool, h.rows, jnp.asarray(cache.block_tables),
            jnp.asarray(base_len), jnp.asarray(path_nodes),
            jnp.asarray(path_len))
        n_slots = committed = drafted = accepted = 0
        paths = []
        for slot, req, t, toks, a in plans:
            n_slots += 1
            # draft-pool valid prefix: same matched-chain rule as the
            # linear commit (the fed chain is the tree's top-1 spine;
            # an accepted path through a SIBLING diverges and re-feeds
            # from the divergence via catch-up)
            if (self.draft_cache is not None
                    and slot in self._draft_chain):
                ch = self._draft_chain.pop(slot)
                m = 0
                while (m < min(a, ch.size)
                       and int(toks[m]) == int(ch[m])):
                    m += 1
                self.draft_cache.lengths[slot] = int(
                    self._draft_base[slot]) + m
            cache.lengths[slot] += a + 1
            self._last[slot] = np.int32(toks[-1])
            for tok in toks:
                self._record_token(req, int(tok))
                committed += 1
                if req.done:
                    break              # eos/max_len: drop the tail
            if t is not None:
                drafted += t.size
                accepted += a
                paths.append(a + 1)
                self.spec.observe(slot, req.rid, t.size, a)
            if h.ttr:
                _obs.serving_trace_span(
                    req, "tree_verify", h.ttr, replica=self.replica_id,
                    slot=slot, seq=len(req.tokens),
                    meta={"nodes": t.size if t is not None else 0,
                          "accepted": int(a)})
        if sampled and drafted:
            _obs.serving_sample_accept(drafted, accepted)
        self._steps += 1
        _obs.serving_tree_verify(h.t0, out, n_slots, drafted, accepted,
                                 paths, t1_ns=t1)
        alloc = cache.allocator
        _obs.serving_step(n_slots, self.max_batch, alloc.num_used,
                          alloc.num_usable)
        if self._dp_axis is not None:
            _obs.serving_dp_step(
                self.dp, h.mask.reshape(self.dp, -1).sum(axis=1))
        self._tp_observe()
        return committed

    def step(self) -> bool:
        """Admit (FIFO), advance chunked prefill by one chunk, then
        advance every fully prefilled slot — one decode token each, or
        a drafted-and-verified run of tokens when speculation is on
        (``spec_k``). Returns False when no work remains (queue empty,
        all slots idle). Priority/budget/preemption scheduling composes
        the same pieces from
        :class:`~paddle_tpu.serving.ServingScheduler`."""
        self._admit()
        self.prefill_step()
        advance = (self.spec_step if self.spec is not None
                   else self.decode_step)
        if advance(self.ready_mask()) == 0:
            return bool(self._queue or self._pending
                        or self.cache.active.any())
        return bool(self._queue) or bool(self.cache.active.any())

    def run(self) -> None:
        """Drive steps until every submitted request finished."""
        while self.step():
            pass

    # ---- scheduler-facing state accessors ----
    @property
    def idle(self) -> bool:
        """True when nothing is queued, mid-prefill, or decoding — the
        state an external scheduler requires at attach time."""
        return not (self._queue or self._pending
                    or self.cache.active.any())

    def running_requests(self) -> List[GenerationRequest]:
        """Live requests currently holding slots (mid-prefill ones
        included) — the preemption-victim candidate set."""
        return [r for r in self._slots if r is not None]

    def queued_requests(self) -> List[GenerationRequest]:
        """Requests waiting in the engine's OWN FIFO queue (the
        scheduler-less :meth:`submit` path; empty under an attached
        :class:`~paddle_tpu.serving.ServingScheduler`, which owns its
        queues)."""
        return list(self._queue)

    def pending_prefills(self) -> Dict[int, tuple]:
        """``slot -> (request, remaining_tokens)`` for every admission
        whose sequence is not yet fully in the pool — the planner's
        prefill work items."""
        return {s: (ent[0], int(ent[1].size - ent[2]))
                for s, ent in self._pending.items()}

    def generate(self, prompts, max_new_tokens: int = 16) -> List[np.ndarray]:
        """Convenience batch API: submit all, run to completion, return
        each request's prompt+generated row (submission order)."""
        reqs = [self.submit(p, max_new_tokens=max_new_tokens)
                for p in prompts]
        self.run()
        return [r.output for r in reqs]

    def stats(self) -> Dict:
        s = self.cache.allocator.stats()
        s["steps"] = self._steps
        s["queued"] = len(self._queue)
        if self.mesh is not None:
            s["tp"] = self._tp
            if self._dp_axis is not None:
                s["dp"] = self.dp
            s["pool_bytes_per_shard"] = self.cache.pool_bytes_per_shard
        s["active_slots"] = int(self.cache.active.sum())
        s["pending_prefills"] = len(self._pending)
        if self.weight_bits is not None:
            s["weight_bits"] = self.weight_bits
        if self.fused:
            s["fused_kernels"] = True
        s["cow_copies"] = self.cache.cow_copies
        if self.adapters is not None:
            s.update(self.adapters.stats())
        if getattr(self.cache, "host", None) is not None:
            s.update(self.cache.tier_stats())
        if self.cache.prefix is not None:
            s["prefix_evictions_total"] = \
                self.cache.prefix.evictions_total
        if self.spec is not None:
            s.update(self.spec.stats())
        if self.draft_cache is not None:
            s["draft_layers"] = self.draft_layers
            da = self.draft_cache.allocator
            s["draft_pool_pages_used"] = da.num_used
            s["draft_pool_pages_usable"] = da.num_usable
        if self.spec_tree is not None:
            s["tree_width"], s["tree_depth"] = self.spec_tree
            s["tree_nodes"] = self._tree_T - 1
        return s
