"""Predictor implementation (reference: paddle/fluid/inference/api/
analysis_predictor.h AnalysisPredictor; python surface
python/paddle/inference/wrapper.py)."""
from __future__ import annotations

import enum
import os
import time
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..observability import hooks as _obs


class PlaceType(enum.Enum):
    CPU = 0
    GPU = 1
    TPU = 2


class PrecisionType(enum.Enum):
    Float32 = 0
    Half = 1
    Bfloat16 = 2
    Int8 = 3


def get_version() -> str:
    import paddle_tpu
    return paddle_tpu.__version__


class Config:
    """reference: AnalysisConfig (paddle/fluid/inference/api/
    analysis_config.cc). TensorRT/OneDNN toggles are accepted for parity
    and map to XLA (always-on compilation)."""

    def __init__(self, model_path: Optional[str] = None,
                 params_path: Optional[str] = None):
        self._model_path = model_path
        self._params_path = params_path
        self._device = "tpu" if any(
            d.platform == "tpu" for d in jax.devices()) else "cpu"
        self._precision = PrecisionType.Float32
        self._memory_pool_mb = 0
        self._enable_profile = False
        self._optim = True
        self._mesh = None
        self._input_pspec = None
        self._param_spec_fn = None

    # --- multi-chip serving (TPU-native analog of the reference's
    # multi-device inference paths: TRT multi-stream, fleet inference
    # helper) — the compiled program runs SPMD over a device mesh ---
    def enable_mesh(self, mesh, input_spec=None, param_spec_fn=None):
        """Serve over ``mesh``. ``input_spec``: a PartitionSpec (or one
        per input) for the data inputs — default shards dim 0 over the
        mesh's first axis (data-parallel serving). ``param_spec_fn(name,
        array) -> PartitionSpec | None`` places parameters (None =
        replicate); supply Column/Row splits for tensor-parallel serving.
        """
        self._mesh = mesh
        self._input_pspec = input_spec
        self._param_spec_fn = param_spec_fn

    def mesh(self):
        return self._mesh

    # --- model location ---
    def set_model(self, model_path, params_path=None):
        self._model_path = model_path
        self._params_path = params_path

    def model_dir(self):
        return self._model_path

    def prog_file(self):
        return self._model_path

    def params_file(self):
        return self._params_path

    # --- device selection (GPU API parity maps to the TPU chip) ---
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0,
                       precision=PrecisionType.Float32):
        self._memory_pool_mb = memory_pool_init_size_mb
        self._precision = precision

    def disable_gpu(self):
        self._device = "cpu"

    def use_gpu(self):
        return self._device != "cpu"

    def enable_xpu(self, *a, **k):
        pass

    def enable_custom_device(self, device_type, device_id=0):
        self._device = device_type

    # --- optimization toggles ---
    # XLA subsumes the reference's IR/memory/TensorRT/OneDNN pipeline:
    # every toggle is accepted for parity but has no engine to configure.
    # Toggles that a user might rely on semantically (turning optimization
    # OFF, routing to TensorRT) warn ONCE instead of silently no-opping.
    @staticmethod
    def _inert(what, detail):
        import warnings
        warnings.warn(
            f"inference.Config.{what}: accepted for API parity but inert "
            f"on TPU — {detail}", stacklevel=3)

    def switch_ir_optim(self, flag=True):
        if not flag:
            self._inert("switch_ir_optim(False)",
                        "XLA always compiles/optimizes; there is no "
                        "unoptimized executor to fall back to")
        self._optim = flag

    def enable_tensorrt_engine(self, *a, **k):
        self._inert("enable_tensorrt_engine",
                    "the compiled engine is XLA; TensorRT is a GPU "
                    "deployment path")

    def enable_mkldnn(self):
        self._inert("enable_mkldnn", "OneDNN is a CPU kernel library; "
                    "XLA:CPU compiles the fallback path")

    def enable_memory_optim(self, flag=True):
        if flag:
            return  # XLA's buffer assignment already reuses/donates
        self._inert("enable_memory_optim(False)",
                    "XLA buffer reuse cannot be disabled")

    def switch_use_feed_fetch_ops(self, flag):
        pass  # feed/fetch are jit arguments; nothing to switch

    def switch_specify_input_names(self, flag=True):
        pass  # inputs are always named (get_input_names order)

    def enable_profile(self):
        self._enable_profile = True

    def summary(self) -> str:
        return (f"Config(model={self._model_path}, device={self._device}, "
                f"precision={self._precision.name})")


class Tensor:
    """Input/output handle (reference: ZeroCopyTensor,
    paddle/fluid/inference/api/details/zero_copy_tensor.cc)."""

    def __init__(self, name: str, owner: "Predictor"):
        self.name = name
        self._owner = owner
        self._value: Optional[jax.Array] = None

    def reshape(self, shape):
        pass  # shapes come from the bound array

    def copy_from_cpu(self, arr: np.ndarray):
        self._value = jnp.asarray(arr)

    def copy_to_cpu(self) -> np.ndarray:
        return np.asarray(self._value)

    def share_external_data(self, arr):
        self._value = arr if isinstance(arr, jax.Array) else jnp.asarray(arr)

    def shape(self):
        return list(self._value.shape) if self._value is not None else []

    def type(self):
        return self._value.dtype if self._value is not None else None


class Predictor:
    """reference: AnalysisPredictor. Loads a jit.save artifact (a
    TranslatedLayer) or wraps a live Layer/function."""

    def __init__(self, config: Config, layer=None):
        self._config = config
        if layer is None:
            from ..jit.save_load import load as jit_load
            layer = jit_load(config.model_dir())
        self._layer = layer
        self._input_names: List[str] = getattr(
            layer, "input_names", None) or ["x"]
        self._inputs: Dict[str, Tensor] = {
            n: Tensor(n, self) for n in self._input_names}
        self._outputs: Dict[str, Tensor] = {}
        self._jitted = None
        # snapshot the mesh config: enable_mesh must be called BEFORE
        # create_predictor (a later call changing the live Config would
        # otherwise shard inputs but silently skip param placement)
        self._mesh = config._mesh
        self._input_pspec = config._input_pspec
        if self._mesh is not None and hasattr(self._layer, "state_dict"):
            # plain-function layers have no params to place; the input
            # sharding below still applies
            self._place_params(self._mesh, config._param_spec_fn)

    def _place_params(self, mesh, spec_fn):
        """Install mesh placements on the layer's parameters in place
        (replicated unless spec_fn says otherwise)."""
        from jax.sharding import NamedSharding, PartitionSpec as P
        for name, t in self._layer.state_dict().items():
            # state_dict entries are always framework Tensors (Layer
            # wraps buffers; TranslatedLayer._state holds Tensors)
            spec = None
            if spec_fn is not None:
                spec = spec_fn(name, t._value)
            sh = NamedSharding(mesh, spec if spec is not None else P())
            t._value = jax.device_put(t._value, sh)

    def get_input_names(self) -> List[str]:
        return list(self._input_names)

    def get_input_handle(self, name: str) -> Tensor:
        return self._inputs[name]

    def _compiled(self):
        """One compiled XLA program per input-shape set (reference: the
        analysis passes + engine of AnalysisPredictor::Run — here jit
        compile-and-cache does both)."""
        if self._jitted is None:
            import jax
            from .._core.tensor import Tensor as FrameworkTensor
            layer = self._layer

            def f(*raw):
                out = layer(*[FrameworkTensor(r, _internal=True)
                              for r in raw])
                outs = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._value if isinstance(o, FrameworkTensor)
                             else o for o in outs)

            mesh = self._mesh
            if mesh is None:
                self._jitted = jax.jit(f)
            else:
                from jax.sharding import NamedSharding, PartitionSpec as P
                spec = self._input_pspec
                if spec is None:
                    spec = P(mesh.axis_names[0])   # batch over axis 0
                specs = (list(spec) if isinstance(spec, (list, tuple))
                         and not isinstance(spec, P)
                         else [spec] * len(self._input_names))
                shards = tuple(NamedSharding(mesh, s) for s in specs)
                self._jitted = jax.jit(f, in_shardings=shards)
        return self._jitted

    def run(self, inputs: Optional[List[np.ndarray]] = None):
        """reference: AnalysisPredictor::Run / ZeroCopyRun.

        Telemetry (paddle_tpu.observability): per-request latency
        histogram + request/sample counters, plus a ``Predictor.run``
        span when the profiler is recording — zero-cost when neither
        sink is active."""
        if not _obs.active():
            return self._run_impl(inputs)
        t0 = time.perf_counter_ns()
        out = self._run_impl(inputs)
        first = next(iter(self._inputs.values()), None)
        batch = (first._value.shape[0]
                 if first is not None and first._value is not None
                 and getattr(first._value, "ndim", 0) else 0)
        _obs.predictor_run(t0, int(batch))
        return out

    def _run_impl(self, inputs: Optional[List[np.ndarray]] = None):
        from .._core.tensor import Tensor as FrameworkTensor
        if inputs is not None:
            for n, arr in zip(self._input_names, inputs):
                self._inputs[n].copy_from_cpu(np.asarray(arr))
        raw = [self._inputs[n]._value for n in self._input_names]
        out = None
        jit_failed = False
        if self._jitted is not False:
            try:
                out = self._compiled()(*raw)
            except Exception:
                if self._mesh is not None:
                    # the user asked for SPMD serving: a sharding
                    # misconfiguration (uneven batch, wrong spec count)
                    # must surface, not silently degrade to one chip
                    raise
                jit_failed = True
                self._jitted = None  # decide after the eager attempt
        if out is None:
            args = [FrameworkTensor(v, _internal=True) for v in raw]
            # bad inputs re-raise here for the user to fix — that's an
            # input error, not a non-jittable forward
            out = self._layer(*args)
            if jit_failed:
                # eager worked where jit didn't: the forward itself is
                # non-jittable; latch eager so we don't re-trace per run
                self._jitted = False
        outs = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = {}
        results = []
        for i, o in enumerate(outs):
            t = Tensor(f"out_{i}", self)
            val = o._value if isinstance(o, FrameworkTensor) else jnp.asarray(o)
            t.share_external_data(val)
            self._outputs[t.name] = t
            results.append(np.asarray(val))
        if inputs is not None:
            return results
        return True

    def get_output_names(self) -> List[str]:
        return list(self._outputs.keys())

    def get_output_handle(self, name: str) -> Tensor:
        return self._outputs[name]

    def clear_intermediate_tensor(self):
        pass

    def try_shrink_memory(self):
        pass


def create_predictor(config: Config, layer=None) -> Predictor:
    """reference: paddle_infer::CreatePredictor."""
    return Predictor(config, layer=layer)
