"""paddle.inference parity (reference: python/paddle/inference/ — Config,
create_predictor wrapping C++ AnalysisPredictor
paddle/fluid/inference/api/analysis_predictor.cc Run:1738 /
ZeroCopyRun:2771, AnalysisConfig analysis_config.cc).

TPU-native: the "analysis + optimization passes" of the reference are
XLA's job — the predictor loads a jit.save artifact (params + traced
program), jit-compiles it once per input signature (the analog of the
predictor's optimized program cache) and serves zero-copy device arrays.
"""
from .predictor import (  # noqa: F401
    Config, ContinuousBatchingEngine, GenerationRequest, InFlightStep,
    Predictor, Tensor as PredictorTensor, create_predictor,
    PlaceType, PrecisionType, get_version,
)
