"""reference: python/paddle/audio/functional/{window,functional}.py —
get_window, hz<->mel, compute_fbank_matrix, create_dct, power_to_db."""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor


def get_window(window: str, win_length: int, fftbins: bool = True,
               dtype="float32") -> Tensor:
    n = win_length
    sym = not fftbins
    N = n if sym else n + 1
    t = np.arange(N)
    if window in ("hann", "hanning"):
        w = 0.5 - 0.5 * np.cos(2 * np.pi * t / (N - 1))
    elif window == "hamming":
        w = 0.54 - 0.46 * np.cos(2 * np.pi * t / (N - 1))
    elif window == "blackman":
        w = (0.42 - 0.5 * np.cos(2 * np.pi * t / (N - 1))
             + 0.08 * np.cos(4 * np.pi * t / (N - 1)))
    elif window in ("rect", "boxcar", "ones"):
        w = np.ones(N)
    elif window == "bartlett":
        w = 1 - np.abs(2 * t / (N - 1) - 1)
    else:
        raise ValueError(f"unknown window {window}")
    if not sym:
        w = w[:n]
    return Tensor(jnp.asarray(w.astype(np.float32)), _internal=True)


def hz_to_mel(freq, htk: bool = False):
    f = np.asarray(freq, np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:  # slaney
        f_min, f_sp = 0.0, 200.0 / 3
        out = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(f >= min_log_hz,
                       min_log_mel + np.log(np.maximum(f, 1e-10)
                                            / min_log_hz) / logstep, out)
    return float(out) if np.isscalar(freq) else out


def mel_to_hz(mel, htk: bool = False):
    m = np.asarray(mel, np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        out = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)),
                       out)
    return float(out) if np.isscalar(mel) else out


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max=None, htk: bool = False,
                         norm="slaney", dtype="float32") -> Tensor:
    """(n_mels, n_fft//2 + 1) mel filterbank."""
    f_max = f_max or sr / 2
    n_freqs = n_fft // 2 + 1
    freqs = np.linspace(0, sr / 2, n_freqs)
    mel_pts = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                          n_mels + 2)
    hz_pts = mel_to_hz(mel_pts, htk)
    fb = np.zeros((n_mels, n_freqs))
    for i in range(n_mels):
        lo, ctr, hi = hz_pts[i], hz_pts[i + 1], hz_pts[i + 2]
        up = (freqs - lo) / max(ctr - lo, 1e-10)
        down = (hi - freqs) / max(hi - ctr, 1e-10)
        fb[i] = np.maximum(0.0, np.minimum(up, down))
    if norm == "slaney":
        enorm = 2.0 / (hz_pts[2:] - hz_pts[:-2])
        fb *= enorm[:, None]
    return Tensor(jnp.asarray(fb.astype(np.float32)), _internal=True)


def create_dct(n_mfcc: int, n_mels: int, norm="ortho",
               dtype="float32") -> Tensor:
    """(n_mels, n_mfcc) DCT-II matrix."""
    t = np.arange(n_mels)
    dct = np.cos(np.pi / n_mels * (t[:, None] + 0.5)
                 * np.arange(n_mfcc)[None, :])
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(jnp.asarray(dct.astype(np.float32)), _internal=True)


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db=80.0):
    from ..ops._registry import as_tensor
    from .._core.autograd import apply

    def f(v):
        db = 10.0 * jnp.log10(jnp.maximum(v, amin))
        db = db - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
        if top_db is not None:
            db = jnp.maximum(db, jnp.max(db) - top_db)
        return db
    return apply(f, as_tensor(spect), name="power_to_db")
