"""paddle.audio parity (reference: python/paddle/audio/ — features/
(Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC layers),
functional/ (window functions, mel utilities), backends (wave IO))."""
from . import functional  # noqa: F401
from . import features  # noqa: F401
from . import backends  # noqa: F401
from . import datasets  # noqa: F401
from .backends import load, save, info  # noqa: F401
