"""reference: python/paddle/audio/backends/ — wave_backend.py load/save
via the stdlib wave module (no soundfile dependency)."""
from __future__ import annotations

import wave as _wave
from typing import Optional, Tuple

import numpy as np

from .._core.tensor import Tensor


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(name: str):
    if name != "wave_backend":
        raise ValueError("only wave_backend is available")


def info(filepath: str):
    with _wave.open(filepath, "rb") as f:
        class _Info:
            sample_rate = f.getframerate()
            num_frames = f.getnframes()
            num_channels = f.getnchannels()
            bits_per_sample = f.getsampwidth() * 8
        return _Info()


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True
         ) -> Tuple[Tensor, int]:
    """16-bit PCM wav -> float32 in [-1, 1] (reference wave_backend.load)."""
    with _wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        raw = f.readframes(n)
        width = f.getsampwidth()
    if width == 1:  # 8-bit WAV is unsigned with a 128 bias
        data = np.frombuffer(raw, dtype=np.uint8).astype(
            np.int16) - 128
        data = data.reshape(-1, nch)
    else:
        dt = {2: np.int16, 4: np.int32}[width]
        data = np.frombuffer(raw, dtype=dt).reshape(-1, nch)
    if normalize:
        data = data.astype(np.float32) / float(2 ** (8 * width - 1))
    arr = data.T if channels_first else data
    return Tensor(np.ascontiguousarray(arr)), sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         encoding: str = "PCM_16", bits_per_sample: Optional[int] = 16):
    if encoding != "PCM_16" or (bits_per_sample not in (None, 16)):
        raise ValueError(
            f"wave backend writes PCM_16 only, got encoding={encoding!r} "
            f"bits_per_sample={bits_per_sample!r}")
    arr = src.numpy() if isinstance(src, Tensor) else np.asarray(src)
    if channels_first:
        arr = arr.T
    pcm = np.clip(arr, -1.0, 1.0)
    pcm = (pcm * 32767.0).astype(np.int16)
    with _wave.open(filepath, "wb") as f:
        f.setnchannels(pcm.shape[1] if pcm.ndim > 1 else 1)
        f.setsampwidth(2)
        f.setframerate(sample_rate)
        f.writeframes(pcm.tobytes())
