"""reference: python/paddle/audio/features/layers.py — Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC as nn Layers."""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer.layers import Layer
from .._core.autograd import apply
from .. import signal as _signal
from . import functional as F


class Spectrogram(Layer):
    def __init__(self, n_fft: int = 512, hop_length=None, win_length=None,
                 window: str = "hann", power: float = 2.0, center=True,
                 pad_mode="reflect", dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer("window",
                             F.get_window(window, self.win_length))

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, self.hop_length,
                            self.win_length, window=self.window,
                            center=self.center, pad_mode=self.pad_mode)
        return apply(lambda v: jnp.abs(v) ** self.power, spec,
                     name="spec_power")


class MelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window: str = "hann", power: float = 2.0,
                 center=True, pad_mode="reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self.spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                       window, power, center, pad_mode)
        self.register_buffer("fbank", F.compute_fbank_matrix(
            sr, n_fft, n_mels, f_min, f_max, htk, norm))

    def forward(self, x):
        spec = self.spectrogram(x)          # (..., freq, T)
        fb = self.fbank

        def f(s, m):
            return jnp.einsum("mf,...ft->...mt", m, s)
        return apply(f, spec, fb, name="mel")


class LogMelSpectrogram(Layer):
    def __init__(self, sr: int = 22050, n_fft: int = 512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        self.mel = MelSpectrogram(sr, n_fft, hop_length, win_length,
                                  window, power, center, pad_mode, n_mels,
                                  f_min, f_max, htk, norm)
        self._ref, self._amin, self._top_db = ref_value, amin, top_db

    def forward(self, x):
        return F.power_to_db(self.mel(x), self._ref, self._amin,
                             self._top_db)


class MFCC(Layer):
    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length=None, win_length=None, window="hann",
                 power=2.0, center=True, pad_mode="reflect", n_mels=64,
                 f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self.log_mel = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db)
        self.register_buffer("dct", F.create_dct(n_mfcc, n_mels))

    def forward(self, x):
        logmel = self.log_mel(x)            # (..., n_mels, T)
        return apply(lambda v, d: jnp.einsum("mk,...mt->...kt", d, v),
                     logmel, self.dct, name="mfcc")
