"""paddle.audio.datasets (reference: python/paddle/audio/datasets/ —
AudioClassificationDataset base, TESS, ESC50).

Same offline contract as paddle_tpu.text.datasets: pass an on-disk
archive dir; downloads are disabled in this environment.
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from ..io.dataset import Dataset
from . import backends as _backends
from .features import MelSpectrogram


class AudioClassificationDataset(Dataset):
    """reference: audio/datasets/dataset.py — wav files + labels, with
    optional on-the-fly feature extraction (raw | melspectrogram)."""

    def __init__(self, files: List[str], labels: List[int],
                 feat_type: str = "raw", sample_rate: int = 16000,
                 **feat_kwargs):
        if feat_type not in ("raw", "melspectrogram"):
            raise ValueError(f"unsupported feat_type {feat_type!r}")
        if len(files) != len(labels):
            raise ValueError("files and labels must align")
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self._feat = (None if feat_type == "raw" else
                      MelSpectrogram(sr=sample_rate, **feat_kwargs))

    def _load(self, path) -> np.ndarray:
        wav, _sr = _backends.load(path)
        arr = wav.numpy() if hasattr(wav, "numpy") else np.asarray(wav)
        return arr[0] if arr.ndim == 2 else arr

    def __getitem__(self, idx) -> Tuple[np.ndarray, np.int64]:
        sig = self._load(self.files[idx]).astype(np.float32)
        if self._feat is not None:
            from .._core.tensor import Tensor
            sig = self._feat(Tensor(sig[None])).numpy()[0]
        return sig, np.int64(self.labels[idx])

    def __len__(self):
        return len(self.files)


class TESS(AudioClassificationDataset):
    """reference: audio/datasets/tess.py — Toronto emotional speech set:
    7 emotions, 200 target words, 2 actresses; label = emotion index."""

    labels_list = ["angry", "disgust", "fear", "happy", "neutral",
                   "ps", "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5,
                 split: int = 1, feat_type: str = "raw",
                 archive_dir: Optional[str] = None, **kwargs):
        if not 1 <= split <= n_folds:
            raise ValueError(f"split must be in [1, {n_folds}]")
        if archive_dir is None:
            raise FileNotFoundError(
                "TESS: downloads are disabled in this environment; pass "
                "archive_dir=<path to the extracted TESS wav tree>")
        files, labels = [], []
        for root, _dirs, names in sorted(os.walk(archive_dir)):
            for n in sorted(names):
                if not n.lower().endswith(".wav"):
                    continue
                emo = n.split("_")[-1][:-4].lower()
                if emo in self.labels_list:
                    files.append(os.path.join(root, n))
                    labels.append(self.labels_list.index(emo))
        # fold split by index (reference: ranks files into n_folds)
        sel_f, sel_l = [], []
        for i, (f, l) in enumerate(zip(files, labels)):
            fold = i % n_folds + 1
            keep = (fold != split) if mode == "train" else (fold == split)
            if keep:
                sel_f.append(f)
                sel_l.append(l)
        super().__init__(sel_f, sel_l, feat_type=feat_type, **kwargs)


class ESC50(AudioClassificationDataset):
    """reference: audio/datasets/esc50.py — 2000 environmental sounds in
    50 classes, 5 predefined folds encoded in the file names
    (fold-srcfile-take-label.wav)."""

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw",
                 archive_dir: Optional[str] = None, **kwargs):
        if archive_dir is None:
            raise FileNotFoundError(
                "ESC50: downloads are disabled in this environment; pass "
                "archive_dir=<path to the extracted ESC-50 audio dir>")
        files, labels = [], []
        for root, _dirs, names in sorted(os.walk(archive_dir)):
            for n in sorted(names):
                if not n.lower().endswith(".wav"):
                    continue
                parts = n[:-4].split("-")
                if len(parts) != 4:
                    continue
                fold, label = int(parts[0]), int(parts[3])
                keep = (fold != split) if mode == "train" \
                    else (fold == split)
                if keep:
                    files.append(os.path.join(root, n))
                    labels.append(label)
        super().__init__(files, labels, feat_type=feat_type, **kwargs)
