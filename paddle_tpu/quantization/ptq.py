"""PTQ — post-training quantization (reference: python/paddle/
quantization/ptq.py: insert observers, calibrate, convert)."""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .config import QuantConfig
from .qat import QuantedLayer


class PTQ:
    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        """Insert observers — run calibration batches through the model
        afterwards (or use :meth:`calibrate`)."""
        qat_like = __import__(
            "paddle_tpu.quantization.qat", fromlist=["QAT"]).QAT(
            self._config)
        return qat_like.quantize(model, inplace)

    def calibrate(self, model: Layer, data, num_batches: int = None):
        """Drive calibration batches through the observer-instrumented
        model (reference: PostTrainingQuantization's sampling loop over a
        DataLoader). ``data``: any iterable — a DataLoader, a list of
        Tensors, or a list of (inputs...) tuples; only the inputs are
        fed (a trailing label in a 2-tuple is dropped, matching the
        common ``(x, y)`` loader)."""
        from .._core import autograd as ag
        was_training = getattr(model, "training", False)
        model.eval()
        try:
            with ag.no_grad():
                for i, batch in enumerate(data):
                    if num_batches is not None and i >= num_batches:
                        break
                    if isinstance(batch, (tuple, list)):
                        feed = batch[:-1] if len(batch) == 2 else batch
                        model(*feed)
                    else:
                        model(batch)
        finally:
            if was_training:
                model.train()
        return model

    def convert(self, model: Layer, inplace=False) -> Layer:
        """Replace observers with fixed-scale fake-quant using collected
        scales; observer-calibrated weights are baked into the layer.
        ``inplace=False`` (default) converts a deep copy so the
        calibrated model keeps its fp32 weights for recalibration."""
        import copy
        from .quanters import fake_quant
        if not inplace:
            model = copy.deepcopy(model)

        class _Frozen(Layer):
            def __init__(self, inner, scale, bits):
                super().__init__()
                self.inner = inner
                self._scale = scale
                self._bits = bits

            def forward(self, x):
                return self.inner(fake_quant(x, self._scale, self._bits))

        for name, sub in list(model.named_sublayers()):
            if isinstance(sub, QuantedLayer):
                parent = model
                parts = name.split(".")
                for p in parts[:-1]:
                    parent = getattr(parent, p)
                w = getattr(sub.inner, "weight", None)
                if sub.weight_quanter is not None and w is not None and \
                        hasattr(sub.weight_quanter, "fake_quant"):
                    if getattr(sub.weight_quanter, "_max", None) is None:
                        sub.weight_quanter(w)   # never calibrated: one shot
                    w.set_value(sub.weight_quanter.fake_quant(w)._value)
                if sub.activation_quanter is not None and \
                        hasattr(sub.activation_quanter, "scales"):
                    scale = float(sub.activation_quanter.scales()._value)
                    bits = sub.activation_quanter.bit_length()
                    setattr(parent, parts[-1],
                            _Frozen(sub.inner, scale, bits))
                else:
                    setattr(parent, parts[-1], sub.inner)
        return model
