"""PTQ — post-training quantization (reference: python/paddle/
quantization/ptq.py: insert observers, calibrate, convert)."""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .config import QuantConfig
from .qat import QuantedLayer


class PTQ:
    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        """Insert observers — run calibration batches through the model
        afterwards."""
        qat_like = __import__(
            "paddle_tpu.quantization.qat", fromlist=["QAT"]).QAT(
            self._config)
        return qat_like.quantize(model, inplace)

    def convert(self, model: Layer, inplace=False) -> Layer:
        """Replace observers with fixed-scale fake-quant using collected
        scales."""
        from .quanters import fake_quant

        class _Frozen(Layer):
            def __init__(self, inner, scale, bits):
                super().__init__()
                self.inner = inner
                self._scale = scale
                self._bits = bits

            def forward(self, x):
                return self.inner(fake_quant(x, self._scale, self._bits))

        for name, sub in list(model.named_sublayers()):
            if isinstance(sub, QuantedLayer):
                parent = model
                parts = name.split(".")
                for p in parts[:-1]:
                    parent = getattr(parent, p)
                if sub.activation_quanter is not None and \
                        hasattr(sub.activation_quanter, "scales"):
                    scale = float(sub.activation_quanter.scales()._value)
                    bits = sub.activation_quanter.bit_length()
                    setattr(parent, parts[-1],
                            _Frozen(sub.inner, scale, bits))
                else:
                    setattr(parent, parts[-1], sub.inner)
        return model
