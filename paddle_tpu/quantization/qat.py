"""QAT — quantization-aware training (reference: python/paddle/
quantization/qat.py QAT.quantize: wraps target layers with quant stubs)."""
from __future__ import annotations

from ..nn.layer.layers import Layer
from .config import QuantConfig


class QuantedLayer(Layer):
    """Wraps a layer: fake-quant activations in, fake-quant weight."""

    def __init__(self, inner: Layer, act_quanter, weight_quanter):
        super().__init__()
        self.inner = inner
        self.activation_quanter = (act_quanter._instance(inner)
                                   if act_quanter else None)
        self.weight_quanter = (weight_quanter._instance(inner)
                               if weight_quanter else None)

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = getattr(self.inner, "weight", None)
        if self.weight_quanter is not None and w is not None:
            wq = self.weight_quanter
            if hasattr(wq, "fake_quant"):
                # observer-calibrated (channel-wise / group-wise) scales.
                # Training (QAT): the scale must track the CURRENT weight
                # — a running max would keep a stale grid as weight decay
                # shrinks channels. Eval (PTQ calibration): accumulate.
                if self.training:
                    wq._max = None
                wq(w)
                new = wq.fake_quant(w)._value
            else:
                from .quanters import fake_quant
                import jax.numpy as jnp
                scale = float(jnp.max(jnp.abs(w._value))) or 1.0
                new = fake_quant(w, scale)._value
            orig = w._value
            w._value = new
            try:
                return self.inner(x)
            finally:
                w._value = orig
        return self.inner(x)


class QAT:
    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace=False) -> Layer:
        targets = []
        for name, sub in model.named_sublayers():
            a, w = self._config.policy_for(name, sub)
            if a is None and w is None:
                continue
            targets.append((name, sub, a, w))
        for name, sub, a, w in targets:
            parent = model
            parts = name.split(".")
            for p in parts[:-1]:
                parent = getattr(parent, p)
            setattr(parent, parts[-1], QuantedLayer(sub, a, w))
        return model

    def convert(self, model: Layer, inplace=False) -> Layer:
        """Strip quanters for export (scales were learned/observed)."""
        for name, sub in list(model.named_sublayers()):
            if isinstance(sub, QuantedLayer):
                parent = model
                parts = name.split(".")
                for p in parts[:-1]:
                    parent = getattr(parent, p)
                setattr(parent, parts[-1], sub.inner)
        return model
